"""Serving hot-path benchmark: serial ``serve_forever`` baseline vs the
pipelined :class:`~mmlspark_tpu.io.scoring.ScoringEngine` (ISSUE 1
acceptance artifact; reference claim: millisecond-class serving,
SURVEY.md §3.4; adaptive-batching rationale: Clipper, Crankshaw 2017).

Three scenarios, one model, correctness pinned bit-exact against
``Booster.predict_margin`` before any timing:

1. ``closed_native`` — exchange-level closed loop (no HTTP sockets),
   native CPU scorer, 64 outstanding requests: steady-state driver
   saturation.  Measures the decode/score/reply hot path itself.
2. ``open_jit`` — Poisson open loop at ``--rate`` rows/s on the JITTED
   scorer (the accelerator serving path, forced via
   ``Booster.predictor(backend="jit")`` for BOTH drivers).  The serial
   loop re-compiles ``_predict_forest`` for every distinct batch shape
   it drains; the engine's power-of-two buckets compile once each.
   Reports delivered rows/s, p50/p99, and GOODPUT within the
   ``--slo-ms`` latency budget — the serving-throughput number that
   matters operationally (a reply seconds late is a timeout, not a
   served row).
3. ``http_threads`` — end-to-end HTTP closed loop (threads topology),
   keep-alive connections, client load in separate OS processes so the
   server keeps its GIL.  Transport-bound on this box; reported for
   transparency.

ISSUE 11 scenarios (the r11 artifact):

4. ``wire_ab`` — the raw-float32 wire vs the JSON wire, open-loop over
   the REAL exchange: a transport client parks requests straight on a
   ``MultiprocessHTTPServer`` driver (``--wire json`` pins
   ``TransportConfig.offer_binary=False`` so BOTH directions ride the
   JSON fallback; ``--wire binary`` rides FLAG_BINARY frames).  Per-row
   encode+decode time comes from the shared transport codec timers
   (``encode_json``/``decode_json``/``encode_binary``/``decode_binary``
   deltas over the run — every frame both wires send is counted,
   acks included), plus a deterministic per-row codec microbench.
   Gate: binary per-row encode+decode <= 1/2 of JSON's.
5. ``fleet_sweep`` — the sharded predictor fleet
   (:class:`mmlspark_tpu.io.fleet.PredictorFleet`, REAL worker
   processes) at 1/2/4 shards under the same closed-loop load: the
   goodput-vs-fleet-size curve ROADMAP item 2 asks for.  Gate: on a
   multi-core box, best multi-shard goodput >= 1.3x one shard; on a
   single-core lease (where the shards time-slice one core and the
   physical scaling ceiling is 1.0x) the enforceable gate is that the
   sharding TAX stays bounded (worst size >= 0.8x one shard) — the
   artifact records ``cores`` and which gate applied.

Acceptance gates: ``open_jit`` SLO-goodput ratio (engine / serial)
>= 3; ``wire_ab`` encode+decode ratio >= 2; ``fleet_sweep`` per the
core-adaptive rule above.

Run: ``python tools/bench_serving.py --out artifacts/bench_serving_r11.json``
(defaults sized for a few minutes of wall on a 2-core box).
"""

import argparse
import http.client
import json
import os
import queue
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------- load gen

def _client_proc_main(addrs_csv, conns, dur, out_path):
    """Closed-loop keep-alive HTTP clients (run as a separate process)."""
    import numpy as np
    addrs = addrs_csv.split(",")
    rng = np.random.default_rng(os.getpid())
    feats = rng.normal(size=(256, 16)).astype(np.float32)
    payloads = [json.dumps({"features": f.tolist()}).encode()
                for f in feats]
    lat = []
    lock = threading.Lock()

    def client(i):
        host, port = addrs[i % len(addrs)].replace(
            "http://", "").rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=60)
        stop_t = time.perf_counter() + float(dur)
        while time.perf_counter() < stop_t:
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/", payloads[(i * 37) % 256],
                             {"Content-Type": "application/json"})
                conn.getresponse().read()
            except Exception:  # noqa: BLE001 - reconnect and continue
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                conn = http.client.HTTPConnection(host, int(port),
                                                  timeout=60)
                continue
            with lock:
                lat.append(time.perf_counter() - t0)
        try:
            conn.close()
        except Exception:  # noqa: BLE001
            pass

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(int(conns))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with open(out_path, "w") as f:
        json.dump(lat, f)


class LoopServer:
    """Exchange-contract load harness (no sockets): requests go straight
    into ``request_queue``; every reply is latency-stamped and, in
    closed-loop mode, immediately re-arms a new request."""

    def __init__(self, X, closed_outstanding=0):
        import numpy as np
        self.np = np
        self.X = X
        self.request_queue = queue.Queue()
        self.lock = threading.Lock()
        self.count = 0
        self.lat = []
        self.t_sent = {}
        self.outstanding = closed_outstanding
        self.n = 0

    def pump(self):
        for _ in range(self.outstanding):
            self.send()

    def send(self):
        with self.lock:
            rid = str(self.n)
            self.n += 1
            self.t_sent[rid] = time.perf_counter()
        payload = {"features": self.X[self.n % len(self.X)].tolist()}
        self.request_queue.put((rid, payload))

    def get_batch(self, max_rows=64, timeout=0.05):
        batch = []
        try:
            batch.append(self.request_queue.get(timeout=timeout))
            while len(batch) < max_rows:
                batch.append(self.request_queue.get_nowait())
        except queue.Empty:
            pass
        return batch

    def _account(self, rid, now):
        t0 = self.t_sent.pop(rid, None)
        if t0 is not None:
            self.lat.append(now - t0)
        self.count += 1

    def reply(self, rid, val, status=200):
        with self.lock:
            self._account(rid, time.perf_counter())
        if self.outstanding:
            self.send()
        return True

    def reply_many(self, entries):
        now = time.perf_counter()
        with self.lock:
            for rid, _, _ in entries:
                self._account(rid, now)
        if self.outstanding:
            for _ in entries:
                self.send()
        return len(entries)

    def reset(self):
        with self.lock:
            self.count = 0
            self.lat.clear()

    def snapshot(self):
        with self.lock:
            return self.count, list(self.lat)


def _percentiles(lat_s, slo_ms=None):
    import numpy as np
    if not lat_s:
        return {"p50_ms": None, "p99_ms": None}
    a = np.sort(np.asarray(lat_s)) * 1e3
    out = {"p50_ms": round(float(np.percentile(a, 50)), 3),
           "p99_ms": round(float(np.percentile(a, 99)), 3)}
    if slo_ms is not None:
        out[f"within_slo{slo_ms:g}ms"] = int((a <= slo_ms).sum())
    return out


# ---------------------------------------------------------------- drivers

def make_serial_loop(scorer):
    """The historical serial ``serve_forever`` body, verbatim: blocking
    micro-batch pull -> request_table -> transform -> per-row replies."""
    from mmlspark_tpu.io.serving import request_table, reply_from_table

    def transform(t):
        import numpy as np
        preds = scorer(np.asarray(t["features"], np.float32))
        return t.withColumn("pred", np.asarray(preds))

    def loop(srv, stop, max_rows):
        while not stop.is_set():
            batch = srv.get_batch(max_rows=max_rows)
            if not batch:
                continue
            out = transform(request_table(batch))
            reply_from_table(srv, out, "pred")

    return loop


def run_driver(kind, srv, scorer, num_features, max_rows,
               latency_budget_ms, num_scorers=2, num_repliers=1):
    """Start serial loop or ScoringEngine over ``srv``; returns stop().

    Engine thread knobs are per-topology: in-process native scoring
    wants one pipeline worker with inline replies (nothing blocks, the
    GIL serializes anyway); jit scoring and blocking reply paths want
    the multi-worker pipeline."""
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    if kind == "serial":
        stop = threading.Event()
        loop = make_serial_loop(scorer)
        th = threading.Thread(target=loop, args=(srv, stop, max_rows),
                              daemon=True)
        th.start()

        def stopper():
            stop.set()
            th.join(timeout=5)
        return stopper, None
    eng = ScoringEngine(srv, predictor=scorer,
                        plan=ColumnPlan("features", num_features),
                        max_rows=max_rows,
                        latency_budget_ms=latency_budget_ms,
                        num_scorers=num_scorers,
                        num_repliers=num_repliers).start()
    return eng.stop, eng


# ---------------------------------------------------------------- scenarios

def scenario_closed_native(b, X, args):
    """Interleaved serial/engine repeats; best-of per kind (ambient load
    on a shared 2-core box swings single runs by 2x — interleaving plus
    best-of compares the two drivers' actual capacity)."""
    runs = {"serial": [], "engine": []}
    best = {}
    for rep in range(args.reps):
        for kind in ("serial", "engine"):
            srv = LoopServer(X, closed_outstanding=args.outstanding)
            scorer = b.predictor(backend="auto")
            stopper, eng = run_driver(kind, srv, scorer, X.shape[1],
                                      args.max_rows, args.budget_ms,
                                      num_scorers=1, num_repliers=0)
            srv.pump()
            time.sleep(1.0)                  # warm
            srv.reset()
            t0 = time.perf_counter()
            time.sleep(args.duration)
            count, lat = srv.snapshot()
            el = time.perf_counter() - t0
            stats = eng.stats_snapshot() if eng else None
            stopper()
            rps = round(count / el, 1)
            runs[kind].append(rps)
            if kind not in best or rps > best[kind]["rows_per_s"]:
                best[kind] = {"rows_per_s": rps, **_percentiles(lat)}
                if stats:
                    best[kind]["engine_stats"] = stats
    out = {"serial": best["serial"], "engine": best["engine"],
           "runs": runs}
    out["ratio_rows_per_s"] = round(
        best["engine"]["rows_per_s"]
        / max(best["serial"]["rows_per_s"], 1e-9), 3)
    return out


def scenario_open_jit(b, X, args):
    import numpy as np
    out = {}
    for kind in ("serial", "engine"):
        srv = LoopServer(X)                  # open loop: no re-arm
        scorer = b.predictor(backend="jit")  # accelerator serving path
        stopper, eng = run_driver(kind, srv, scorer, X.shape[1],
                                  args.max_rows, args.budget_ms)
        # identical minimal warm: one single-row shape
        srv.send()
        time.sleep(1.5)
        srv.reset()
        t0 = time.perf_counter()
        stop = threading.Event()

        def feeder():
            r = np.random.default_rng(7)     # same arrivals for both
            t_end = time.perf_counter() + args.duration
            nxt = time.perf_counter()
            while time.perf_counter() < t_end and not stop.is_set():
                nxt += r.exponential(1.0 / args.rate)
                dt = nxt - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                srv.send()

        fth = threading.Thread(target=feeder)
        fth.start()
        fth.join()
        time.sleep(args.drain)               # let queued work finish
        count, lat = srv.snapshot()
        # completion-of-offered metric: every counted reply answers a
        # request OFFERED inside the window (the drain accepts late
        # replies but offers nothing new), so count/el is bounded by
        # the offered rate and late replies show up in the percentiles
        # rather than vanishing
        el = time.perf_counter() - t0 - args.drain
        stopper()
        stop.set()
        pct = _percentiles(lat, slo_ms=args.slo_ms)
        goodput = pct.pop(f"within_slo{args.slo_ms:g}ms", 0) / el
        out[kind] = {"offered_rows_per_s": args.rate,
                     "delivered_rows_per_s": round(count / el, 1),
                     f"goodput_slo{args.slo_ms:g}ms_rows_per_s":
                         round(goodput, 1),
                     **pct}
    gkey = f"goodput_slo{args.slo_ms:g}ms_rows_per_s"
    out["ratio_slo_goodput"] = round(
        out["engine"][gkey] / max(out["serial"][gkey], 1e-9), 3)
    out["ratio_p50_latency"] = round(
        (out["serial"]["p50_ms"] or 0)
        / max(out["engine"]["p50_ms"] or 1e-9, 1e-9), 2)
    return out


def scenario_http_threads(b, X, args):
    """End-to-end HTTP closed loop, interleaved repeats, MEDIAN
    reported (single reps swing >2x with ambient load on a shared
    2-core box).  This scenario is transport-bound (HTTP parse + JSON
    in handler threads plus external client processes sharing the
    cores), so it characterizes the full-socket floor rather than the
    driver gap."""
    from mmlspark_tpu.io.serving import DistributedHTTPServer
    runs = {"serial": [], "engine": []}
    per_run = {"serial": [], "engine": []}
    for rep in range(3):
        for kind in ("serial", "engine"):
            srv = DistributedHTTPServer(num_workers=3).start()
            scorer = b.predictor(backend="auto")
            stopper, _ = run_driver(kind, srv, scorer, X.shape[1],
                                    args.max_rows, args.budget_ms)
            t0 = time.perf_counter()
            procs, outs = [], []
            for i in range(args.client_procs):
                path = f"/tmp/bench_serving_lat_{os.getpid()}_{i}.json"
                outs.append(path)
                procs.append(subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--client", ",".join(srv.addresses),
                     str(args.client_conns),
                     str(args.http_duration), path]))
            for p in procs:
                p.wait(timeout=args.http_duration + 60)
            el = time.perf_counter() - t0
            lat = []
            for path in outs:
                with open(path) as f:
                    lat += json.load(f)
                os.unlink(path)
            stopper()
            srv.stop()
            rps = round(len(lat) / el, 1)
            runs[kind].append(rps)
            per_run[kind].append({"rows_per_s": rps, **_percentiles(lat)})
    out = {"runs": runs}
    for kind in ("serial", "engine"):
        med = sorted(per_run[kind],
                     key=lambda r: r["rows_per_s"])[len(per_run[kind]) // 2]
        out[kind] = med
    out["ratio_rows_per_s"] = round(
        out["engine"]["rows_per_s"]
        / max(out["serial"]["rows_per_s"], 1e-9), 3)
    return out


# ------------------------------------------------------- ISSUE 11: wire A/B


class WireLoadGen:
    """Open-loop load over the REAL exchange transport: this client
    hellos into a worker slot of a ``MultiprocessHTTPServer`` driver
    and parks scoring requests directly — raw-float32 blocks on the
    binary wire, ``op=park`` JSON frames on the JSON wire — so the A/B
    measures the exchange hot path without the HTTP edge noise."""

    def __init__(self, srv, X, binary: bool):
        import numpy as np
        from mmlspark_tpu.io import wire
        from mmlspark_tpu.io.transport import (CH_CONTROL, CH_SCORING,
                                               TransportClient,
                                               TransportConfig)
        self._wire = wire
        self._np = np
        self._CH = CH_SCORING
        self.X = X
        self.binary = binary
        self.lock = threading.Lock()
        self.t_sent = {}
        self.lat = []
        self.n = 0

        def on_msg(session, channel, msg, dl):
            now = time.perf_counter()
            if isinstance(msg, (bytes, memoryview)):
                entries = wire.unpack_replies(msg)
                rids = [rid for rid, _v in entries]
                with self.lock:
                    for rid in rids:
                        t0 = self.t_sent.pop(rid, None)
                        if t0 is not None:
                            self.lat.append(now - t0)
                try:
                    self.client.send(CH_SCORING,
                                     {"op": "ack_many", "rids": rids,
                                      "delivered": [True] * len(rids)},
                                     timeout=2.0)
                except OSError:
                    pass
            elif isinstance(msg, dict) and msg.get("op") == "reply":
                rid = msg["rid"]
                with self.lock:
                    t0 = self.t_sent.pop(rid, None)
                    if t0 is not None:
                        self.lat.append(now - t0)
                try:
                    self.client.send(CH_SCORING,
                                     {"op": "ack", "rid": rid,
                                      "delivered": True}, timeout=2.0)
                except OSError:
                    pass

        cfg = TransportConfig(offer_binary=binary,
                              initial_credits=2048, credit_batch=64)
        holder = {}

        def dial():
            h, p = srv._ts.address
            c = TransportClient((h, p), token=srv.token, cfg=cfg,
                                on_message=on_msg, name="wire-loadgen")
            for _ in range(200):
                try:
                    c.connect(retries=0)
                    break
                except OSError:
                    time.sleep(0.05)
            c.send(CH_CONTROL, {"op": "hello", "worker": 0,
                                "host": "127.0.0.1", "port": 1})
            holder["c"] = c

        t = threading.Thread(target=dial, daemon=True)
        t.start()
        srv.start()
        t.join(20)
        self.client = holder.get("c")
        if self.client is None:
            raise RuntimeError(
                "wire load generator could not reach the exchange "
                f"at {srv._ts.address} (dial thread never connected)")
        assert self.client.session.peer_binary == binary, \
            "wire negotiation did not follow --wire"

    def send_one(self):
        with self.lock:
            rid = f"w{self.n}"
            self.n += 1
            self.t_sent[rid] = time.perf_counter()
        row = self.X[self.n % len(self.X)]
        try:
            if self.binary:
                self.client.send_bytes(
                    self._CH,
                    self._wire.pack_matrix(rid, row.reshape(1, -1)))
            else:
                self.client.send(self._CH,
                                 {"op": "park", "rid": rid,
                                  "payload":
                                      {"features": row.tolist()}})
        except OSError:
            with self.lock:
                self.t_sent.pop(rid, None)

    def close(self):
        try:
            self.client.close()
        except OSError:
            pass


def _codec_timer_deltas(before, after):
    """Per-timer (count, total_s) deltas between two transport_stats
    snapshots — the in-situ wire codec cost of one run."""
    out = {}
    for name in ("encode_json", "decode_json", "encode_binary",
                 "decode_binary"):
        b = before.get("stages", {}).get(name, {})
        a = after.get("stages", {}).get(name, {})
        out[name] = {
            "count": a.get("count", 0) - b.get("count", 0),
            "total_s": round(a.get("total_s", 0.0)
                             - b.get("total_s", 0.0), 6)}
    return out


def scenario_wire_ab(b, X, args):
    """The wire-format A/B: identical open-loop arrivals, identical
    model and engine, over the identical exchange — only the payload
    encoding differs.  Reports SLO goodput, latency percentiles, and
    per-delivered-row encode+decode time summed over EVERY frame the
    run sent (parks, replies, acks — the honest end-to-end codec
    bill).

    The payload is ``--wire-features`` wide (default 64 — a realistic
    serving feature vector; the toy 16-column model under-states the
    JSON bill because JSON encode/decode scales with the value count
    while the binary pack is one fixed-cost memcpy), so the scenario
    trains its own small wide model rather than reusing the 16-feature
    one the other scenarios time."""
    import numpy as np
    from mmlspark_tpu.core.telemetry import get_registry
    from mmlspark_tpu.gbdt import LightGBMRegressor
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    from mmlspark_tpu.io.serving import MultiprocessHTTPServer
    from mmlspark_tpu.io.transport import transport_stats

    f = int(args.wire_features)
    if f != X.shape[1]:
        rng = np.random.default_rng(2)
        X = rng.normal(size=(2000, f)).astype(np.float32)
        y = (X[:, 0] + X[:, 1] * X[:, 2]).astype(np.float64)
        t0 = time.time()
        b = LightGBMRegressor(numIterations=30, numLeaves=31,
                              parallelism="serial", verbosity=0).fit(
            {"features": X, "label": y}).getModel()
        print(f"wire model: {len(b.trees)} trees, {f} features "
              f"({time.time() - t0:.1f}s)", flush=True)
    wires = (("json", False), ("binary", True)) \
        if args.wire == "both" else ((args.wire,
                                      args.wire == "binary"),)
    out = {"features": f}
    # ONE scorer, every power-of-two bucket compiled BEFORE either
    # timed run: the jitted walk's compile cache is process-global, so
    # without this the FIRST wire measured would eat every bucket
    # compile and the second would ride the warm cache — an ordering
    # artifact, not a wire difference
    scorer = b.predictor(backend="jit")
    nb = 1
    while nb <= args.max_rows:
        np.asarray(scorer(np.zeros((nb, f), np.float32)))
        nb *= 2
    for name, binary in wires:
        srv = MultiprocessHTTPServer(num_workers=1,
                                     spawn_workers=False,
                                     join_timeout=30.0,
                                     reply_timeout=15.0)
        gen = WireLoadGen(srv, X, binary)
        eng = ScoringEngine(srv, predictor=scorer,
                            plan=ColumnPlan("features", X.shape[1]),
                            max_rows=args.max_rows,
                            latency_budget_ms=args.budget_ms,
                            num_scorers=2, num_repliers=1).start()
        try:
            gen.send_one()                         # warm one shape
            time.sleep(1.5)
            with gen.lock:
                gen.lat.clear()
            before = transport_stats.snapshot()
            t0 = time.perf_counter()
            r = np.random.default_rng(7)           # same arrivals A/B
            t_end = t0 + args.duration
            nxt = t0
            # wire_rate keeps BOTH wires under this box's capacity: an
            # overloaded open loop measures queue collapse, not codec
            # cost (the JSON wire at 64 features cannot even sustain
            # the open_jit rate on one core — that cliff is exactly
            # why the binary wire exists, but the per-row codec A/B
            # needs matched delivered load to be apples-to-apples)
            while time.perf_counter() < t_end:
                nxt += r.exponential(1.0 / args.wire_rate)
                dt = nxt - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                gen.send_one()
            time.sleep(args.drain)
            el = time.perf_counter() - t0 - args.drain
            after = transport_stats.snapshot()
            with gen.lock:
                lat = list(gen.lat)
        finally:
            eng.stop()
            gen.close()
            srv.stop()
        pct = _percentiles(lat, slo_ms=args.slo_ms)
        good = pct.pop(f"within_slo{args.slo_ms:g}ms", 0) / el
        codec = _codec_timer_deltas(before, after)
        codec_s = sum(v["total_s"] for v in codec.values())
        rows = max(len(lat), 1)
        out[name] = {
            "offered_rows_per_s": args.wire_rate,
            "delivered_rows_per_s": round(len(lat) / el, 1),
            f"goodput_slo{args.slo_ms:g}ms_rows_per_s": round(good, 1),
            **pct,
            "codec_timers": codec,
            "encode_decode_us_per_row": round(codec_s / rows * 1e6, 3),
        }
        # keep the registry's scoring ns pointing at a live engine for
        # the artifact's telemetry block
        get_registry()
    if "json" in out and "binary" in out:
        j = out["json"]["encode_decode_us_per_row"]
        bn = out["binary"]["encode_decode_us_per_row"]
        out["ratio_encode_decode"] = round(j / max(bn, 1e-9), 2)
        gkey = f"goodput_slo{args.slo_ms:g}ms_rows_per_s"
        out["ratio_slo_goodput"] = round(
            out["binary"][gkey] / max(out["json"][gkey], 1e-9), 3)
        out["ratio_p50_latency"] = round(
            (out["json"]["p50_ms"] or 0)
            / max(out["binary"]["p50_ms"] or 1e-9, 1e-9), 2)
    return out


def codec_microbench(X, reps=20000, features=None):
    """Deterministic per-row codec A/B: JSON encode+decode vs
    pack_matrix+unpack_matrix on identical single-row payloads —
    supporting data for the in-situ numbers (no scheduler noise)."""
    import numpy as np
    from mmlspark_tpu.io import wire
    row = X[0]
    if features and features != len(row):
        row = np.random.default_rng(3).normal(
            size=features).astype(np.float32)
    payload = {"features": row.tolist()}
    t0 = time.perf_counter()
    for _ in range(reps):
        json.loads(json.dumps({"op": "park", "rid": "r",
                               "payload": payload}))
    json_us = (time.perf_counter() - t0) / reps * 1e6
    r2 = row.reshape(1, -1)
    t0 = time.perf_counter()
    for _ in range(reps):
        wire.unpack_matrix(wire.pack_matrix("r", r2))
    bin_us = (time.perf_counter() - t0) / reps * 1e6
    return {"json_us_per_row": round(json_us, 3),
            "binary_us_per_row": round(bin_us, 3),
            "ratio": round(json_us / max(bin_us, 1e-9), 2)}


# ---------------------------------------------- ISSUE 20: saturation ramp


class RampServer(LoopServer):
    """Open-loop harness whose requests are enqueue-stamped 3-tuples
    (the exchange contract for stamped requests), so the engine's
    ``queue_age`` saturation tap and the per-request deadline both see
    TRUE queue age — the signal the knee estimator regresses on.
    Payloads are pre-built once: at 100k sends/s a per-send
    ``.tolist()`` would starve the scorer it shares the core with and
    deepen congestion collapse artificially."""

    def __init__(self, X, closed_outstanding=0):
        super().__init__(X, closed_outstanding=closed_outstanding)
        self._payloads = [{"features": row.tolist()} for row in X]

    def send(self):
        with self.lock:
            rid = str(self.n)
            self.n += 1
            t = time.perf_counter()
            self.t_sent[rid] = t
        self.request_queue.put(
            (rid, self._payloads[self.n % len(self._payloads)], t))


def scenario_saturation_ramp(b, X, args):
    """Ramped open-loop sweep past the capacity knee (ISSUE 20): a
    closed-loop probe measures this box's service capacity, then an
    open loop steps the offered rate through fractions of it (default
    0.3x .. 1.6x, well past saturation) while the live
    ``CapacityMonitor`` windows (load, latency) into its knee
    estimator and the SLO monitor burns the ``scoring_headroom``
    (gauge) and ``scoring_goodput`` (shed+expired ratio) objectives.

    Gates: the ONLINE knee estimate lands within 25% of the MEASURED
    goodput knee (best within-SLO delivery over the sweep), and the
    headroom objective breaches BEFORE the goodput objective does —
    "approaching saturation" has to page first or the surface is
    useless to an autoscaler."""
    import numpy as np
    from mmlspark_tpu.core import capacity as cap
    from mmlspark_tpu.core.slo import SLOMonitor, get_monitor, set_monitor
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine

    cap.configure(enabled=True)
    scorer = b.predictor(backend="auto")

    # -- closed-loop capacity probe: what can this box actually serve
    srv = LoopServer(X, closed_outstanding=args.outstanding)
    stopper, _eng = run_driver("engine", srv, scorer, X.shape[1],
                               args.max_rows, args.budget_ms,
                               num_scorers=1, num_repliers=0)
    srv.pump()
    time.sleep(1.0)
    srv.reset()
    t0 = time.perf_counter()
    time.sleep(args.ramp_probe_s)
    count, _lat = srv.snapshot()
    cap_rps = count / (time.perf_counter() - t0)
    stopper()
    print(f"  capacity probe: {cap_rps:.0f} rows/s closed-loop",
          flush=True)

    # -- ramp engine: per-request deadline makes overload EXPIRE rows
    # (the goodput objective's bad counter) instead of queueing forever
    srv = RampServer(X)
    eng = ScoringEngine(srv, predictor=scorer,
                        plan=ColumnPlan("features", X.shape[1]),
                        max_rows=args.max_rows,
                        latency_budget_ms=args.budget_ms,
                        num_scorers=1, num_repliers=0,
                        deadline_ms=args.ramp_deadline_ms).start()
    # fresh monitors AFTER engine start (ns="scoring" re-registered):
    # bench-scaled windows — 1 Hz production sampling is too coarse
    # for 6 s ramp steps
    # stricter knee gates than the production defaults: on a 1-core box
    # p50 grows roughly linearly with load even well BELOW the knee
    # (scheduler contention), so rise_factor=1.3 would bless a hinge on
    # healthy data — demand the ~order-of-magnitude queueing blowup
    # before calling it a knee
    mon = cap.set_capacity_monitor(cap.CapacityMonitor(
        window_s=args.ramp_window_s, min_dt_s=0.4,
        onset_ticks=2, clear_ticks=4,
        resources=(cap.ResourceSpec("scoring", "scoring",
                                    ("queue_age", "e2e")),),
        estimators={"scoring": cap.KneeEstimator(
            min_points=12, min_load_span=2.0, rise_factor=6.0,
            band=0.25, confirm=2)}))
    mon.start(interval_s=0.5)
    prev_slo = get_monitor()
    slo_mon = set_monitor(SLOMonitor(fast_window_s=2.0,
                                     slow_window_s=6.0))
    slo_mon.start(tick_s=0.25)

    factors = [float(f) for f in args.ramp_factors.split(",")]
    steps = []
    first_breach = {}
    gkey = f"goodput_slo{args.slo_ms:g}ms_rows_per_s"
    try:
        srv.send()                                   # warm one shape
        time.sleep(1.0)
        ramp_t0 = time.perf_counter()
        for factor in factors:
            rate = max(1.0, factor * cap_rps)
            srv.reset()
            step_t0 = time.perf_counter()
            t_end = step_t0 + args.ramp_step_s
            sent, last_poll = 0, 0.0
            while True:
                now = time.perf_counter()
                if now >= t_end:
                    break
                # burst-paced open loop: send everything due so the
                # offered rate holds even when one Python loop
                # iteration costs more than 1/rate
                due = int((now - step_t0) * rate) - sent
                for _ in range(min(max(due, 0), 1024)):
                    srv.send()
                sent += min(max(due, 0), 1024)
                if now - last_poll >= 0.2:
                    last_poll = now
                    rep = slo_mon.report()
                    for name in ("scoring_headroom",
                                 "scoring_goodput"):
                        if name not in first_breach and name in (
                                rep.get("breaching") or []):
                            first_breach[name] = round(
                                now - ramp_t0, 3)
                            print(f"  BREACH {name} at "
                                  f"t={first_breach[name]}s "
                                  f"(offered {factor:.2f}x)",
                                  flush=True)
                time.sleep(0.002)
            el = time.perf_counter() - step_t0
            count, lat = srv.snapshot()
            pct = _percentiles(lat, slo_ms=args.slo_ms)
            good = pct.pop(f"within_slo{args.slo_ms:g}ms", 0) / el
            g = mon.snapshot().get("gauges") or {}
            steps.append({
                "offered_factor": factor,
                "offered_rows_per_s": round(rate, 1),
                "delivered_rows_per_s": round(count / el, 1),
                gkey: round(good, 1),
                **pct,
                "headroom": g.get("headroom_scoring", 0.0),
                "knee_estimate": g.get("knee_scoring", 0.0),
            })
            print(f"  ramp {factor:.2f}x: "
                  f"{json.dumps(steps[-1])}", flush=True)
        time.sleep(args.drain)
        est_knee = mon.estimator("scoring").knee
        cap_snap = mon.snapshot()
        if os.environ.get("RAMP_DEBUG"):
            e = mon.estimator("scoring")
            print("  DEBUG pts:", [(round(l), round(y, 2))
                                   for l, y in e._pts], flush=True)
            print("  DEBUG raw:", e.raw_estimate(),
                  "published:", e.knee, flush=True)
    finally:
        eng.stop()
        mon.stop()
        slo_mon.stop()
        set_monitor(prev_slo)
    measured_knee = max(s[gkey] for s in steps)
    rel_err = (abs((est_knee or 0.0) - measured_knee)
               / max(measured_knee, 1e-9))
    onsets = int((cap_snap.get("counters") or {})
                 .get("saturation_onsets", 0))
    hb, gb = (first_breach.get("scoring_headroom"),
              first_breach.get("scoring_goodput"))
    out = {
        "closed_loop_capacity_rows_per_s": round(cap_rps, 1),
        "deadline_ms": args.ramp_deadline_ms,
        "steps": steps,
        "measured_knee_rows_per_s": round(measured_knee, 1),
        "estimated_knee_rows_per_s": (round(est_knee, 1)
                                      if est_knee else None),
        "knee_rel_err": round(rel_err, 4),
        "accept_knee_within_25pct": (est_knee is not None
                                     and rel_err <= 0.25),
        "first_breach_s": first_breach,
        "accept_headroom_breach_before_goodput": (
            hb is not None and (gb is None or hb < gb)),
        "saturation_onsets": onsets,
        "accept_saturation_onset_journaled": onsets >= 1,
    }
    return out


# --------------------------------------------------- ISSUE 11: fleet sweep


def scenario_fleet_sweep(args):
    """Goodput vs fleet size: the SAME closed-loop load (outstanding
    requests re-arm on reply, so the pipeline stays saturated and the
    measurement is CAPACITY, not offered-rate tracking) scored by a
    PredictorFleet of 1/2/4 REAL worker processes (tree-range shards,
    partial-sum reduce over resumable sessions).  A heavier forest
    (``--fleet-trees``) makes the tree walk the dominant cost so the
    curve measures sharding, not fixed overhead."""
    import numpy as np
    from mmlspark_tpu.gbdt import LightGBMRegressor
    from mmlspark_tpu.io.fleet import PredictorFleet, ShardedPredictor
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 16)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + np.sin(X[:, 3])).astype(
        np.float64)
    t0 = time.time()
    fb = LightGBMRegressor(numIterations=args.fleet_trees, numLeaves=31,
                           parallelism="serial", verbosity=0).fit(
        {"features": X, "label": y}).getModel()
    print(f"fleet model: {len(fb.trees)} trees "
          f"({time.time() - t0:.1f}s)", flush=True)
    # parity pinned before any timing: fleet reduce == local reduce
    ref = np.asarray(ShardedPredictor(fb, num_shards=2)(X[:64]))
    out = {"model": {"trees": len(fb.trees), "num_leaves": 31},
           "sizes": {}}
    gkey = f"goodput_slo{args.slo_ms:g}ms_rows_per_s"
    for shards in (1, 2, 4):
        fleet = PredictorFleet(fb, num_shards=shards, spawn=True,
                               join_timeout=120.0,
                               request_timeout_s=30.0).start()
        try:
            if shards == 2:
                got = fleet(X[:64])
                bit_exact = bool(np.array_equal(got, ref))
                out["parity_fleet2_vs_single_host_bit_exact"] = \
                    bit_exact
            srv = LoopServer(X,
                             closed_outstanding=args.fleet_outstanding)
            eng = ScoringEngine(srv, predictor=fleet,
                                plan=ColumnPlan("features",
                                                X.shape[1]),
                                max_rows=args.max_rows,
                                latency_budget_ms=args.budget_ms,
                                num_scorers=2, num_repliers=1).start()
            srv.pump()
            time.sleep(1.5)                        # warm
            srv.reset()
            t0 = time.perf_counter()
            time.sleep(args.duration)
            count, lat = srv.snapshot()
            el = time.perf_counter() - t0
            eng.stop()
        finally:
            fleet.stop()
        pct = _percentiles(lat, slo_ms=args.slo_ms)
        good = pct.pop(f"within_slo{args.slo_ms:g}ms", 0) / el
        out["sizes"][str(shards)] = {
            "outstanding": args.fleet_outstanding,
            "delivered_rows_per_s": round(count / el, 1),
            gkey: round(good, 1),
            **pct,
            "shard_ranges": [list(rg) for rg in fleet.ranges],
        }
        print(f"  fleet={shards}: "
              f"{json.dumps(out['sizes'][str(shards)])}", flush=True)
    curve = [(s, out["sizes"][s][gkey]) for s in ("1", "2", "4")]
    out["goodput_curve"] = curve
    base = max(out["sizes"]["1"][gkey], 1e-9)
    out["best_scaling_vs_1_shard"] = round(
        max(v for _s, v in curve) / base, 3)
    # honesty block: fleet-size scaling is a MULTI-CORE/MULTI-HOST
    # property — on a CPU-starved CI box (this lease: see `cores`) the
    # shards time-slice one core and the physical ceiling is 1.0x, so
    # the gate this box can actually enforce is that the sharding TAX
    # (pack + fan-out + partial-sum reduce) stays bounded while the
    # topology gains horizontal scale-out.  On >=2 cores the same
    # sweep's curve is the scaling evidence and `best_scaling` is the
    # gate.
    # both readings recorded (ISSUE 12 satellite): `cores` is the
    # EFFECTIVE count (sched_getaffinity — cgroup/affinity caps seen),
    # which is what the gate keys off; cpu_count is the advertised one
    host = host_block()
    out["cores"] = host["cores_effective"]
    out["cpu_count"] = host["cpu_count"]
    out["scaling_physically_possible"] = out["cores"] >= 2
    out["fleet_tax_vs_1_shard"] = round(
        min(v for _s, v in curve) / base, 3)
    return out


# ---------------------------------------------------------------- main

def telemetry_block(journal_tail=40):
    """The artifact's telemetry section (ISSUE 5): the exact Prometheus
    exposition a ``/metrics`` scrape of this process would return
    (the last engine's stage latencies and resilience counters are
    registered under ``ns="scoring"``) plus a journal excerpt — so a
    perf regression review can read the claimed numbers straight from
    telemetry instead of ad-hoc prints — plus the continuous
    profiler's snapshot (ISSUE 12: the phase/compile/dispatch
    attribution ``tools/perf_report.py`` consumes).  Schema is pinned
    by tests/test_telemetry.py."""
    from mmlspark_tpu.core.profiler import get_profiler
    from mmlspark_tpu.core.telemetry import get_journal, get_registry
    return {
        "metrics_exposition": get_registry().render_prometheus(),
        "journal_excerpt": get_journal().tail(journal_tail),
        "profile": get_profiler().snapshot(),
    }


def host_block():
    """Core detection for the artifact (ISSUE 12 satellite):
    ``cores_effective`` is what this process may actually RUN on
    (cgroup/affinity caps included — the truth the fleet-scaling gate
    must key off), ``cpu_count`` is what the box advertises.  On the
    r11 1-core lease these differed exactly the way that matters.
    Single definition in core.telemetry — the sentinel reads the
    same one."""
    from mmlspark_tpu.core.telemetry import host_info
    return host_info()


def check_correctness(b, X):
    """Bit-exact margins across every scored path, pinned BEFORE timing."""
    import numpy as np
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    want = np.asarray(b.predict_margin(X[:64])).astype(np.float32)
    ok = {}
    try:
        ok["native"] = bool(np.array_equal(
            np.asarray(b.predictor(backend="native")(X[:64])), want))
    except RuntimeError:
        # no native kernel on this host: record that honestly instead
        # of silently re-testing the jit path under a "native" label
        ok["native"] = "unavailable"
    ok["jit"] = bool(np.array_equal(
        np.asarray(b.predictor(backend="jit")(X[:64])).astype(np.float32),
        want))
    eng = ScoringEngine(LoopServer(X), predictor=b.predictor(),
                        plan=ColumnPlan("features", X.shape[1]))
    batch = [(str(i), {"features": X[i].tolist()}) for i in range(64)]
    pairs = eng._score_predictor(batch)
    ok["engine_padded"] = bool(np.array_equal(
        np.asarray([v for _, v in pairs], np.float32), want))
    return ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="artifact JSON path")
    ap.add_argument("--duration", type=float, default=12.0)
    ap.add_argument("--http-duration", type=float, default=10.0)
    ap.add_argument("--drain", type=float, default=1.0)
    ap.add_argument("--rate", type=float, default=2000.0,
                    help="open-loop offered rows/s")
    ap.add_argument("--slo-ms", type=float, default=50.0)
    ap.add_argument("--outstanding", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repeats for closed_native")
    ap.add_argument("--max-rows", type=int, default=256)
    ap.add_argument("--budget-ms", type=float, default=5.0)
    ap.add_argument("--client-procs", type=int, default=2)
    ap.add_argument("--client-conns", type=int, default=8)
    ap.add_argument("--trees", type=int, default=60)
    ap.add_argument("--skip-http", action="store_true")
    ap.add_argument("--wire", choices=("json", "binary", "both"),
                    default="both",
                    help="wire-format A/B over the real exchange")
    ap.add_argument("--wire-rate", type=float, default=800.0,
                    help="open-loop offered rows/s for the wire A/B "
                         "(kept under single-core capacity so the A/B "
                         "measures codec cost, not overload collapse)")
    ap.add_argument("--wire-features", type=int, default=64,
                    help="payload width for the wire A/B (JSON cost "
                         "scales with it; binary is one memcpy)")
    ap.add_argument("--skip-wire", action="store_true")
    ap.add_argument("--skip-fleet", action="store_true")
    ap.add_argument("--fleet-trees", type=int, default=300,
                    help="forest size for the fleet sweep (heavy "
                         "enough that the tree walk dominates)")
    ap.add_argument("--fleet-outstanding", type=int, default=512,
                    help="closed-loop outstanding requests for the "
                         "fleet sweep (keeps the pipeline saturated)")
    ap.add_argument("--scenario", default="all",
                    choices=("all", "closed_native", "open_jit",
                             "http_threads", "wire_ab", "fleet_sweep",
                             "saturation_ramp"),
                    help="run one scenario instead of the full suite "
                         "(skip flags still apply under 'all')")
    ap.add_argument("--ramp-factors",
                    default="0.3,0.5,0.7,0.85,1.0,1.15,1.3,1.6",
                    help="offered-rate fractions of the measured "
                         "closed-loop capacity, swept in order past "
                         "the knee")
    ap.add_argument("--ramp-step-s", type=float, default=6.0,
                    help="seconds per ramp step")
    ap.add_argument("--ramp-probe-s", type=float, default=2.5,
                    help="closed-loop capacity probe duration")
    ap.add_argument("--ramp-window-s", type=float, default=2.0,
                    help="capacity monitor window during the ramp")
    ap.add_argument("--ramp-deadline-ms", type=float, default=600.0,
                    help="per-request deadline during the ramp "
                         "(overload expires rows -> goodput burn; "
                         "generous so queue-age growth pages headroom "
                         "before expiry burns goodput)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    from mmlspark_tpu.gbdt import LightGBMRegressor

    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 16)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + np.sin(X[:, 3])).astype(np.float64)
    t0 = time.time()
    b = LightGBMRegressor(numIterations=args.trees, numLeaves=31,
                          parallelism="serial", verbosity=0).fit(
        {"features": X, "label": y}).getModel()
    print(f"model: {len(b.trees)} trees ({time.time() - t0:.1f}s)",
          flush=True)

    correctness = check_correctness(b, X)
    print("correctness:", correctness, flush=True)

    # SLO burn-rate monitor (ISSUE 8): sample the registry through the
    # whole bench so the artifact carries "was the error budget being
    # burned" next to the raw goodput numbers.  Windows are scaled to
    # the bench duration (the production defaults are 60 s / 300 s).
    from mmlspark_tpu.core.slo import SLOMonitor, set_monitor
    slo_monitor = set_monitor(SLOMonitor(
        fast_window_s=max(2.0, args.duration / 4),
        slow_window_s=max(8.0, args.duration)))
    slo_monitor.start(tick_s=0.5)

    detail = {"correctness_bit_exact": correctness,
              "model": {"trees": len(b.trees), "num_leaves": 31,
                        "features": int(X.shape[1])},
              "config": {"max_rows": args.max_rows,
                         "latency_budget_ms": args.budget_ms,
                         "engine_threads": {
                             "closed_native": "1 worker, inline replies",
                             "open_jit": "2 workers, 1 replier",
                             "http_threads": "2 workers, 1 replier"},
                         "open_loop_rate": args.rate,
                         "slo_ms": args.slo_ms}}

    def want(name):
        return args.scenario in ("all", name)

    if want("closed_native"):
        print("== closed_native ==", flush=True)
        detail["closed_native"] = scenario_closed_native(b, X, args)
        print(json.dumps(detail["closed_native"], default=str)[:400],
              flush=True)
    if want("open_jit"):
        print("== open_jit ==", flush=True)
        detail["open_jit"] = scenario_open_jit(b, X, args)
        print(json.dumps(detail["open_jit"]), flush=True)
    if want("http_threads") and not args.skip_http:
        print("== http_threads ==", flush=True)
        detail["http_threads"] = scenario_http_threads(b, X, args)
        print(json.dumps(detail["http_threads"]), flush=True)
    if want("wire_ab") and not args.skip_wire:
        print("== wire_ab ==", flush=True)
        detail["codec_micro"] = codec_microbench(
            X, features=args.wire_features)
        print("codec_micro:", json.dumps(detail["codec_micro"]),
              flush=True)
        detail["wire_ab"] = scenario_wire_ab(b, X, args)
        print(json.dumps({k: v for k, v in detail["wire_ab"].items()
                          if not isinstance(v, dict)
                          or "codec_timers" not in v},
                         default=str)[:600], flush=True)
    if want("fleet_sweep") and not args.skip_fleet:
        print("== fleet_sweep ==", flush=True)
        detail["fleet_sweep"] = scenario_fleet_sweep(args)
    if want("saturation_ramp"):
        print("== saturation_ramp ==", flush=True)
        detail["saturation_ramp"] = scenario_saturation_ramp(b, X, args)

    slo_monitor.stop()
    slo_report = slo_monitor.report()
    print("slo:", json.dumps({"healthy": slo_report["healthy"],
                              "breaching": slo_report["breaching"]}),
          flush=True)

    gkey = f"goodput_slo{args.slo_ms:g}ms_rows_per_s"
    result = {
        "host": host_block(),
        "telemetry": telemetry_block(),
        # burn-rate verdict over the whole bench: pass/fail context for
        # the goodput number (a bench that "won" while torching its
        # error budget did not win)
        "slo": slo_report,
        "detail": detail,
    }
    if "open_jit" in detail:
        result.update({
            "metric": "serving_slo_goodput_rows_per_sec",
            "value": detail["open_jit"]["engine"][gkey],
            "unit": "rows/s",
            "vs_baseline": detail["open_jit"]["ratio_slo_goodput"],
            "accept_ratio_ge_3":
                detail["open_jit"]["ratio_slo_goodput"] >= 3.0,
        })
    else:
        # single-scenario run: the headline metric comes from whatever
        # actually ran
        sr = detail.get("saturation_ramp")
        if sr:
            result.update({
                "metric": "serving_capacity_knee_rows_per_s",
                "value": sr["estimated_knee_rows_per_s"],
                "unit": "rows/s"})
    # ISSUE 20 acceptance gates: online knee estimate within 25% of
    # the measured goodput knee, headroom pages before goodput burns
    if "saturation_ramp" in detail:
        sr = detail["saturation_ramp"]
        result["capacity_knee_measured_rows_per_s"] = \
            sr["measured_knee_rows_per_s"]
        result["capacity_knee_estimated_rows_per_s"] = \
            sr["estimated_knee_rows_per_s"]
        result["accept_knee_within_25pct"] = \
            sr["accept_knee_within_25pct"]
        result["accept_headroom_breach_before_goodput"] = \
            sr["accept_headroom_breach_before_goodput"]
    # ISSUE 11 acceptance gates: binary wire halves the per-row
    # encode+decode bill, and SLO goodput scales with fleet size
    if "wire_ab" in detail and "ratio_encode_decode" in detail["wire_ab"]:
        result["wire_encode_decode_ratio"] = \
            detail["wire_ab"]["ratio_encode_decode"]
        result["accept_wire_codec_ge_2x"] = \
            detail["wire_ab"]["ratio_encode_decode"] >= 2.0
    if "fleet_sweep" in detail:
        fs = detail["fleet_sweep"]
        result["fleet_goodput_curve"] = fs["goodput_curve"]
        result["fleet_best_scaling_vs_1_shard"] = \
            fs["best_scaling_vs_1_shard"]
        result["fleet_cores"] = fs["cores"]
        # the gate adapts to what the box can physically show (see
        # scenario_fleet_sweep's honesty block): scaling on >=2 cores,
        # bounded sharding tax on a 1-core lease
        if fs["scaling_physically_possible"]:
            result["accept_fleet_scaling"] = \
                fs["best_scaling_vs_1_shard"] >= 1.3
        else:
            result["accept_fleet_scaling"] = \
                fs["fleet_tax_vs_1_shard"] >= 0.8
    print(json.dumps({k: v for k, v in result.items() if k != "detail"}),
          flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"artifact -> {args.out}", flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--client":
        _client_proc_main(*sys.argv[2:6])
    else:
        main()
