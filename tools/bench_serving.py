"""Serving hot-path benchmark: serial ``serve_forever`` baseline vs the
pipelined :class:`~mmlspark_tpu.io.scoring.ScoringEngine` (ISSUE 1
acceptance artifact; reference claim: millisecond-class serving,
SURVEY.md §3.4; adaptive-batching rationale: Clipper, Crankshaw 2017).

Three scenarios, one model, correctness pinned bit-exact against
``Booster.predict_margin`` before any timing:

1. ``closed_native`` — exchange-level closed loop (no HTTP sockets),
   native CPU scorer, 64 outstanding requests: steady-state driver
   saturation.  Measures the decode/score/reply hot path itself.
2. ``open_jit`` — Poisson open loop at ``--rate`` rows/s on the JITTED
   scorer (the accelerator serving path, forced via
   ``Booster.predictor(backend="jit")`` for BOTH drivers).  The serial
   loop re-compiles ``_predict_forest`` for every distinct batch shape
   it drains; the engine's power-of-two buckets compile once each.
   Reports delivered rows/s, p50/p99, and GOODPUT within the
   ``--slo-ms`` latency budget — the serving-throughput number that
   matters operationally (a reply seconds late is a timeout, not a
   served row).
3. ``http_threads`` — end-to-end HTTP closed loop (threads topology),
   keep-alive connections, client load in separate OS processes so the
   server keeps its GIL.  Transport-bound on this box; reported for
   transparency.

Acceptance gate: ``open_jit`` SLO-goodput ratio (engine / serial) >= 3.

Run: ``python tools/bench_serving.py --out artifacts/bench_serving_r01.json``
(defaults sized for a ~3 minute wall on a 2-core box).
"""

import argparse
import http.client
import json
import os
import queue
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------- load gen

def _client_proc_main(addrs_csv, conns, dur, out_path):
    """Closed-loop keep-alive HTTP clients (run as a separate process)."""
    import numpy as np
    addrs = addrs_csv.split(",")
    rng = np.random.default_rng(os.getpid())
    feats = rng.normal(size=(256, 16)).astype(np.float32)
    payloads = [json.dumps({"features": f.tolist()}).encode()
                for f in feats]
    lat = []
    lock = threading.Lock()

    def client(i):
        host, port = addrs[i % len(addrs)].replace(
            "http://", "").rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=60)
        stop_t = time.perf_counter() + float(dur)
        while time.perf_counter() < stop_t:
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/", payloads[(i * 37) % 256],
                             {"Content-Type": "application/json"})
                conn.getresponse().read()
            except Exception:  # noqa: BLE001 - reconnect and continue
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                conn = http.client.HTTPConnection(host, int(port),
                                                  timeout=60)
                continue
            with lock:
                lat.append(time.perf_counter() - t0)
        try:
            conn.close()
        except Exception:  # noqa: BLE001
            pass

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(int(conns))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with open(out_path, "w") as f:
        json.dump(lat, f)


class LoopServer:
    """Exchange-contract load harness (no sockets): requests go straight
    into ``request_queue``; every reply is latency-stamped and, in
    closed-loop mode, immediately re-arms a new request."""

    def __init__(self, X, closed_outstanding=0):
        import numpy as np
        self.np = np
        self.X = X
        self.request_queue = queue.Queue()
        self.lock = threading.Lock()
        self.count = 0
        self.lat = []
        self.t_sent = {}
        self.outstanding = closed_outstanding
        self.n = 0

    def pump(self):
        for _ in range(self.outstanding):
            self.send()

    def send(self):
        with self.lock:
            rid = str(self.n)
            self.n += 1
            self.t_sent[rid] = time.perf_counter()
        payload = {"features": self.X[self.n % len(self.X)].tolist()}
        self.request_queue.put((rid, payload))

    def get_batch(self, max_rows=64, timeout=0.05):
        batch = []
        try:
            batch.append(self.request_queue.get(timeout=timeout))
            while len(batch) < max_rows:
                batch.append(self.request_queue.get_nowait())
        except queue.Empty:
            pass
        return batch

    def _account(self, rid, now):
        t0 = self.t_sent.pop(rid, None)
        if t0 is not None:
            self.lat.append(now - t0)
        self.count += 1

    def reply(self, rid, val, status=200):
        with self.lock:
            self._account(rid, time.perf_counter())
        if self.outstanding:
            self.send()
        return True

    def reply_many(self, entries):
        now = time.perf_counter()
        with self.lock:
            for rid, _, _ in entries:
                self._account(rid, now)
        if self.outstanding:
            for _ in entries:
                self.send()
        return len(entries)

    def reset(self):
        with self.lock:
            self.count = 0
            self.lat.clear()

    def snapshot(self):
        with self.lock:
            return self.count, list(self.lat)


def _percentiles(lat_s, slo_ms=None):
    import numpy as np
    if not lat_s:
        return {"p50_ms": None, "p99_ms": None}
    a = np.sort(np.asarray(lat_s)) * 1e3
    out = {"p50_ms": round(float(np.percentile(a, 50)), 3),
           "p99_ms": round(float(np.percentile(a, 99)), 3)}
    if slo_ms is not None:
        out[f"within_slo{slo_ms:g}ms"] = int((a <= slo_ms).sum())
    return out


# ---------------------------------------------------------------- drivers

def make_serial_loop(scorer):
    """The historical serial ``serve_forever`` body, verbatim: blocking
    micro-batch pull -> request_table -> transform -> per-row replies."""
    from mmlspark_tpu.io.serving import request_table, reply_from_table

    def transform(t):
        import numpy as np
        preds = scorer(np.asarray(t["features"], np.float32))
        return t.withColumn("pred", np.asarray(preds))

    def loop(srv, stop, max_rows):
        while not stop.is_set():
            batch = srv.get_batch(max_rows=max_rows)
            if not batch:
                continue
            out = transform(request_table(batch))
            reply_from_table(srv, out, "pred")

    return loop


def run_driver(kind, srv, scorer, num_features, max_rows,
               latency_budget_ms, num_scorers=2, num_repliers=1):
    """Start serial loop or ScoringEngine over ``srv``; returns stop().

    Engine thread knobs are per-topology: in-process native scoring
    wants one pipeline worker with inline replies (nothing blocks, the
    GIL serializes anyway); jit scoring and blocking reply paths want
    the multi-worker pipeline."""
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    if kind == "serial":
        stop = threading.Event()
        loop = make_serial_loop(scorer)
        th = threading.Thread(target=loop, args=(srv, stop, max_rows),
                              daemon=True)
        th.start()

        def stopper():
            stop.set()
            th.join(timeout=5)
        return stopper, None
    eng = ScoringEngine(srv, predictor=scorer,
                        plan=ColumnPlan("features", num_features),
                        max_rows=max_rows,
                        latency_budget_ms=latency_budget_ms,
                        num_scorers=num_scorers,
                        num_repliers=num_repliers).start()
    return eng.stop, eng


# ---------------------------------------------------------------- scenarios

def scenario_closed_native(b, X, args):
    """Interleaved serial/engine repeats; best-of per kind (ambient load
    on a shared 2-core box swings single runs by 2x — interleaving plus
    best-of compares the two drivers' actual capacity)."""
    runs = {"serial": [], "engine": []}
    best = {}
    for rep in range(args.reps):
        for kind in ("serial", "engine"):
            srv = LoopServer(X, closed_outstanding=args.outstanding)
            scorer = b.predictor(backend="auto")
            stopper, eng = run_driver(kind, srv, scorer, X.shape[1],
                                      args.max_rows, args.budget_ms,
                                      num_scorers=1, num_repliers=0)
            srv.pump()
            time.sleep(1.0)                  # warm
            srv.reset()
            t0 = time.perf_counter()
            time.sleep(args.duration)
            count, lat = srv.snapshot()
            el = time.perf_counter() - t0
            stats = eng.stats_snapshot() if eng else None
            stopper()
            rps = round(count / el, 1)
            runs[kind].append(rps)
            if kind not in best or rps > best[kind]["rows_per_s"]:
                best[kind] = {"rows_per_s": rps, **_percentiles(lat)}
                if stats:
                    best[kind]["engine_stats"] = stats
    out = {"serial": best["serial"], "engine": best["engine"],
           "runs": runs}
    out["ratio_rows_per_s"] = round(
        best["engine"]["rows_per_s"]
        / max(best["serial"]["rows_per_s"], 1e-9), 3)
    return out


def scenario_open_jit(b, X, args):
    import numpy as np
    out = {}
    for kind in ("serial", "engine"):
        srv = LoopServer(X)                  # open loop: no re-arm
        scorer = b.predictor(backend="jit")  # accelerator serving path
        stopper, eng = run_driver(kind, srv, scorer, X.shape[1],
                                  args.max_rows, args.budget_ms)
        # identical minimal warm: one single-row shape
        srv.send()
        time.sleep(1.5)
        srv.reset()
        t0 = time.perf_counter()
        stop = threading.Event()

        def feeder():
            r = np.random.default_rng(7)     # same arrivals for both
            t_end = time.perf_counter() + args.duration
            nxt = time.perf_counter()
            while time.perf_counter() < t_end and not stop.is_set():
                nxt += r.exponential(1.0 / args.rate)
                dt = nxt - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                srv.send()

        fth = threading.Thread(target=feeder)
        fth.start()
        fth.join()
        time.sleep(args.drain)               # let queued work finish
        count, lat = srv.snapshot()
        # completion-of-offered metric: every counted reply answers a
        # request OFFERED inside the window (the drain accepts late
        # replies but offers nothing new), so count/el is bounded by
        # the offered rate and late replies show up in the percentiles
        # rather than vanishing
        el = time.perf_counter() - t0 - args.drain
        stopper()
        stop.set()
        pct = _percentiles(lat, slo_ms=args.slo_ms)
        goodput = pct.pop(f"within_slo{args.slo_ms:g}ms", 0) / el
        out[kind] = {"offered_rows_per_s": args.rate,
                     "delivered_rows_per_s": round(count / el, 1),
                     f"goodput_slo{args.slo_ms:g}ms_rows_per_s":
                         round(goodput, 1),
                     **pct}
    gkey = f"goodput_slo{args.slo_ms:g}ms_rows_per_s"
    out["ratio_slo_goodput"] = round(
        out["engine"][gkey] / max(out["serial"][gkey], 1e-9), 3)
    out["ratio_p50_latency"] = round(
        (out["serial"]["p50_ms"] or 0)
        / max(out["engine"]["p50_ms"] or 1e-9, 1e-9), 2)
    return out


def scenario_http_threads(b, X, args):
    """End-to-end HTTP closed loop, interleaved repeats, MEDIAN
    reported (single reps swing >2x with ambient load on a shared
    2-core box).  This scenario is transport-bound (HTTP parse + JSON
    in handler threads plus external client processes sharing the
    cores), so it characterizes the full-socket floor rather than the
    driver gap."""
    from mmlspark_tpu.io.serving import DistributedHTTPServer
    runs = {"serial": [], "engine": []}
    per_run = {"serial": [], "engine": []}
    for rep in range(3):
        for kind in ("serial", "engine"):
            srv = DistributedHTTPServer(num_workers=3).start()
            scorer = b.predictor(backend="auto")
            stopper, _ = run_driver(kind, srv, scorer, X.shape[1],
                                    args.max_rows, args.budget_ms)
            t0 = time.perf_counter()
            procs, outs = [], []
            for i in range(args.client_procs):
                path = f"/tmp/bench_serving_lat_{os.getpid()}_{i}.json"
                outs.append(path)
                procs.append(subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--client", ",".join(srv.addresses),
                     str(args.client_conns),
                     str(args.http_duration), path]))
            for p in procs:
                p.wait(timeout=args.http_duration + 60)
            el = time.perf_counter() - t0
            lat = []
            for path in outs:
                with open(path) as f:
                    lat += json.load(f)
                os.unlink(path)
            stopper()
            srv.stop()
            rps = round(len(lat) / el, 1)
            runs[kind].append(rps)
            per_run[kind].append({"rows_per_s": rps, **_percentiles(lat)})
    out = {"runs": runs}
    for kind in ("serial", "engine"):
        med = sorted(per_run[kind],
                     key=lambda r: r["rows_per_s"])[len(per_run[kind]) // 2]
        out[kind] = med
    out["ratio_rows_per_s"] = round(
        out["engine"]["rows_per_s"]
        / max(out["serial"]["rows_per_s"], 1e-9), 3)
    return out


# ---------------------------------------------------------------- main

def telemetry_block(journal_tail=40):
    """The artifact's telemetry section (ISSUE 5): the exact Prometheus
    exposition a ``/metrics`` scrape of this process would return
    (the last engine's stage latencies and resilience counters are
    registered under ``ns="scoring"``) plus a journal excerpt — so a
    perf regression review can read the claimed numbers straight from
    telemetry instead of ad-hoc prints.  Schema is pinned by
    tests/test_telemetry.py."""
    from mmlspark_tpu.core.telemetry import get_journal, get_registry
    return {
        "metrics_exposition": get_registry().render_prometheus(),
        "journal_excerpt": get_journal().tail(journal_tail),
    }


def check_correctness(b, X):
    """Bit-exact margins across every scored path, pinned BEFORE timing."""
    import numpy as np
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    want = np.asarray(b.predict_margin(X[:64])).astype(np.float32)
    ok = {}
    try:
        ok["native"] = bool(np.array_equal(
            np.asarray(b.predictor(backend="native")(X[:64])), want))
    except RuntimeError:
        # no native kernel on this host: record that honestly instead
        # of silently re-testing the jit path under a "native" label
        ok["native"] = "unavailable"
    ok["jit"] = bool(np.array_equal(
        np.asarray(b.predictor(backend="jit")(X[:64])).astype(np.float32),
        want))
    eng = ScoringEngine(LoopServer(X), predictor=b.predictor(),
                        plan=ColumnPlan("features", X.shape[1]))
    batch = [(str(i), {"features": X[i].tolist()}) for i in range(64)]
    pairs = eng._score_predictor(batch)
    ok["engine_padded"] = bool(np.array_equal(
        np.asarray([v for _, v in pairs], np.float32), want))
    return ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="artifact JSON path")
    ap.add_argument("--duration", type=float, default=12.0)
    ap.add_argument("--http-duration", type=float, default=10.0)
    ap.add_argument("--drain", type=float, default=1.0)
    ap.add_argument("--rate", type=float, default=2000.0,
                    help="open-loop offered rows/s")
    ap.add_argument("--slo-ms", type=float, default=50.0)
    ap.add_argument("--outstanding", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved repeats for closed_native")
    ap.add_argument("--max-rows", type=int, default=256)
    ap.add_argument("--budget-ms", type=float, default=5.0)
    ap.add_argument("--client-procs", type=int, default=2)
    ap.add_argument("--client-conns", type=int, default=8)
    ap.add_argument("--trees", type=int, default=60)
    ap.add_argument("--skip-http", action="store_true")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    from mmlspark_tpu.gbdt import LightGBMRegressor

    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 16)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + np.sin(X[:, 3])).astype(np.float64)
    t0 = time.time()
    b = LightGBMRegressor(numIterations=args.trees, numLeaves=31,
                          parallelism="serial", verbosity=0).fit(
        {"features": X, "label": y}).getModel()
    print(f"model: {len(b.trees)} trees ({time.time() - t0:.1f}s)",
          flush=True)

    correctness = check_correctness(b, X)
    print("correctness:", correctness, flush=True)

    # SLO burn-rate monitor (ISSUE 8): sample the registry through the
    # whole bench so the artifact carries "was the error budget being
    # burned" next to the raw goodput numbers.  Windows are scaled to
    # the bench duration (the production defaults are 60 s / 300 s).
    from mmlspark_tpu.core.slo import SLOMonitor, set_monitor
    slo_monitor = set_monitor(SLOMonitor(
        fast_window_s=max(2.0, args.duration / 4),
        slow_window_s=max(8.0, args.duration)))
    slo_monitor.start(tick_s=0.5)

    detail = {"correctness_bit_exact": correctness,
              "model": {"trees": len(b.trees), "num_leaves": 31,
                        "features": int(X.shape[1])},
              "config": {"max_rows": args.max_rows,
                         "latency_budget_ms": args.budget_ms,
                         "engine_threads": {
                             "closed_native": "1 worker, inline replies",
                             "open_jit": "2 workers, 1 replier",
                             "http_threads": "2 workers, 1 replier"},
                         "open_loop_rate": args.rate,
                         "slo_ms": args.slo_ms}}

    print("== closed_native ==", flush=True)
    detail["closed_native"] = scenario_closed_native(b, X, args)
    print(json.dumps(detail["closed_native"], default=str)[:400],
          flush=True)
    print("== open_jit ==", flush=True)
    detail["open_jit"] = scenario_open_jit(b, X, args)
    print(json.dumps(detail["open_jit"]), flush=True)
    if not args.skip_http:
        print("== http_threads ==", flush=True)
        detail["http_threads"] = scenario_http_threads(b, X, args)
        print(json.dumps(detail["http_threads"]), flush=True)

    slo_monitor.stop()
    slo_report = slo_monitor.report()
    print("slo:", json.dumps({"healthy": slo_report["healthy"],
                              "breaching": slo_report["breaching"]}),
          flush=True)

    gkey = f"goodput_slo{args.slo_ms:g}ms_rows_per_s"
    result = {
        "metric": "serving_slo_goodput_rows_per_sec",
        "value": detail["open_jit"]["engine"][gkey],
        "unit": "rows/s",
        "vs_baseline": detail["open_jit"]["ratio_slo_goodput"],
        "accept_ratio_ge_3": detail["open_jit"]["ratio_slo_goodput"] >= 3.0,
        "telemetry": telemetry_block(),
        # burn-rate verdict over the whole bench: pass/fail context for
        # the goodput number (a bench that "won" while torching its
        # error budget did not win)
        "slo": slo_report,
        "detail": detail,
    }
    print(json.dumps({k: v for k, v in result.items() if k != "detail"}),
          flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"artifact -> {args.out}", flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--client":
        _client_proc_main(*sys.argv[2:6])
    else:
        main()
