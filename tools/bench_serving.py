"""Serving latency characterization (the reference's DistributedHTTPSource
claims millisecond-class latency; SURVEY.md §3.4).

Measures end-to-end HTTP round-trip latency through the micro-batch
serving loop for both topologies:

* threads  — DistributedHTTPServer (N thread-workers, one process)
* processes — MultiprocessHTTPServer (N worker OS processes, TCP exchange)

Prints one JSON line per topology with p50/p95/p99 (ms) under sequential
and concurrent load.  Run: ``python tools/bench_serving.py``.
"""

import json
import sys
import threading
import time
import urllib.request

sys.path.insert(0, ".")

from mmlspark_tpu.io.serving import (DistributedHTTPServer,  # noqa: E402
                                     MultiprocessHTTPServer,
                                     reply_from_table, request_table)


def _post(addr, payload, timeout=10.0):
    req = urllib.request.Request(
        addr, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _driver_loop(srv, stop):
    import numpy as np
    while not stop.is_set():
        batch = srv.get_batch(max_rows=64, timeout=0.005)
        if not batch:
            continue
        t = request_table(batch)
        t = t.withColumn("reply", np.asarray(
            [{"y": float(v) * 2} for v in t["x"]], dtype=object))
        reply_from_table(srv, t, "reply")


def _percentiles(lat):
    import numpy as np
    a = np.asarray(sorted(lat)) * 1000.0
    return {"p50_ms": round(float(np.percentile(a, 50)), 2),
            "p95_ms": round(float(np.percentile(a, 95)), 2),
            "p99_ms": round(float(np.percentile(a, 99)), 2)}


def bench(kind, n_seq=200, n_conc=200, conc=16):
    cls = (DistributedHTTPServer if kind == "threads"
           else MultiprocessHTTPServer)
    srv = cls(num_workers=3).start()
    stop = threading.Event()
    drv = threading.Thread(target=_driver_loop, args=(srv, stop),
                           daemon=True)
    drv.start()
    try:
        addrs = srv.addresses
        _post(addrs[0], {"x": 0})          # warm
        seq = []
        for i in range(n_seq):
            t0 = time.perf_counter()
            _post(addrs[i % len(addrs)], {"x": i})
            seq.append(time.perf_counter() - t0)
        conc_lat = []
        lock = threading.Lock()

        def client(i):
            t0 = time.perf_counter()
            _post(addrs[i % len(addrs)], {"x": i})
            with lock:
                conc_lat.append(time.perf_counter() - t0)

        threads = []
        for i in range(n_conc):
            th = threading.Thread(target=client, args=(i,))
            th.start()
            threads.append(th)
            if len(threads) >= conc:
                for th2 in threads:
                    th2.join(20)
                threads = []
        for th in threads:
            th.join(20)
        print(json.dumps({
            "topology": kind,
            "sequential": _percentiles(seq),
            f"concurrent_{conc}": _percentiles(conc_lat),
        }), flush=True)
    finally:
        stop.set()
        srv.stop()


if __name__ == "__main__":
    bench("threads")
    bench("processes")
