"""Online-learning chaos drill (ISSUE 18 acceptance artifact): prove
the whole self-healing loop — streaming ingest → drift-triggered
incremental refresh → gated hot-swap — survives its worst day:

A. **sigkill_mid_refresh** — a drifting feed (ramped
   :class:`ChaosDrift`) served through a real
   :class:`ScoringEngine` + :class:`RolloutController` is tapped into
   an :class:`IngestBuffer`; the SLO burn auto-triggers a refresh in a
   separate trainer process, which is SIGKILLed mid-boost; a fresh
   trainer resumes the SAME episode from the durable dataset +
   checkpoint, publishes the candidate, and the driver canaries and
   promotes it through the standard gate.
B. **canary_drift_rollback_converge** — the feed drifts again; the
   second refresh's canary is soaking when a NEW drift hits the live
   feed — the canary drift gate auto-rolls-back; the episode parks
   under cooldown; once the feed stabilises a third episode fits on
   the post-drift window, canaries clean, promotes, and a fresh
   monitor built from the new active profile shows the SLO burn is
   OUT — the loop converged, no human involved.
C. **serving_consistency** — every reply pumped during A and B is
   bit-exact against exactly one registry version live at that
   moment; zero wrong answers, zero dropped replies, while models
   hot-swap underneath.
D. **journal_chain** — ONE merged trace (driver mirror + both trainer
   mirrors) reconstructs the full chain across three pids:
   triggered → dataset → fit_begin → SIGKILL → recovered →
   candidate → canary → promoted → rolled_back → … → promoted.

All injection is seeded (:class:`ChaosPlan`).  Run:
``python tools/chaos_online.py --out artifacts/chaos_online_r18.json``
(~60 s wall on a 2-core CPU box).
"""

import argparse
import glob
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import chaos_drift  # noqa: E402  (tools/ sibling, not a package)
from chaos_drift import (_QueueServer, fresh_monitor,  # noqa: E402
                         journal_seq, pump, slo_breach_probe, verdict)

SCHEMA = "mmlspark_tpu.chaos_online/v1"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KEEP = ("refresh_triggered", "refresh_dataset", "refresh_fit_begin",
        "refresh_retry", "refresh_recovered", "refresh_candidate",
        "refresh_canary", "refresh_canary_blocked", "refresh_promoted",
        "refresh_rolled_back", "refresh_gave_up", "rollout_started",
        "rollout_promoted", "rollout_rolled_back", "trainer_sigkill",
        "ingest_replay", "drift_onset")


def journal_excerpt(since_seq, max_events=60):
    return chaos_drift.journal_excerpt(since_seq, keep=KEEP,
                                       max_events=max_events)


def label_fn(X):
    # the drill's known ground truth — stands in for the label join a
    # real deployment does before appending to the buffer
    return (X[:, 0] + 0.5 * X[:, 1]).astype("float64")


class Ctx:
    """Shared drill state: data, registry, rollout, ingest, ledger."""

    def __init__(self, root, seed):
        import numpy as np
        from mmlspark_tpu.gbdt import fit_bin_mapper
        from mmlspark_tpu.gbdt.engine import TrainParams, train
        from mmlspark_tpu.gbdt.objectives import RegressionL2
        from mmlspark_tpu.io.chaos import ChaosPlan
        from mmlspark_tpu.io.ingest import IngestBuffer
        from mmlspark_tpu.io.registry import ModelRegistry
        from mmlspark_tpu.io.rollout import (RolloutConfig,
                                             RolloutController)
        self.root = root
        self.rng = np.random.default_rng(seed)
        self.plan = ChaosPlan(seed)
        self.X = self.rng.normal(size=(1600, 6)).astype(np.float32)
        y = label_fn(self.X)
        self.mapper = fit_bin_mapper(self.X, max_bin=63)
        self.base = train(
            self.mapper.transform_packed(self.X), y, None,
            self.mapper, RegressionL2(),
            TrainParams(num_iterations=10, num_leaves=15,
                        min_data_in_leaf=5, parallelism="serial",
                        verbosity=0))
        assert self.base.reference_profile is not None
        self.registry = ModelRegistry(os.path.join(root, "registry"))
        self.registry.publish(self.base, activate=True)
        # reservoir is SEASONING (~3% of the fit window): big enough
        # that a refresh never fully forgets the old regime, small
        # enough that the candidate's reference profile stays within
        # the canary drift gate's PSI budget against settled
        # post-drift traffic — oversize it and the loop can never
        # converge (every refreshed profile keeps old-regime mass the
        # live feed no longer has)
        self.ingest = IngestBuffer(
            os.path.join(root, "ingest"), self.mapper,
            window_rows=2000, reservoir_rows=64, segment_rows=256,
            seed=seed, register=False)
        self.rollout = RolloutController(
            self.registry, backend="auto",
            config=RolloutConfig(canary_fraction=0.5, soak_s=0.3,
                                 min_canary_rows=200,
                                 canary_deadline_ms=None,
                                 fast_window_s=1.0, slow_window_s=2.0,
                                 live_drift_threshold=0.25))
        self.led = {"total": 0, "wrong": 0, "dropped": 0,
                    "by_version": {}}
        self._boosters = {1: self.base}

    def tap(self, rows, margins):
        self.ingest.append(rows, label_fn(rows))

    def reopen_ingest(self):
        """Pick up whatever another process spilled — a fresh handle
        replays the durable segments (the kill-anywhere contract)."""
        from mmlspark_tpu.io.ingest import IngestBuffer
        self.ingest = IngestBuffer(os.path.join(self.root, "ingest"),
                                   register=False)

    def booster(self, v):
        if v not in self._boosters:
            self._boosters[v] = self.registry.load(v)
        return self._boosters[v]

    def steady(self, n, shifts):
        """Sample on-distribution rows, then apply the settled drift
        regime (feature → additive shift)."""
        batch = self.X[self.rng.integers(0, len(self.X), n)].copy()
        for f, s in shifts.items():
            batch[:, f] += s
        return batch


def make_engine(ctx, server, mon=None):
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    return ScoringEngine(
        server, predictor=ctx.rollout,
        plan=ColumnPlan("features", ctx.X.shape[1]),
        max_rows=64, latency_budget_ms=5.0, num_scorers=1,
        num_repliers=0, drift_monitor=mon,
        ingest_tap=ctx.tap).start()


def serve_batch(ctx, server, served, batch, versions, tag):
    """Pump one batch and classify every reply bit-exactly against the
    registry versions live at this instant (scenario C evidence)."""
    import numpy as np
    exp = {v: np.asarray(ctx.booster(v).predict_margin(batch),
                         np.float32) for v in versions}
    served_new = pump(server, served, batch, tag)
    for i in range(len(batch)):
        val, status = server.replies[f"{tag}{served + i}"]
        ctx.led["total"] += 1
        if status != 200:
            ctx.led["dropped"] += 1
            continue
        v32 = np.float32(val)
        for v, w in exp.items():
            if v32 == w[i]:
                key = f"v{v}"
                ctx.led["by_version"][key] = \
                    ctx.led["by_version"].get(key, 0) + 1
                break
        else:
            ctx.led["wrong"] += 1
    return served_new


def make_slo(mon):
    """Private burn monitor over the live drift gauges (fake-clock
    sampled by the refresh controller's polls)."""
    from mmlspark_tpu.core.slo import SLOMonitor, default_objectives
    from mmlspark_tpu.core.telemetry import MetricsRegistry
    mon.flush()
    mon.evaluate(force=True)
    reg = MetricsRegistry()
    reg.register("drift", mon)
    objs = [o for o in default_objectives()
            if o.name in ("feature_drift", "prediction_drift")]
    return SLOMonitor(objs, registry=reg, fast_window_s=3.0,
                      slow_window_s=6.0)


def make_refresh(ctx, monitor, rollout=None):
    from mmlspark_tpu.io.refresh import RefreshConfig, RefreshController
    return RefreshController(
        os.path.join(ctx.root, "refresh"), registry=ctx.registry,
        rollout=rollout if rollout is not None else ctx.rollout,
        ingest=ctx.ingest, monitor=monitor,
        config=RefreshConfig(hysteresis_evals=2, cooldown_s=5.0,
                             min_fit_rows=400, num_iterations=12,
                             checkpoint_chunk=4),
        register=False)


# the trainer process: SAME durable dirs, its own burn monitor; in
# phase "kill" a fit callback SIGKILLs the process mid-boost (the
# refresh analog of the rollout drill's canary_wrap seam)
_TRAINER_SRC = """
import os, signal, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
root, phase = {root!r}, {phase!r}
from mmlspark_tpu.core.telemetry import (configure_flight_recorder,
                                         get_journal)
configure_flight_recorder(directory=root)
get_journal().configure(
    os.path.join(root, "journal_trainer_" + phase + ".jsonl"),
    max_bytes=8 << 20)
from mmlspark_tpu.core.drift import DriftConfig, DriftMonitor
from mmlspark_tpu.core.slo import SLOMonitor, default_objectives
from mmlspark_tpu.core.telemetry import MetricsRegistry
from mmlspark_tpu.io.ingest import IngestBuffer
from mmlspark_tpu.io.refresh import RefreshConfig, RefreshController
from mmlspark_tpu.io.registry import ModelRegistry
registry = ModelRegistry(os.path.join(root, "registry"))
ingest = IngestBuffer(os.path.join(root, "ingest"), register=False)
active = registry.load()
with np.load(os.path.join(root, "drifted.npz")) as d:
    Xd = d["X"]
mon = DriftMonitor(active.reference_profile,
                   DriftConfig(duty=1.0, eval_interval_s=0.02,
                               min_rows=200))
mon.observe(Xd, np.asarray(active.predict_margin(Xd)))
mon.flush(); mon.evaluate(force=True)
reg = MetricsRegistry(); reg.register("drift", mon)
objs = [o for o in default_objectives()
        if o.name in ("feature_drift", "prediction_drift")]
slo = SLOMonitor(objs, registry=reg, fast_window_s=3.0,
                 slow_window_s=6.0)
refresh = RefreshController(
    os.path.join(root, "refresh"), registry=registry, rollout=None,
    ingest=ingest, monitor=slo,
    config=RefreshConfig(hysteresis_evals=1, cooldown_s=5.0,
                         min_fit_rows=400, num_iterations=12,
                         checkpoint_chunk=4),
    register=False)
if phase == "kill":
    def killer(it, trees):
        if it >= 6:
            get_journal().emit("trainer_sigkill", it=int(it))
            os.kill(os.getpid(), signal.SIGKILL)
    refresh.fit_callbacks = [killer]
    for i in range(10):
        refresh.poll(now=float(i))
    print("UNREACHABLE"); sys.exit(3)
assert refresh.state == "fitting", refresh.state
out = None
for i in range(6):
    out = refresh.poll(now=20.0 + i)
    if out == "candidate":
        break
assert out == "candidate", out
print("CANDIDATE", refresh.candidate_version)
"""


def run_trainer(ctx, phase, timeout=300):
    src = _TRAINER_SRC.format(repo=REPO, root=ctx.root, phase=phase)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run([sys.executable, "-c", src], env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


D1 = {0: 3.0}                       # episode-1 drift, settled
D2 = {0: 3.0, 2: 2.5}               # + episode-2 drift, settled
D3 = {0: 3.0, 2: 2.5, 1: 4.0}       # + the mid-canary hit, settled


def scenario_sigkill_mid_refresh(art, ctx):
    print("== A. sigkill_mid_refresh ==")
    import numpy as np
    from mmlspark_tpu.io.chaos import ChaosDrift
    ledger = []
    seq0 = journal_seq()
    # 1. the feed starts drifting: ramped injector over live serving,
    #    every scored batch tapped into the ingest buffer
    drift = ChaosDrift(ctx.plan, feature=0, shift=3.0, after_rows=0,
                       ramp_rows=600, name="feed_drift_ep1")
    server = _QueueServer()
    eng = make_engine(ctx, server)
    served, drifted = 0, []
    try:
        for i in range(8):
            batch = drift(ctx.X[ctx.rng.integers(0, len(ctx.X), 200)])
            drifted.append(batch)
            served = serve_batch(ctx, server, served, batch, [1],
                                 f"a{i}_")
    finally:
        eng.stop()
    ctx.ingest.flush()
    rows_ingested = ctx.ingest.rows_durable
    # the trainer builds its burn monitor off the drifted tail
    np.savez(os.path.join(ctx.root, "drifted.npz"),
             X=np.concatenate(drifted)[-800:])
    # 2. trainer auto-triggers and is SIGKILLed mid-boost
    r1 = run_trainer(ctx, "kill")
    verdict(ledger, "trainer_sigkilled_mid_fit", r1.returncode == -9,
            f"returncode={r1.returncode}")
    state_path = os.path.join(ctx.root, "refresh",
                              "refresh_state.json")
    with open(state_path) as fh:
        state = json.load(fh)
    ck = os.path.join(ctx.root, "refresh", "ckpt_0001",
                      "boost_checkpoint.npz")
    verdict(ledger, "durable_fitting_state",
            state["state"] == "fitting" and os.path.exists(ck),
            f"state={state['state']}, checkpoint={os.path.exists(ck)}")
    # 3. a fresh trainer resumes the SAME episode and publishes
    r2 = run_trainer(ctx, "resume")
    ok2 = r2.returncode == 0 and "CANDIDATE" in r2.stdout
    verdict(ledger, "resumed_fit_published_candidate", ok2,
            (r2.stdout.strip() or r2.stderr[-400:]))
    if not ok2:
        art["scenarios"]["sigkill_mid_refresh"] = {
            "verdicts": ledger, "stderr": r2.stderr[-2000:]}
        return ledger
    v2 = int(r2.stdout.split()[-1])
    ctx.registry.reload()           # see the trainer's publish
    meta = ctx.registry.entry(v2).get("meta") or {}
    verdict(ledger, "candidate_tagged_with_episode",
            meta.get("refresh_episode") == 1, json.dumps(meta))
    # 4. the driver adopts the durable state and runs the gate
    ctx.reopen_ingest()
    refresh = make_refresh(ctx, monitor=None)
    out = refresh.poll(now=50.0)
    verdict(ledger, "candidate_canaried", out == "canary",
            f"poll -> {out}")
    server2 = _QueueServer()
    eng2 = make_engine(ctx, server2)
    gate, served2 = "soaking", 0
    try:
        for i in range(40):
            batch = ctx.steady(200, D1)
            served2 = serve_batch(ctx, server2, served2, batch,
                                  [1, v2], f"ap{i}_")
            gate = ctx.rollout.tick()
            time.sleep(0.12)
            if gate == "promoted":
                break
    finally:
        eng2.stop()
    out2 = refresh.poll(now=60.0)
    verdict(ledger, "gate_promoted_refreshed_model",
            gate == "promoted" and out2 == "promoted",
            f"gate={gate}, refresh={out2}")
    verdict(ledger, "registry_active_is_refreshed",
            ctx.registry.active_version() == v2,
            f"active={ctx.registry.active_version()}")
    merged = ctx.booster(v2)
    verdict(ledger, "merged_forest_extended",
            len(merged.trees) == 10 + 12,
            f"{len(merged.trees)} trees (10 base + 12 refresh)")
    art["scenarios"]["sigkill_mid_refresh"] = {
        "verdicts": ledger,
        "rows_ingested_durable": rows_ingested,
        "refreshed_version": v2,
        "candidate_meta": meta,
        "injections": ctx.plan.counts(),
        "journal": journal_excerpt(seq0),
    }
    return ledger


def scenario_rollback_converge(art, ctx):
    print("== B. canary_drift_rollback_converge ==")
    from mmlspark_tpu.io.chaos import ChaosDrift
    ledger = []
    seq0 = journal_seq()
    v_active = ctx.registry.active_version()
    # 1. the feed drifts AGAIN (ramped, a different feature); the burn
    #    vs the refreshed model's own profile triggers episode 2
    drift2 = ChaosDrift(ctx.plan, feature=2, shift=2.5, after_rows=0,
                        ramp_rows=400, name="feed_drift_ep2")
    mon2 = fresh_monitor(ctx.booster(v_active).reference_profile)
    server = _QueueServer()
    eng = make_engine(ctx, server, mon=mon2)
    served = 0
    try:
        # enough post-ramp traffic that the recency window is pure
        # settled-D2 by fit time (see the reservoir sizing note above)
        for i in range(12):
            batch = drift2(ctx.steady(200, D1))
            served = serve_batch(ctx, server, served, batch,
                                 [v_active], f"b{i}_")
    finally:
        eng.stop()
    refresh = make_refresh(ctx, monitor=make_slo(mon2))
    trace, t = [], 100.0
    while t < 120.0:
        out = refresh.poll(now=t)
        trace.append(out)
        t += 1.0
        if out in ("candidate", "gave_up"):
            break
    verdict(ledger, "second_episode_fit", out == "candidate",
            f"trace={trace}")
    if out != "candidate":
        art["scenarios"]["canary_drift_rollback_converge"] = {
            "verdicts": ledger, "trace": trace}
        return ledger
    v3 = refresh.candidate_version
    # 2. canary soaks with the drift gate armed off the CANDIDATE's
    #    fit-time profile (trained on the drifted window: the settled
    #    D2 feed looks clean to it).  The gate's monitor is fed the
    #    CANARY's view of the traffic — rows scored by the candidate —
    #    not the engine's mixed baseline/canary margin stream, which
    #    would read as prediction drift for any candidate that
    #    (correctly) predicts differently from the model it replaces.
    import numpy as np
    mon3 = fresh_monitor(ctx.booster(v3).reference_profile)
    ctx.rollout.attach_drift(mon3)

    def observe_as(mon, v, batch):
        mon.observe(batch, np.asarray(
            ctx.booster(v).predict_margin(batch)))

    # this phase exists to prove the gate ROLLS BACK a canary hit by
    # drift mid-soak, so the soak window must outlast the clean-soak
    # batches plus the drift's detection latency (production default is
    # 60 s; the drill's promote phases compress it to 0.3 s) — restored
    # before episode 3 canaries
    ctx.rollout.cfg.soak_s = 60.0
    out = refresh.poll(now=t)
    verdict(ledger, "second_candidate_canaried", out == "canary",
            f"poll -> {out}")
    server3 = _QueueServer()
    eng3 = make_engine(ctx, server3)
    drift3 = ChaosDrift(ctx.plan, feature=1, shift=4.0, after_rows=0,
                        name="mid_canary_hit")
    gate, served3, held_clean = "soaking", 0, None
    try:
        for i in range(4):          # clean soak: the gate must hold
            batch = ctx.steady(150, D2)
            served3 = serve_batch(ctx, server3, served3, batch,
                                  [v_active, v3], f"bc{i}_")
            observe_as(mon3, v3, batch)
            gate = ctx.rollout.tick()
            time.sleep(0.12)
        held_clean = gate == "soaking"
        for i in range(40):         # then the mid-canary drift hits
            batch = drift3(ctx.steady(150, D2))
            served3 = serve_batch(ctx, server3, served3, batch,
                                  [v_active, v3], f"bd{i}_")
            observe_as(mon3, v3, batch)
            gate = ctx.rollout.tick()
            time.sleep(0.1)
            if gate == "rolled_back":
                break
    finally:
        eng3.stop()
    verdict(ledger, "clean_canary_held", bool(held_clean),
            f"gate after clean soak: {'soaking' if held_clean else gate}")
    verdict(ledger, "mid_canary_drift_rolled_back",
            gate == "rolled_back", f"gate={gate}")
    t += 1.0
    out = refresh.poll(now=t)
    verdict(ledger, "episode_finished_rolled_back",
            out == "rolled_back"
            and ctx.registry.entry(v3)["promoted_state"]
            == "rolled_back"
            and ctx.registry.active_version() == v_active,
            f"poll={out}, v3={ctx.registry.entry(v3)['promoted_state']}"
            f", active={ctx.registry.active_version()}")
    t += 1.0
    verdict(ledger, "cooldown_enforced",
            refresh.poll(now=t) == "cooldown", "")
    # 3. the feed settles on the post-hit distribution; episode 3
    #    fits on it, canaries clean, promotes, and the burn goes out
    server4 = _QueueServer()
    mon2b = fresh_monitor(ctx.booster(v_active).reference_profile)
    eng4 = make_engine(ctx, server4, mon=mon2b)
    served4 = 0
    try:
        for i in range(11):
            batch = ctx.steady(200, D3)
            served4 = serve_batch(ctx, server4, served4, batch,
                                  [v_active], f"bs{i}_")
    finally:
        eng4.stop()
    refresh3 = make_refresh(ctx, monitor=make_slo(mon2b))
    t += 10.0                       # past the episode-2 cooldown
    trace3 = []
    while t < 160.0:
        out = refresh3.poll(now=t)
        trace3.append(out)
        t += 1.0
        if out in ("candidate", "gave_up"):
            break
    verdict(ledger, "third_episode_fit", out == "candidate",
            f"trace={trace3}")
    if out != "candidate":
        art["scenarios"]["canary_drift_rollback_converge"] = {
            "verdicts": ledger, "trace": trace, "trace3": trace3}
        return ledger
    v4 = refresh3.candidate_version
    mon4 = fresh_monitor(ctx.booster(v4).reference_profile)
    ctx.rollout.attach_drift(mon4)
    ctx.rollout.cfg.soak_s = 0.3    # promote phase: short soak again
    out = refresh3.poll(now=t)
    server5 = _QueueServer()
    eng5 = make_engine(ctx, server5)
    gate, served5 = "soaking", 0
    try:
        for i in range(40):
            batch = ctx.steady(200, D3)
            served5 = serve_batch(ctx, server5, served5, batch,
                                  [v_active, v4], f"bp{i}_")
            observe_as(mon4, v4, batch)
            gate = ctx.rollout.tick()
            time.sleep(0.12)
            if gate == "promoted":
                break
    finally:
        eng5.stop()
    t += 1.0
    out = refresh3.poll(now=t)
    verdict(ledger, "second_refresh_promoted",
            gate == "promoted" and out == "promoted"
            and ctx.registry.active_version() == v4,
            f"gate={gate}, refresh={out}, "
            f"active={ctx.registry.active_version()}")
    # the convergence check: a FRESH monitor off the new active
    # profile sees the live feed as in-distribution — no burn left
    mon_check = fresh_monitor(ctx.booster(v4).reference_profile)
    batch = ctx.steady(800, D3)
    import numpy as np
    mon_check.observe(batch, np.asarray(
        ctx.booster(v4).predict_margin(batch)))
    mon_check.flush()
    verdicts = slo_breach_probe(mon_check)
    verdict(ledger, "converged_slo_clean",
            not any(v["breach"] for v in verdicts.values())
            and not mon_check.report()["alerting"],
            json.dumps({k: v["breach"] for k, v in verdicts.items()}))
    art["scenarios"]["canary_drift_rollback_converge"] = {
        "verdicts": ledger,
        "rolled_back_version": v3,
        "converged_version": v4,
        "trace_episode2": trace,
        "trace_episode3": trace3,
        "final_slo": {k: v["breach"] for k, v in verdicts.items()},
        "final_drift_gauges": mon_check.report()["gauges"],
        "journal": journal_excerpt(seq0),
    }
    return ledger


def scenario_serving_consistency(art, ctx):
    print("== C. serving_consistency ==")
    ledger = []
    led = ctx.led
    verdict(ledger, "replies_observed", led["total"] >= 4000,
            f"{led['total']} replies across the drill")
    verdict(ledger, "zero_dropped", led["dropped"] == 0,
            f"dropped={led['dropped']}")
    verdict(ledger, "all_bit_exact_one_version", led["wrong"] == 0,
            f"wrong={led['wrong']}, by_version={led['by_version']}")
    verdict(ledger, "served_from_multiple_versions",
            len(led["by_version"]) >= 3,
            f"versions seen: {sorted(led['by_version'])}")
    art["scenarios"]["serving_consistency"] = {
        "verdicts": ledger, "replies": dict(led)}
    return ledger


def scenario_journal_chain(art, ctx):
    print("== D. journal_chain ==")
    from mmlspark_tpu.core.telemetry import read_journal
    ledger = []
    evs = []
    for path in sorted(glob.glob(
            os.path.join(ctx.root, "journal_*.jsonl"))):
        evs += read_journal(path)
    evs = [e for e in evs if e["ev"] in KEEP]
    evs.sort(key=lambda e: (e["ts"], e["seq"]))

    def first(ev, episode=None):
        for i, e in enumerate(evs):
            if e["ev"] == ev and (episode is None
                                  or e.get("episode") == episode):
                return i, e
        return None, None

    chain1 = ["refresh_triggered", "refresh_dataset",
              "refresh_fit_begin", "trainer_sigkill",
              "refresh_recovered", "refresh_candidate",
              "refresh_canary", "refresh_promoted"]
    idx = [first(ev, None if ev == "trainer_sigkill" else 1)[0]
           for ev in chain1]
    ok1 = all(i is not None for i in idx) and idx == sorted(idx)
    verdict(ledger, "episode1_chain_ordered", ok1,
            " -> ".join(f"{ev}@{i}" for ev, i in zip(chain1, idx)))
    i_fit, e_fit = first("refresh_fit_begin", 1)
    i_rec, e_rec = first("refresh_recovered", 1)
    verdict(ledger, "recovery_crossed_processes",
            e_fit and e_rec and e_fit["pid"] != e_rec["pid"],
            f"fit pid={e_fit and e_fit['pid']}, "
            f"recover pid={e_rec and e_rec['pid']}")
    i_rb, _ = first("refresh_rolled_back", 2)
    verdict(ledger, "episode2_rolled_back_in_trace", i_rb is not None,
            f"idx={i_rb}")
    i_p3, _ = first("refresh_promoted", 3)
    verdict(ledger, "episode3_promoted_in_trace",
            i_p3 is not None and (i_rb is None or i_rb < i_p3),
            f"idx={i_p3}")
    pids = {e["pid"] for e in evs}
    verdict(ledger, "trace_spans_processes", len(pids) >= 3,
            f"{len(pids)} pids in the merged trace")
    art["scenarios"]["journal_chain"] = {
        "verdicts": ledger,
        "events": [{k: e.get(k) for k in
                    ("ts", "pid", "ev", "episode", "state", "version")}
                   for e in evs],
    }
    return ledger


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/chaos_online_r18.json")
    ap.add_argument("--seed", type=int, default=18)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    from mmlspark_tpu.core.drift import set_drift_monitor
    from mmlspark_tpu.core.telemetry import (configure_flight_recorder,
                                             get_journal, host_info)
    t0 = time.time()
    art = {"schema": SCHEMA, "seed": args.seed, "host": host_info(),
           "scenarios": {}}
    ledgers = []
    with tempfile.TemporaryDirectory() as root:
        configure_flight_recorder(directory=root)
        get_journal().configure(
            os.path.join(root, "journal_driver.jsonl"),
            max_bytes=8 << 20)
        ctx = Ctx(root, args.seed)
        try:
            ledgers += scenario_sigkill_mid_refresh(art, ctx)
            ledgers += scenario_rollback_converge(art, ctx)
            ledgers += scenario_serving_consistency(art, ctx)
            ledgers += scenario_journal_chain(art, ctx)
        finally:
            ctx.rollout.stop()
            set_drift_monitor(None)
            get_journal().configure(None)
    art["verdicts_total"] = len(ledgers)
    art["verdicts_pass"] = sum(1 for v in ledgers if v["pass"])
    art["healthy"] = art["verdicts_pass"] == art["verdicts_total"]
    art["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(art, fh, indent=1)
    print(f"\n{art['verdicts_pass']}/{art['verdicts_total']} verdicts "
          f"pass in {art['wall_s']}s -> {args.out}")
    return 0 if art["healthy"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
