"""Training chaos drill (ISSUE 4 acceptance artifact): inject controller
death, snapshot corruption and heartbeat stalls into a REAL 2-process
multicontroller fit and verify the fault-tolerance contract:

1. **zero wrong trees** — every recovered run's forest is bit-identical
   (native model text equality) to the uninterrupted baseline;
2. **recovery to completion** — a SIGKILLed controller's gang respawns
   (fresh rendezvous port, same checkpoint directory), resumes from the
   last chunk boundary, and finishes;
3. **corruption safety** — a bit-flipped snapshot is discarded with a
   warning and the fit degrades to fresh, never to garbage;
4. **observability** — ckpt_resumed / ckpt_discarded / heartbeat_stalls
   counters and the heartbeat_age_ms gauge are present in the workers'
   StageStats dumps and move when the faults fire.

Topology: 2 OS processes x 1 CPU device, ``jax.distributed`` rendezvous
over localhost with gloo CPU collectives — the
``tests/test_multicontroller.py`` configuration, driven through the
elastic runner (``python -m mmlspark_tpu.gbdt.elastic``) under the
:func:`mmlspark_tpu.gbdt.elastic.supervise` gang supervisor.

Phase 4 additionally drills the ISSUE 6 transport heartbeat mode: two
watchdogs beaconing through a ``HeartbeatHub`` over resumable
``io/transport.py`` sessions under seeded link kills and an injected
beacon stall — a link blip must never fake a dead peer.

Run: ``python tools/chaos_training.py --out artifacts/chaos_training_r06.json``
(~2-3 min wall on a 2-core CPU box; jax process startups dominate).
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env(workdir=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if workdir:
        # flight records from the drill's INTENDED kills (fit_failed /
        # peer_lost dumps) belong next to the drill's logs, not in the
        # repo's committed artifacts/
        env.setdefault("MMLSPARK_TPU_FLIGHTREC_DIR", workdir)
    return env


def spawn_worker(pid, port, workdir, phase, attempt, *, ckpt="",
                 iterations, checkpoint_chunk, stall="",
                 kill_at_boundary=0, lease_timeout=5.0,
                 straggler_age=0.6):
    hb = os.path.join(workdir, f"hb_{phase}_{attempt}")
    os.makedirs(hb, exist_ok=True)
    cmd = [sys.executable, "-m", "mmlspark_tpu.gbdt.elastic",
           "--coordinator", f"127.0.0.1:{port}",
           "--num-processes", "2", "--process-id", str(pid),
           "--heartbeat-dir", hb,
           "--checkpoint-dir", ckpt,
           "--out", os.path.join(workdir, f"model_{phase}.txt"),
           "--stats-out", os.path.join(
               workdir, f"stats_{phase}_{attempt}_p{pid}.json"),
           "--iterations", str(iterations),
           "--checkpoint-chunk", str(checkpoint_chunk),
           "--lease-timeout", str(lease_timeout),
           "--straggler-age", str(straggler_age)]
    if stall and pid == 1:
        cmd += ["--chaos-heartbeat-stall", stall]
    if kill_at_boundary and pid == 1:
        cmd += ["--chaos-kill-at-boundary", str(kill_at_boundary)]
    # log files, not PIPEs: the supervisor only wait()s, and an
    # undrained PIPE wedges any worker whose traceback exceeds the
    # ~64KiB buffer — recording a successful recovery as a timed-out
    # round; files also keep the failure diagnostics
    log_path = os.path.join(workdir, f"log_{phase}_{attempt}_p{pid}.txt")
    with open(log_path, "w") as log_fh:
        return subprocess.Popen(cmd, env=_worker_env(workdir),
                                stdout=log_fh,
                                stderr=subprocess.STDOUT, text=True)


def read_stats(workdir, phase, attempt):
    out = {}
    for pid in range(2):
        path = os.path.join(workdir, f"stats_{phase}_{attempt}_p{pid}.json")
        if os.path.exists(path):
            with open(path) as fh:
                out[str(pid)] = json.load(fh)
    return out


def run_phase(phase, workdir, args, *, kill=False, corrupt="",
              stall=""):
    """One drill phase: supervise gang rounds until a clean finish.

    ``kill``: controller 1 is SIGKILLed (``ChaosControllerKill``: no
    cleanup runs) the moment the first chunk boundary is durable
    (round 0 only).  ``corrupt``: corrupt the snapshot meta with this
    mode before the RESPAWN round.  ``stall``: heartbeat stall spec
    injected into controller 1."""
    from mmlspark_tpu.gbdt.elastic import supervise
    from mmlspark_tpu.io.chaos import corrupt_file

    ckpt = os.path.join(workdir, f"ckpt_{phase}")
    os.makedirs(ckpt, exist_ok=True)
    events = []
    procs_by_round = {}

    def spawn_round(attempt, port):
        if corrupt and attempt == 1:
            from mmlspark_tpu.gbdt.engine import _CKPT_FILE
            meta = os.path.join(ckpt, _CKPT_FILE)
            if os.path.exists(meta):
                corrupt_file(meta, mode=corrupt)
                events.append({"event": f"corrupted snapshot ({corrupt})",
                               "round": attempt})
                print(f"[{phase}] corrupted {meta} ({corrupt})",
                      flush=True)
            else:
                # round 0 died before any boundary became durable
                # (e.g. rendezvous exhausted): nothing to corrupt — the
                # corrupt_snapshot_discarded verdict will fail and say
                # so, which beats crashing the drill with no artifact
                events.append({"event": "no durable snapshot to corrupt",
                               "round": attempt})
                print(f"[{phase}] no durable snapshot to corrupt",
                      flush=True)
        kb = args.checkpoint_chunk if (kill and attempt == 0) else 0
        if kb:
            events.append({"event": "armed SIGKILL of controller 1 at "
                                    f"boundary {kb}", "round": attempt})
        procs = [spawn_worker(pid, port, workdir, phase, attempt,
                              ckpt=ckpt, iterations=args.iterations,
                              checkpoint_chunk=args.checkpoint_chunk,
                              stall=stall, kill_at_boundary=kb,
                              lease_timeout=args.lease_timeout)
                 for pid in range(2)]
        procs_by_round[attempt] = procs
        return procs

    t0 = time.time()
    restarts = supervise(spawn_round, max_restarts=args.max_restarts,
                         round_timeout_s=args.phase_timeout)
    wall = time.time() - t0
    stats = {str(a): read_stats(workdir, phase, a)
             for a in range(restarts + 1)}
    exit_codes = {str(a): [p.returncode for p in ps]
                  for a, ps in procs_by_round.items()}
    model = open(os.path.join(workdir, f"model_{phase}.txt")).read()
    ckpt_leftover = [p for p in os.listdir(ckpt)] if os.path.isdir(ckpt) \
        else []
    return {"model": model, "restarts": restarts, "stats": stats,
            "events": events, "wall_s": round(wall, 1),
            "exit_codes": exit_codes, "ckpt_leftover": ckpt_leftover}


def telemetry_block(stats_by_pid, journal_tail=60):
    """The artifact's telemetry section (ISSUE 5): render the workers'
    dumped StageStats snapshots (``train`` + ``watchdog`` per
    controller, plus gang-aggregated totals) in the same Prometheus
    exposition a ``/metrics`` scrape would return, and merge their
    journal tails into one ``(ts, seq)``-ordered excerpt — the recovery
    story (ckpt_saved/ckpt_resumed, peer_stalled, fit spans) read from
    telemetry instead of ad-hoc prints.  Schema is pinned by
    tests/test_telemetry.py."""
    from mmlspark_tpu.core.telemetry import (merge_snapshots,
                                             render_prometheus)
    snaps, journal = {}, []
    for pid in sorted(stats_by_pid):
        s = stats_by_pid[pid]
        for group in ("train", "watchdog"):
            if isinstance(s.get(group), dict):
                snaps[f"{group}_p{pid}"] = s[group]
        journal.extend(s.get("journal_tail") or [])
    for group in ("train", "watchdog"):
        members = [s[group] for s in stats_by_pid.values()
                   if isinstance(s.get(group), dict)]
        if members:
            snaps[f"{group}_gang"] = merge_snapshots(members)
    journal.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return {"metrics_exposition": render_prometheus(snaps),
            "journal_excerpt": journal[-journal_tail:]}


def transport_heartbeat_drill(seed=17, runtime_s=4.0):
    """Phase 4 (ISSUE 6): drill the TRANSPORT heartbeat mode — two
    watchdogs beaconing leases through a ``HeartbeatHub`` over
    resumable transport sessions while seeded link kills and an
    injected beacon stall hit the wire.  Contract: a link blip NEVER
    fakes a dead peer (session resume outruns the lease timeout), a
    genuine beacon stall past the straggler threshold IS counted, and
    the transport's reconnect/resume counters move."""
    import threading
    import time as _t

    from mmlspark_tpu.core.profiling import StageStats
    from mmlspark_tpu.gbdt.elastic import (ElasticConfig, HeartbeatHub,
                                           HeartbeatWatchdog)
    from mmlspark_tpu.io import transport as tp
    from mmlspark_tpu.io.chaos import ChaosHeartbeat, ChaosPlan

    c0 = dict(tp.transport_stats.snapshot()["counters"])
    hub = HeartbeatHub(token="hb-drill").start()
    lost = []
    watchdogs = []
    stats = {}
    plan = ChaosPlan(seed=seed)
    # controller 1's beacons stall once for 1.0 s (straggler range:
    # above straggler_age_s=0.5, far below lease_timeout_s=3.0)
    stall = ChaosHeartbeat(plan, after_s=1.0, stall_s=1.0)
    for pid in range(2):
        cfg = ElasticConfig(
            heartbeat_dir="", process_id=pid, num_processes=2,
            heartbeat_interval_s=0.1, straggler_age_s=0.5,
            lease_timeout_s=3.0, transport_address=hub.address,
            transport_token="hb-drill")
        stats[pid] = StageStats()
        wd = HeartbeatWatchdog(
            cfg, stats=stats[pid],
            on_peer_lost=lambda p, a: lost.append((p, round(a, 2))),
            write_hook=stall if pid == 1 else None)
        wd.start()
        watchdogs.append(wd)
    _t.sleep(1.0)
    # seeded link kills: yank controller 0's hub link twice mid-run;
    # the session must resume before any lease expires
    kills = 0
    for _ in range(2):
        sock = watchdogs[0]._client.session._sock
        if sock is not None:
            sock.close()
            kills += 1
        _t.sleep(runtime_s / 2)
    for wd in watchdogs:
        wd.stop()
    hub.stop()
    c1 = tp.transport_stats.snapshot()["counters"]
    delta = {k: c1[k] - c0.get(k, 0) for k in c1}
    snap = {pid: stats[pid].snapshot() for pid in stats}
    stalls = sum(s["counters"].get("heartbeat_stalls", 0)
                 for s in snap.values())
    verdicts = {
        "transport_hb_no_false_peer_loss": not lost,
        "transport_hb_link_resumed":
            kills >= 1 and delta.get("resumes", 0) >= 1,
        "transport_hb_straggler_counted": stalls >= 1,
    }
    detail = {"link_kills": kills, "peer_lost": lost,
              "injected_stalls": stall.stalls,
              "watchdog_stats": {str(k): v for k, v in snap.items()},
              "counters_delta": delta}
    return verdicts, detail


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="artifact JSON path")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--iterations", type=int, default=24)
    ap.add_argument("--checkpoint-chunk", type=int, default=6)
    ap.add_argument("--lease-timeout", type=float, default=4.0)
    ap.add_argument("--heartbeat-stall", default="2.0:1.2",
                    help="AFTER_S:STALL_S for the stall phase (between "
                         "the straggler threshold and the lease)")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--phase-timeout", type=float, default=240.0)
    args = ap.parse_args()

    import tempfile
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_training_")
    os.makedirs(workdir, exist_ok=True)
    print(f"workdir: {workdir}", flush=True)
    detail = {"config": {
        "iterations": args.iterations,
        "checkpoint_chunk": args.checkpoint_chunk,
        "lease_timeout_s": args.lease_timeout,
        "heartbeat_stall": args.heartbeat_stall,
        "topology": "2 processes x 1 CPU device, gloo collectives"}}

    t_all = time.time()
    print("== phase 0: uninterrupted baseline ==", flush=True)
    base = run_phase("baseline", workdir, args)
    detail["baseline"] = {k: base[k] for k in
                          ("restarts", "wall_s", "exit_codes",
                           "ckpt_leftover")}

    print("== phase 1: controller SIGKILL mid-fit ==", flush=True)
    killp = run_phase("kill", workdir, args, kill=True)
    detail["kill"] = {k: killp[k] for k in
                      ("restarts", "wall_s", "events", "exit_codes",
                       "stats")}

    print("== phase 2: kill + snapshot bitflip corruption ==", flush=True)
    corr = run_phase("corrupt", workdir, args, kill=True,
                     corrupt="bitflip")
    detail["corrupt"] = {k: corr[k] for k in
                         ("restarts", "wall_s", "events", "exit_codes",
                          "stats")}

    print("== phase 3: heartbeat stall (straggler) ==", flush=True)
    stall = run_phase("stall", workdir, args,
                      stall=args.heartbeat_stall)
    detail["stall"] = {k: stall[k] for k in
                       ("restarts", "wall_s", "exit_codes", "stats")}

    print("== phase 4: transport heartbeat chaos (ISSUE 6) ==",
          flush=True)
    # SLO burn-rate context (ISSUE 8): phase 4 runs watchdogs and the
    # transport IN THIS process, so the heartbeat-freshness and
    # transport-retransmit objectives are live — sample them through
    # the phase and embed the verdict
    from mmlspark_tpu.core.slo import SLOMonitor, set_monitor
    slo_monitor = set_monitor(SLOMonitor(fast_window_s=2.0,
                                         slow_window_s=8.0))
    slo_monitor.start(tick_s=0.25)
    transport_verdicts, transport_detail = transport_heartbeat_drill()
    slo_monitor.stop()
    slo_report = slo_monitor.report()
    detail["slo"] = slo_report
    print("slo:", json.dumps({"healthy": slo_report["healthy"],
                              "breaching": slo_report["breaching"]}),
          flush=True)
    detail["transport_heartbeats"] = transport_detail
    print(json.dumps(transport_verdicts), flush=True)
    detail["total_wall_s"] = round(time.time() - t_all, 1)

    def last_round_stats(phase_result):
        rounds = sorted(phase_result["stats"], key=int)
        return phase_result["stats"][rounds[-1]] if rounds else {}

    def any_counter(stats_by_pid, group, name):
        return sum(s.get(group, {}).get("counters", {}).get(name, 0)
                   for s in stats_by_pid.values())

    kill_last = last_round_stats(killp)
    corr_last = last_round_stats(corr)
    stall_last = last_round_stats(stall)
    kill_codes_r0 = killp["exit_codes"].get("0", [])
    verdicts = {
        "baseline_clean": base["restarts"] == 0,
        "baseline_ckpt_cleared": base["ckpt_leftover"] == [],
        "kill_recovered_to_completion": killp["restarts"] >= 1,
        "kill_sigkill_observed": -9 in kill_codes_r0,
        # the survivor must be torn down so the gang can respawn — via
        # the lease watchdog's RESTART_EXIT_CODE (76) when the runtime
        # wedges, or by the jax runtime's own fast failure (collective
        # error / coordination-service abort) when it notices first;
        # either way no member of round 0 may report success (a 0 exit
        # would mean a half-gang "finished" without its peer).  The
        # lease-expiry path itself is pinned by
        # tests/test_chaos_training.py::TestElasticWatchdog.
        "kill_survivor_torn_down": all(rc != 0 for rc in kill_codes_r0),
        "kill_resumed_from_checkpoint":
            any_counter(kill_last, "train", "ckpt_resumed") >= 1,
        "kill_forest_bit_identical": killp["model"] == base["model"],
        "corrupt_snapshot_discarded":
            any_counter(corr_last, "train", "ckpt_discarded") >= 1,
        "corrupt_forest_bit_identical": corr["model"] == base["model"],
        "stall_completed_without_restart": stall["restarts"] == 0,
        "stall_straggler_counted":
            any_counter(stall_last, "watchdog", "heartbeat_stalls") >= 1,
        "stall_no_false_peer_loss":
            any_counter(stall_last, "watchdog", "peer_lost") == 0,
        "stall_forest_bit_identical": stall["model"] == base["model"],
        # len guards: all(...) over an empty stats dict is vacuously
        # true — exactly when observability produced nothing
        "heartbeat_age_gauge_exposed": len(stall_last) == 2 and all(
            "heartbeat_age_ms" in s.get("watchdog", {}).get("gauges", {})
            for s in stall_last.values()),
        "recovery_counters_exposed": len(kill_last) == 2 and all(
            k in s.get("train", {}).get("counters", {})
            for s in kill_last.values()
            for k in ("chunks_replayed", "ckpt_resumed",
                      "ckpt_discarded")),
        # ISSUE 8: the SLO monitor MEASURED the in-process transport-
        # heartbeat phase — the objectives live there (watchdog gauges
        # + transport counters) must have produced real windowed burn
        # numbers, not just rendered their keys (burn levels are
        # context; the drill's own verdicts gate correctness)
        "slo_evaluated": bool(slo_report["objectives"])
        and slo_report["objectives"]["heartbeat_freshness"]
        ["burn_rate_slow"] is not None
        and slo_report["objectives"]["transport_retransmit"]
        ["burn_rate_slow"] is not None,
        **transport_verdicts,
    }
    result = {
        "metric": "chaos_training_drill",
        "value": int(all(verdicts.values())),
        "unit": "pass",
        "verdicts": verdicts,
        # the kill phase's final round carries the richest recovery
        # telemetry (resume counters, fit spans, ckpt events)
        "telemetry": telemetry_block(kill_last),
        "detail": detail,
    }
    print(json.dumps({"verdicts": verdicts,
                      "pass": bool(all(verdicts.values()))}, indent=1),
          flush=True)
    if not all(verdicts.values()):
        from mmlspark_tpu.core.telemetry import record_flight
        path = record_flight(
            "chaos_training_verdict_failure",
            {"verdicts": {k: bool(v) for k, v in verdicts.items()}})
        print(f"flight record -> {path}", flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"artifact -> {args.out}", flush=True)
    return 0 if all(verdicts.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
