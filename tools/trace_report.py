"""Trace-report reader: reconstruct per-request and per-fit timelines
from an :class:`mmlspark_tpu.core.telemetry.EventJournal` JSONL dump
(ISSUE 5).

The serving engine journals per-BATCH pipeline events
(``form``/``decode``/``score``/``reply``, plus
``shed``/``expired``/``salvage``) carrying the batch's request ids and
trace ids; the training engine journals per-FIT events (``fit_begin``,
``boost_chunk``, ``ckpt_saved``/``ckpt_resumed``/``ckpt_discarded``,
``chunk_replayed``, ``peer_stalled``/``peer_lost``, ``fit_end``) stamped
with a fit span id.  This tool stitches either kind back into a
timeline:

* :func:`request_timeline` — given a trace id (the client's
  ``_trace_id`` payload key, or the request id minted at admission),
  find the request's batch events and order them: a complete scored
  request shows ``form → decode → score → reply``.
* :func:`fit_timeline` — given a fit span id (or the newest fit in the
  journal), order everything stamped with it.

CLI::

    python tools/trace_report.py JOURNAL.jsonl [more.jsonl ...] \
        [--trace-id TID] [--fit SPAN | --fit latest]

Multiple journal files (e.g. one per controller of a gang) are merged
and ordered by ``(ts, seq)`` — ``seq`` is process-monotonic, ``ts`` is
wall clock, so cross-process order is as honest as the hosts' clocks.
"""

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the serving pipeline stages a fully-served request passes through
REQUEST_STAGES = ("form", "decode", "score", "reply")


def load_events(paths) -> List[dict]:
    """Load and merge one or more JSONL journals (or pass event dicts
    through), ordered by ``(ts, seq)``."""
    from mmlspark_tpu.core.telemetry import read_journal
    events: List[dict] = []
    for p in ([paths] if isinstance(paths, str) else list(paths)):
        if isinstance(p, dict):
            events.append(p)
        else:
            events.extend(read_journal(p))
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return events


def _resolve_rid(events: Iterable[dict], trace_id: str) -> str:
    """Map a trace id to its request id via any batch event that
    carries both aligned lists; a trace id that never appears is
    assumed to BE the rid (the minted-at-admission default, where the
    two are the same string)."""
    for e in events:
        tids = e.get("trace_ids") or []
        if trace_id in tids:
            rids = e.get("rids") or []
            i = tids.index(trace_id)
            if i < len(rids):
                return str(rids[i])
    return trace_id


def request_timeline(events: Iterable[dict], trace_id: str) -> dict:
    """Reconstruct one request's pipeline timeline.

    Returns ``{"trace_id", "rid", "events": [...], "stages": [...],
    "complete": bool}`` — ``complete`` means the full
    form→decode→score→reply chain was observed (a shed/expired request
    is legitimately incomplete and shows its degradation event
    instead)."""
    events = list(events)
    rid = _resolve_rid(events, trace_id)
    mine: List[dict] = []
    for e in events:
        if rid in (e.get("rids") or []) \
                or trace_id in (e.get("trace_ids") or []):
            mine.append(e)
    mine.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    stages = [e.get("ev") for e in mine]
    return {
        "trace_id": trace_id,
        "rid": rid,
        "events": mine,
        "stages": stages,
        "complete": all(s in stages for s in REQUEST_STAGES),
    }


def list_fits(events: Iterable[dict]) -> List[str]:
    """Fit span ids in first-seen order."""
    out: List[str] = []
    for e in events:
        span = e.get("fit")
        if span and span not in out:
            out.append(span)
    return out


def fit_timeline(events: Iterable[dict],
                 fit_span: Optional[str] = None) -> dict:
    """Reconstruct one fit's timeline (``fit_span=None`` picks the
    NEWEST fit that has a ``fit_begin`` — the one a post-mortem usually
    wants).  ``complete`` means both ``fit_begin`` and ``fit_end`` were
    observed; a crashed fit shows ``fit_failed`` or simply no end."""
    events = list(events)
    if fit_span is None:
        begins = [e.get("fit") for e in events
                  if e.get("ev") == "fit_begin" and e.get("fit")]
        fit_span = begins[-1] if begins else None
    mine = [e for e in events if e.get("fit") == fit_span]
    mine.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    kinds = [e.get("ev") for e in mine]
    return {
        "fit": fit_span,
        "events": mine,
        "kinds": kinds,
        "complete": "fit_begin" in kinds and "fit_end" in kinds,
    }


def _fmt_event(e: dict, t0: float) -> str:
    extras = {k: v for k, v in e.items()
              if k not in ("ts", "seq", "ev", "rids", "trace_ids")}
    nrows = len(e.get("rids") or [])
    if nrows:
        extras["batch"] = nrows
    tail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
    return f"  +{e.get('ts', t0) - t0:9.3f}s  {e.get('ev', '?'):14s} {tail}"


def print_request(report: dict) -> None:
    print(f"request trace_id={report['trace_id']} rid={report['rid']} "
          f"complete={report['complete']}")
    evs = report["events"]
    t0 = evs[0].get("ts", 0.0) if evs else 0.0
    for e in evs:
        print(_fmt_event(e, t0))


def print_fit(report: dict) -> None:
    print(f"fit span={report['fit']} complete={report['complete']} "
          f"({len(report['events'])} events)")
    evs = report["events"]
    t0 = evs[0].get("ts", 0.0) if evs else 0.0
    for e in evs:
        print(_fmt_event(e, t0))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="reconstruct request/fit timelines from telemetry "
                    "journals")
    ap.add_argument("journals", nargs="+", help="JSONL journal file(s)")
    ap.add_argument("--trace-id", default=None,
                    help="report this request's pipeline timeline")
    ap.add_argument("--fit", default=None,
                    help="fit span id to report ('latest' for the "
                         "newest fit in the journal)")
    args = ap.parse_args(argv)
    events = load_events(args.journals)
    print(f"{len(events)} events from {len(args.journals)} journal(s)")
    did = False
    if args.trace_id:
        print_request(request_timeline(events, args.trace_id))
        did = True
    if args.fit:
        span = None if args.fit == "latest" else args.fit
        print_fit(fit_timeline(events, span))
        did = True
    if not did:
        # no selector: summarize what's in there
        kinds: Dict[str, int] = {}
        for e in events:
            kinds[e.get("ev", "?")] = kinds.get(e.get("ev", "?"), 0) + 1
        print("event counts:", json.dumps(kinds, sort_keys=True))
        fits = list_fits(events)
        print(f"fits: {fits}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
