"""Trace-report reader: reconstruct per-request and per-fit timelines
from :class:`mmlspark_tpu.core.telemetry.EventJournal` JSONL dumps
(ISSUE 5), including CROSS-PROCESS timelines merged from driver and
worker journals (ISSUE 8).

The serving engine journals per-BATCH pipeline events
(``form``/``decode``/``score``/``reply``, plus
``shed``/``expired``/``salvage``) carrying the batch's request ids and
trace ids; the transport journals per-hop spans (``hop_enqueue`` /
``hop_send`` / ``hop_ack`` sender-side, ``hop_deliver`` with the
send→recv clock offset receiver-side, a ``retrans`` flag on replayed
sends); the multiprocess serving worker journals ``request_recv`` /
``request_reply`` where the client socket lives; the training engine
journals per-FIT events (``fit_begin``, ``boost_chunk``,
``ckpt_saved``/``ckpt_resumed``/``ckpt_discarded``,
``chunk_replayed``, ``peer_stalled``/``peer_lost``, ``fit_end``)
stamped with a fit span id.  This tool stitches any of it back into a
timeline:

* :func:`request_timeline` — given a trace id (the client's
  ``_trace_id`` payload key, or the request id minted at admission),
  find the request's events across every journal handed in and order
  them: a complete scored request on the multiprocess topology shows
  ``request_recv → hop_enqueue/hop_send → hop_deliver → form → decode
  → score → reply → hop_enqueue/hop_send → hop_deliver →
  request_reply`` spanning both processes (``cross_process`` reports
  how many pids contributed).
* :func:`fit_timeline` — given a fit span id (or the newest fit in the
  journal), order everything stamped with it.

CLI::

    python tools/trace_report.py JOURNAL.jsonl [more.jsonl ...] \
        [--trace-id TID] [--fit SPAN | --fit latest] \
        [--format text|json]

``--format json`` (ISSUE 12 satellite) emits ONE machine-readable
document in the stable ``mmlspark_tpu.trace_timeline/v1`` schema (see
:func:`timeline_report`) — the shape ``tools/perf_report.py`` consumes
to put a per-hop cost breakdown under every timeline.

Multiple journal files (e.g. the driver's plus each worker's
``MMLSPARK_TPU_JOURNAL_DIR`` mirror, or one per controller of a gang)
are merged and ordered by ``(ts, seq)`` — ``seq`` is
process-monotonic, ``ts`` is wall clock, so cross-process order is as
honest as the hosts' clocks (the ``hop_deliver`` ``offset_ms`` field
carries the measured send→recv skew for exactly that reason).
"""

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the serving pipeline stages a fully-served request passes through
REQUEST_STAGES = ("form", "decode", "score", "reply")

#: per-hop transport span events (single ``tid`` field, not the batch
#: ``trace_ids`` list)
HOP_EVENTS = ("hop_enqueue", "hop_send", "hop_ack", "hop_deliver")

#: worker-process bookend events of a multiprocess request
WORKER_EVENTS = ("request_recv", "request_reply")


def load_events(paths) -> List[dict]:
    """Load and merge one or more JSONL journals (or pass event dicts
    through), ordered by ``(ts, seq)``."""
    from mmlspark_tpu.core.telemetry import read_journal
    events: List[dict] = []
    for p in ([paths] if isinstance(paths, str) else list(paths)):
        if isinstance(p, dict):
            events.append(p)
        else:
            events.extend(read_journal(p))
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return events


def _resolve_rid(events: Iterable[dict], trace_id: str) -> str:
    """Map a trace id to its request id via any batch event that
    carries both aligned lists (or a worker bookend event carrying
    both scalar fields); a trace id that never appears is assumed to
    BE the rid (the minted-at-admission default, where the two are the
    same string)."""
    for e in events:
        tids = e.get("trace_ids") or []
        if trace_id in tids:
            rids = e.get("rids") or []
            i = tids.index(trace_id)
            if i < len(rids):
                return str(rids[i])
        if e.get("tid") == trace_id and e.get("rid"):
            return str(e["rid"])
    return trace_id


def request_timeline(events: Iterable[dict], trace_id: str) -> dict:
    """Reconstruct one request's pipeline timeline across every
    journal handed in (driver + workers).

    Returns ``{"trace_id", "rid", "events": [...], "stages": [...],
    "hops": [...], "pids": [...], "cross_process": bool,
    "complete": bool}`` — ``complete`` means the full
    form→decode→score→reply chain was observed (a shed/expired request
    is legitimately incomplete and shows its degradation event
    instead); ``hops`` is the subset of per-hop transport spans,
    ``retransmits`` counts replayed sends among them, and
    ``cross_process`` is True when more than one pid contributed
    events — the stitched driver+worker view."""
    events = list(events)
    rid = _resolve_rid(events, trace_id)
    ids = {trace_id, rid}
    mine: List[dict] = []
    for e in events:
        if ids & set(e.get("rids") or []) \
                or ids & set(e.get("trace_ids") or []) \
                or e.get("tid") in ids or e.get("rid") in ids:
            mine.append(e)
    mine.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    stages = [e.get("ev") for e in mine]
    hops = [e for e in mine if e.get("ev") in HOP_EVENTS]
    pids = sorted({e["pid"] for e in mine if e.get("pid") is not None})
    return {
        "trace_id": trace_id,
        "rid": rid,
        "events": mine,
        "stages": stages,
        "hops": hops,
        "retransmits": sum(1 for e in hops if e.get("retrans")),
        "pids": pids,
        "cross_process": len(pids) > 1,
        "complete": all(s in stages for s in REQUEST_STAGES),
    }


def list_fits(events: Iterable[dict]) -> List[str]:
    """Fit span ids in first-seen order."""
    out: List[str] = []
    for e in events:
        span = e.get("fit")
        if span and span not in out:
            out.append(span)
    return out


def fit_timeline(events: Iterable[dict],
                 fit_span: Optional[str] = None) -> dict:
    """Reconstruct one fit's timeline (``fit_span=None`` picks the
    NEWEST fit that has a ``fit_begin`` — the one a post-mortem usually
    wants).  ``complete`` means both ``fit_begin`` and ``fit_end`` were
    observed; a crashed fit shows ``fit_failed`` or simply no end."""
    events = list(events)
    if fit_span is None:
        begins = [e.get("fit") for e in events
                  if e.get("ev") == "fit_begin" and e.get("fit")]
        fit_span = begins[-1] if begins else None
    mine = [e for e in events if e.get("fit") == fit_span]
    mine.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    kinds = [e.get("ev") for e in mine]
    return {
        "fit": fit_span,
        "events": mine,
        "kinds": kinds,
        "complete": "fit_begin" in kinds and "fit_end" in kinds,
    }


#: machine-readable schema tag; bump the suffix on ANY key change —
#: perf_report and external consumers key off it
TIMELINE_SCHEMA = "mmlspark_tpu.trace_timeline/v1"


def timeline_report(events, trace_id: Optional[str] = None,
                    fit: Optional[str] = None) -> dict:
    """The stable machine-readable timeline document (``--format
    json``).  Keys are FIXED for the schema version:

    * ``schema`` — :data:`TIMELINE_SCHEMA`.
    * ``events_total`` — merged event count across the journals.
    * ``event_counts`` — ``{ev: count}`` over every merged event.
    * ``fits`` — fit span ids in first-seen order.
    * ``request`` — :func:`request_timeline` output for ``trace_id``
      (``null`` when no trace id was asked for).
    * ``fit`` — :func:`fit_timeline` output (``null`` unless asked;
      ``fit="latest"`` picks the newest ``fit_begin``).

    Every value is JSON-native (the journal records already are), so
    ``json.loads(json.dumps(report)) == report`` — the round-trip the
    tier-1 schema test pins."""
    events = list(events)
    kinds: Dict[str, int] = {}
    for e in events:
        kinds[e.get("ev", "?")] = kinds.get(e.get("ev", "?"), 0) + 1
    return {
        "schema": TIMELINE_SCHEMA,
        "events_total": len(events),
        "event_counts": kinds,
        "fits": list_fits(events),
        "request": (request_timeline(events, trace_id)
                    if trace_id else None),
        "fit": (fit_timeline(events, None if fit == "latest" else fit)
                if fit else None),
    }


def _fmt_event(e: dict, t0: float) -> str:
    extras = {k: v for k, v in e.items()
              if k not in ("ts", "seq", "ev", "rids", "trace_ids",
                           "pid")}
    nrows = len(e.get("rids") or [])
    if nrows:
        extras["batch"] = nrows
    tail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
    pid = f"[{e['pid']:>7}] " if e.get("pid") is not None else ""
    return (f"  +{e.get('ts', t0) - t0:9.3f}s  {pid}"
            f"{e.get('ev', '?'):14s} {tail}")


def print_request(report: dict) -> None:
    print(f"request trace_id={report['trace_id']} rid={report['rid']} "
          f"complete={report['complete']} "
          f"cross_process={report.get('cross_process', False)} "
          f"hops={len(report.get('hops') or [])} "
          f"retransmits={report.get('retransmits', 0)}")
    evs = report["events"]
    t0 = evs[0].get("ts", 0.0) if evs else 0.0
    for e in evs:
        print(_fmt_event(e, t0))


def print_fit(report: dict) -> None:
    print(f"fit span={report['fit']} complete={report['complete']} "
          f"({len(report['events'])} events)")
    evs = report["events"]
    t0 = evs[0].get("ts", 0.0) if evs else 0.0
    for e in evs:
        print(_fmt_event(e, t0))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="reconstruct request/fit timelines from telemetry "
                    "journals")
    ap.add_argument("journals", nargs="+", help="JSONL journal file(s)")
    ap.add_argument("--trace-id", default=None,
                    help="report this request's pipeline timeline")
    ap.add_argument("--fit", default=None,
                    help="fit span id to report ('latest' for the "
                         "newest fit in the journal)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text",
                    help="json: one stable machine-readable timeline "
                         "document (mmlspark_tpu.trace_timeline/v1)")
    args = ap.parse_args(argv)
    events = load_events(args.journals)
    if args.format == "json":
        print(json.dumps(timeline_report(events, args.trace_id,
                                         args.fit),
                         sort_keys=True))
        return 0
    print(f"{len(events)} events from {len(args.journals)} journal(s)")
    did = False
    if args.trace_id:
        print_request(request_timeline(events, args.trace_id))
        did = True
    if args.fit:
        span = None if args.fit == "latest" else args.fit
        print_fit(fit_timeline(events, span))
        did = True
    if not did:
        # no selector: summarize what's in there
        kinds: Dict[str, int] = {}
        for e in events:
            kinds[e.get("ev", "?")] = kinds.get(e.get("ev", "?"), 0) + 1
        print("event counts:", json.dumps(kinds, sort_keys=True))
        fits = list_fits(events)
        print(f"fits: {fits}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
