"""Measure the scaling-model collectives on the 8-virtual-device host mesh.

docs/scaling.md predicts bytes-per-split for each mesh layout; this tool
MEASURES the same collectives (VERDICT r4 next #9) two ways:

* **bytes on the wire** — read from the compiled HLO's all-reduce /
  all-gather operands, so the table's `bytes per split` column is checked
  against what XLA actually schedules, not just arithmetic;
* **wall time per collective** — the in-program slope method from
  tools/sweep_histogram.py ((t(R reps) − t(1 rep)) / (R−1), min over
  repeated endpoints) so dispatch overhead cancels.

Host-mesh caveat, stated on every row: the 8 "devices" are CPU threads
sharing one memory system — collectives are memcpy-speed, so wall times
validate SCALING (payload-linearity, layout ratios), not ICI latency.
Run with:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python tools/measure_collectives.py
"""

import json
import os
import sys
import time

# CPU platform via the LIVE-CONFIG path, before backends initialize:
# in this image the JAX_PLATFORMS env-var route hangs backend init
# (see __graft_entry__._bootstrap_cpu_devices), while config.update
# works because sitecustomize imports jax without instantiating
# backends.  Order matters: config first, then anything that may
# trigger initialization.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:  # older jax: pre-init XLA flag fallback
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

if jax.default_backend() != "cpu":
    sys.exit("measure_collectives must run on the CPU host mesh")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402
from jax.experimental.shard_map import shard_map  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mmlspark_tpu.core.mesh import DATA_AXIS, FEATURE_AXIS  # noqa: E402

B, K3 = 256, 3
D = 8
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "artifacts", "collectives_hostmesh.json")


def slope_us(fn, arg, reps=17, runs=3):
    """In-program per-op cost: scan the op R times vs once, diff mins."""
    p1 = jax.jit(lambda a: jax.lax.scan(
        lambda c, _: (fn(c), None), a, None, length=1)[0])
    pR = jax.jit(lambda a: jax.lax.scan(
        lambda c, _: (fn(c), None), a, None, length=reps)[0])
    jax.block_until_ready(p1(arg))
    jax.block_until_ready(pR(arg))
    t1 = _time(p1, arg, runs)
    tR = _time(pR, arg, runs)
    return max(tR - t1, 0.0) / (reps - 1) * 1e6


def _time(p, arg, runs):
    best = np.inf
    for _ in range(runs):
        t0 = time.perf_counter()
        jax.block_until_ready(p(arg))
        best = min(best, time.perf_counter() - t0)
    return best


def hlo_allreduce_bytes(fn, arg):
    """Sum of all-reduce/all-gather RESULT bytes in the compiled HLO.

    Line-based: only instructions whose opcode (right of `=`) is a
    collective count, and only their result shape — matching the free
    `all-reduce` substring anywhere would also hit the instruction NAME
    and double-count every collective."""
    import re
    txt = jax.jit(fn).lower(arg).compile().as_text()
    total = 0
    for line in txt.splitlines():
        if "=" not in line:
            continue
        rhs = line.split("=", 1)[1].lstrip()
        m = re.match(r"f32\[([\d,]*)\][^ ]* (all-reduce|all-gather)\(",
                     rhs)
        if not m:
            continue
        n = 1
        for d in m.group(1).split(","):
            if d:
                n *= int(d)
        total += 4 * n
    return total


def main():
    devs = np.asarray(jax.devices()[:D])
    mesh = Mesh(devs.reshape(D, 1), (DATA_AXIS, FEATURE_AXIS))
    rows = []

    for f, label in ((39, "Criteo-shape f=39"), (4096, "wide f=4096")):
        hist = jax.device_put(
            jnp.ones((D, f, B, K3), jnp.float32),
            NamedSharding(mesh, P(DATA_AXIS)))

        def psum_hist(h):
            # carry-type-preserving for lax.scan: every shard keeps the
            # reduced block at its own slot (out spec = in spec)
            return shard_map(
                lambda x: jax.lax.psum(x, DATA_AXIS),
                mesh=mesh, in_specs=P(DATA_AXIS),
                out_specs=P(DATA_AXIS))(h)

        us = slope_us(psum_hist, hist)
        measured_b = hlo_allreduce_bytes(psum_hist, hist)
        rows.append({"layout": "data", "shape": label,
                     "predicted_bytes": 12 * f * B,
                     "hlo_allreduce_bytes": measured_b,
                     "wall_us_per_split": round(us, 1)})

    # voting: psum of <= 2k candidate histograms only
    k = 20
    cand = jax.device_put(jnp.ones((D, 2 * k, B, K3), jnp.float32),
                          NamedSharding(mesh, P(DATA_AXIS)))

    def psum_vote(h):
        return shard_map(lambda x: jax.lax.psum(x, DATA_AXIS),
                         mesh=mesh, in_specs=P(DATA_AXIS),
                         out_specs=P(DATA_AXIS))(h)

    rows.append({"layout": "voting k=20", "shape": "any f",
                 "predicted_bytes": 12 * 2 * k * B,
                 "hlo_allreduce_bytes": hlo_allreduce_bytes(psum_vote, cand),
                 "wall_us_per_split": round(slope_us(psum_vote, cand), 1)})

    # feature layout: owner broadcasts ONE split column of n rows (psum
    # of a one-hot-owner column == the owner-broadcast the grower uses)
    n = 400_000
    col = jax.device_put(jnp.ones((D, n // D), jnp.float32),
                         NamedSharding(mesh, P(DATA_AXIS)))

    def bcast_col(c):
        # gather the full column, keep the local slice (type-preserving)
        def body(x):
            g = jax.lax.all_gather(x, DATA_AXIS, tiled=True)
            i = jax.lax.axis_index(DATA_AXIS)
            return jax.lax.dynamic_slice_in_dim(
                g, i * x.shape[0], x.shape[0])
        return shard_map(body, mesh=mesh, in_specs=P(DATA_AXIS),
                         out_specs=P(DATA_AXIS))(c)

    rows.append({"layout": "feature (column broadcast)", "shape": "n=400k",
                 "predicted_bytes": 4 * n,
                 "hlo_allreduce_bytes": hlo_allreduce_bytes(bcast_col, col),
                 "wall_us_per_split": round(slope_us(bcast_col, col), 1)})

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as fh:
        json.dump({"device_count": D, "backend": jax.default_backend(),
                   "rows": rows}, fh, indent=1)
    for r in rows:
        print(f"{r['layout']:28s} {r['shape']:18s} "
              f"predicted {r['predicted_bytes']:>10,d} B  "
              f"HLO {r['hlo_allreduce_bytes']:>10,d} B  "
              f"{r['wall_us_per_split']:>8.1f} us/split")
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
