"""Drift chaos drill (ISSUE 15 acceptance artifact): prove the
streaming data-quality subsystem's contract end to end —

A. **clean_traffic** — on-distribution traffic through a real
   :class:`ScoringEngine` + :class:`DriftMonitor` raises NO drift
   alert, NO ``drift_onset`` journal event and NO drift-SLO breach:
   zero false alarms is as much the contract as detection.
B. **feature_shift** — a seeded :class:`ChaosDrift` shifts one feature
   column mid-traffic (upstream recalibration); the monitor flags the
   INJECTED feature within the drill's traffic window (detection
   latency recorded in rows), the ``feature_drift`` SLO burns to a
   breach, a ``drift_onset`` journal event + flight record land, and
   ``tools/drift_report.py`` names the injected feature as the top
   drifter off the monitor's merged counters.
C. **nan_storm** — the same feature goes 80% NaN mid-traffic (silent
   upstream null-out); detected through the null-rate delta / missing
   distribution slot with the same evidence chain.
D. **canary_drift_rollback** — a live :class:`RolloutController`
   canary soaks while the INPUT feed starts drifting; the new
   ``canary_live_drift`` objective (attached drift monitor) trips the
   gate and the canary is auto-rolled-back — no human, no error burn,
   drift alone.

All injection is seeded (:class:`ChaosPlan`): same seed, same fault
schedule.  Each scenario embeds its verdicts, the drift report, the
SLO verdicts and a journal excerpt; scenario B additionally embeds the
monitor's raw merged counters so ``drift_report.py --artifact`` can
re-render the table from the committed file alone.

Run: ``python tools/chaos_drift.py --out artifacts/chaos_drift_r15.json``
(~30 s wall on a 2-core CPU box).
"""

import argparse
import json
import os
import queue
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import drift_report  # noqa: E402  (tools/ sibling, not a package)

SCHEMA = "mmlspark_tpu.chaos_drift/v1"


def verdict(ledger, name, ok, detail=""):
    ledger.append({"name": name, "pass": bool(ok), "detail": detail})
    print(f"  [{'PASS' if ok else 'FAIL'}] {name}"
          + (f" — {detail}" if detail else ""))


def journal_excerpt(since_seq, keep=("drift_onset", "drift_recovered",
                                     "slo_burn", "slo_recovered",
                                     "rollout_rolled_back",
                                     "rollout_started"),
                    max_events=40):
    from mmlspark_tpu.core.telemetry import get_journal
    return [e for e in get_journal().events()
            if e["ev"] in keep and e["seq"] > since_seq][-max_events:]


def journal_seq():
    from mmlspark_tpu.core.telemetry import get_journal
    evs = get_journal().events()
    return evs[-1]["seq"] if evs else 0


class _QueueServer:
    """Minimal in-process exchange (the engine's documented queue
    contract): requests park on ``request_queue``, replies land in a
    dict — the drill drives the REAL engine hot path without sockets."""

    def __init__(self):
        self.request_queue = queue.Queue()
        self.replies = {}

    def reply(self, rid, body, status=200):
        self.replies[rid] = (body, status)


def build_model(seed):
    import numpy as np
    from mmlspark_tpu.gbdt import LightGBMRegressor
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(2000, 8)).astype(np.float32)
    y = (X[:, 0] + 0.6 * X[:, 1] * X[:, 2]
         - 0.3 * X[:, 3]).astype(np.float64)
    booster = LightGBMRegressor(
        numIterations=10, numLeaves=15, parallelism="serial",
        verbosity=0).fit({"features": X, "label": y}).getModel()
    assert booster.reference_profile is not None, \
        "fit did not capture a reference profile"
    return X, y, booster


def fresh_monitor(profile):
    from mmlspark_tpu.core.drift import DriftConfig, DriftMonitor
    # duty=1.0: the drill wants every batch sketched (determinism);
    # production keeps the 2% duty gate — the perf sentinel A/Bs it
    return DriftMonitor(profile, DriftConfig(
        duty=1.0, eval_interval_s=0.02, min_rows=200))


def pump(server, engine_rows, X_rows, tag):
    """Push rows as payloads and wait for every reply."""
    want = len(X_rows)
    for i, row in enumerate(X_rows):
        # rid unique across pumps (engine_rows is the running total)
        server.request_queue.put(
            (f"{tag}{engine_rows + i}",
             {"features": [float(v) for v in row]}))
    t0 = time.time()
    while len(server.replies) < engine_rows + want:
        if time.time() - t0 > 30:
            raise RuntimeError(
                f"pump timeout: {len(server.replies)} replies, want "
                f"{engine_rows + want}")
        time.sleep(0.005)
    return engine_rows + want


def slo_breach_probe(drift_mon, samples=10):
    """Deterministic burn-gate evaluation over a synthetic timeline:
    one private SLOMonitor over the stock drift objectives, reading a
    private registry that carries the live drift monitor's gauges,
    sampled at fixed fake timestamps.  Returns the
    feature/prediction-drift verdict dict."""
    from mmlspark_tpu.core.slo import SLOMonitor, default_objectives
    from mmlspark_tpu.core.telemetry import MetricsRegistry
    reg = MetricsRegistry()
    reg.register("drift", drift_mon)
    objs = [o for o in default_objectives()
            if o.name in ("feature_drift", "prediction_drift")]
    mon = SLOMonitor(objs, registry=reg,
                     fast_window_s=3.0, slow_window_s=6.0)
    for i in range(samples):
        mon.sample(now=float(i))
    return mon.evaluate()


def scenario_clean(art, X, booster, seed):
    print("== A. clean_traffic ==")
    import numpy as np
    from mmlspark_tpu.core.drift import set_drift_monitor
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    ledger = []
    seq0 = journal_seq()
    rng = np.random.default_rng(seed + 1)
    server = _QueueServer()
    mon = fresh_monitor(booster.reference_profile)
    eng = ScoringEngine(server, predictor=booster.predictor(
        backend="auto"), plan=ColumnPlan("features", X.shape[1]),
        max_rows=64, latency_budget_ms=2.0, num_scorers=1,
        num_repliers=0, drift_monitor=mon).start()
    try:
        rows = 0
        for _ in range(8):
            batch = X[rng.integers(0, len(X), 200)]
            rows = pump(server, rows, batch, "c")
    finally:
        eng.stop()
        set_drift_monitor(None)
    report = mon.report()
    verdicts = slo_breach_probe(mon)
    evs = journal_excerpt(seq0, keep=("drift_onset",))
    verdict(ledger, "rows_sketched", report["rows_observed"] >= 1000,
            f"{report['rows_observed']} rows observed")
    verdict(ledger, "no_alert", not report["alerting"],
            f"alerting={report['alerting']}")
    verdict(ledger, "no_drift_onset_event", not evs,
            f"{len(evs)} drift_onset events")
    verdict(ledger, "no_slo_breach",
            not any(v["breach"] for v in verdicts.values()),
            json.dumps({k: v["breach"] for k, v in verdicts.items()}))
    art["scenarios"]["clean_traffic"] = {
        "verdicts": ledger,
        "drift_gauges": report["gauges"],
        "slo": {k: v["breach"] for k, v in verdicts.items()},
        "journal": journal_excerpt(seq0),
    }
    return ledger


def _run_injected(X, booster, seed, drift_kwargs, tag):
    """Shared B/C body: clean warmup, then injected traffic; returns
    (monitor, detection dict, ledger-ready evidence)."""
    import numpy as np
    from mmlspark_tpu.core.drift import set_drift_monitor
    from mmlspark_tpu.io.chaos import ChaosDrift, ChaosPlan
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    rng = np.random.default_rng(seed + 2)
    plan = ChaosPlan(seed)
    drift = ChaosDrift(plan, after_rows=0, name=f"{tag}_inject",
                       **drift_kwargs)
    server = _QueueServer()
    mon = fresh_monitor(booster.reference_profile)
    eng = ScoringEngine(server, predictor=booster.predictor(
        backend="auto"), plan=ColumnPlan("features", X.shape[1]),
        max_rows=64, latency_budget_ms=2.0, num_scorers=1,
        num_repliers=0, drift_monitor=mon).start()
    detection_rows = None
    try:
        rows = 0
        # clean warmup: the live sketch must hold enough
        # on-distribution mass that detection is a real distribution
        # test, not an empty-sketch artifact
        for _ in range(5):
            batch = X[rng.integers(0, len(X), 200)]
            rows = pump(server, rows, batch, f"{tag}w")
        assert not mon.report()["alerting"], \
            "false alarm during warmup"
        injected = 0
        for i in range(40):
            batch = drift(X[rng.integers(0, len(X), 200)])
            rows = pump(server, rows, batch, f"{tag}i{i}_")
            injected += len(batch)
            if mon.report()["alerting"]:
                detection_rows = injected
                break
    finally:
        eng.stop()
        set_drift_monitor(None)
    return mon, drift, plan, detection_rows


def scenario_shift(art, X, booster, seed):
    print("== B. feature_shift ==")
    ledger = []
    seq0 = journal_seq()
    feat = 2
    mon, drift, plan, det = _run_injected(
        X, booster, seed, {"feature": feat, "shift": 3.0}, "s")
    report = mon.report()
    verdicts = slo_breach_probe(mon)
    evs = journal_excerpt(seq0, keep=("drift_onset",))
    counters = mon.snapshot()["counters"]
    rep = drift_report.build_report(booster.reference_profile,
                                    counters)
    text = drift_report.render_text(rep, top=5)
    print(text)
    verdict(ledger, "detected_in_window", det is not None,
            f"detection after {det} injected rows "
            f"({drift.rows_injected} injected total)")
    verdict(ledger, "injected_feature_flagged",
            f"f{feat}" in report["alerting"],
            f"alerting={report['alerting']}")
    verdict(ledger, "drift_onset_journaled",
            any(e.get("signal") == f"f{feat}" for e in evs),
            f"{len(evs)} drift_onset events")
    verdict(ledger, "feature_drift_slo_breach",
            verdicts["feature_drift"]["breach"],
            f"burn_fast={verdicts['feature_drift']['burn_rate_fast']}")
    verdict(ledger, "report_names_injected_top",
            rep["worst_feature"] == f"f{feat}",
            f"top drifter {rep['worst_feature']}")
    art["scenarios"]["feature_shift"] = {
        "verdicts": ledger,
        "injected_feature": f"f{feat}",
        "detection_rows": det,
        "injections": plan.counts(),
        "drift_gauges": report["gauges"],
        "drift_counters": counters,
        "report_text": text,
        "slo": {k: {kk: v[kk] for kk in
                    ("breach", "burn_rate_fast", "burn_rate_slow")}
                for k, v in verdicts.items()},
        "journal": journal_excerpt(seq0),
    }
    return ledger


def scenario_nan(art, X, booster, seed):
    print("== C. nan_storm ==")
    ledger = []
    seq0 = journal_seq()
    feat = 4
    mon, drift, plan, det = _run_injected(
        X, booster, seed, {"feature": feat, "nan_rate": 0.8}, "n")
    report = mon.report()
    sig = next(s for s in report["signals"]
               if s["signal"] == f"f{feat}")
    verdicts = slo_breach_probe(mon)
    evs = journal_excerpt(seq0, keep=("drift_onset",))
    verdict(ledger, "detected_in_window", det is not None,
            f"detection after {det} injected rows "
            f"({drift.nans_injected} NaNs injected)")
    verdict(ledger, "null_delta_flagged",
            sig["null_delta"] > mon.cfg.null_delta_threshold,
            f"null live={sig['null_rate_live']} vs "
            f"ref={sig['null_rate_ref']}")
    verdict(ledger, "drift_onset_journaled",
            any(e.get("signal") == f"f{feat}" for e in evs),
            f"{len(evs)} drift_onset events")
    verdict(ledger, "feature_drift_slo_breach",
            verdicts["feature_drift"]["breach"], "")
    art["scenarios"]["nan_storm"] = {
        "verdicts": ledger,
        "injected_feature": f"f{feat}",
        "detection_rows": det,
        "nans_injected": drift.nans_injected,
        "injections": plan.counts(),
        "drift_gauges": report["gauges"],
        "signal": sig,
        "slo": {k: v["breach"] for k, v in verdicts.items()},
        "journal": journal_excerpt(seq0),
    }
    return ledger


def scenario_canary(art, X, y, booster, seed, tmpdir):
    print("== D. canary_drift_rollback ==")
    import numpy as np
    from mmlspark_tpu.core.drift import set_drift_monitor
    from mmlspark_tpu.gbdt import LightGBMRegressor
    from mmlspark_tpu.io.chaos import ChaosDrift, ChaosPlan
    from mmlspark_tpu.io.registry import ModelRegistry
    from mmlspark_tpu.io.rollout import RolloutConfig, RolloutController
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    ledger = []
    seq0 = journal_seq()
    rng = np.random.default_rng(seed + 3)
    plan = ChaosPlan(seed)
    registry = ModelRegistry(os.path.join(tmpdir, "registry"))
    registry.publish(booster, activate=True)
    b2 = LightGBMRegressor(numIterations=14, numLeaves=15,
                           parallelism="serial", verbosity=0).fit(
        {"features": X, "label": y}).getModel()
    v2 = registry.publish(b2)
    cfg = RolloutConfig(canary_fraction=0.3, soak_s=60.0,
                        min_canary_rows=100000,
                        canary_deadline_ms=None,
                        fast_window_s=1.0, slow_window_s=2.0,
                        live_drift_threshold=0.25)
    ctl = RolloutController(registry, backend="auto", config=cfg)
    mon = fresh_monitor(booster.reference_profile)
    ctl.attach_drift(mon)
    server = _QueueServer()
    eng = ScoringEngine(server, predictor=ctl,
                        plan=ColumnPlan("features", X.shape[1]),
                        max_rows=64, latency_budget_ms=2.0,
                        num_scorers=1, num_repliers=0,
                        drift_monitor=mon).start()
    drift = ChaosDrift(plan, feature=1, shift=4.0, after_rows=0,
                       name="canary_inject")
    state = "soaking"
    clean_state = None
    try:
        rows = 0
        # clean soak first: the gate must hold a healthy canary
        ctl.start_canary(v2)
        for _ in range(6):
            batch = X[rng.integers(0, len(X), 150)]
            rows = pump(server, rows, batch, "dcl")
            clean_state = ctl.tick()
            time.sleep(0.15)
        held_clean = clean_state == "soaking"
        # then the feed starts drifting under the soaking canary
        for i in range(40):
            batch = drift(X[rng.integers(0, len(X), 150)])
            rows = pump(server, rows, batch, f"ddr{i}_")
            state = ctl.tick()
            time.sleep(0.1)
            if state == "rolled_back":
                break
    finally:
        eng.stop()
        set_drift_monitor(None)
    evs = journal_excerpt(seq0, keep=("rollout_rolled_back",))
    reason = evs[-1].get("reason", "") if evs else ""
    verdict(ledger, "clean_canary_held", held_clean,
            f"state after clean soak: {clean_state}")
    verdict(ledger, "auto_rolled_back", state == "rolled_back",
            f"final state {state}")
    verdict(ledger, "rolled_back_by_drift_objective",
            "canary_live_drift" in reason
            or "canary_prediction_drift" in reason,
            f"reason={reason!r}")
    verdict(ledger, "registry_marked_rolled_back",
            registry.entry(v2)["promoted_state"] == "rolled_back",
            registry.entry(v2)["promoted_state"])
    verdict(ledger, "baseline_still_active",
            registry.active_version() == 1,
            f"active={registry.active_version()}")
    art["scenarios"]["canary_drift_rollback"] = {
        "verdicts": ledger,
        "rollback_reason": reason,
        "drift_gauges": mon.report()["gauges"],
        "injections": plan.counts(),
        "journal": journal_excerpt(seq0),
    }
    return ledger


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/chaos_drift_r15.json")
    ap.add_argument("--seed", type=int, default=15)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    from mmlspark_tpu.core.telemetry import host_info
    t0 = time.time()
    X, y, booster = build_model(args.seed)
    art = {"schema": SCHEMA, "seed": args.seed, "host": host_info(),
           "profile": json.loads(
               booster.reference_profile.to_json()),
           "scenarios": {}}
    ledgers = []
    with tempfile.TemporaryDirectory() as tmpdir:
        # drift onsets / rollbacks dump flight records — into the
        # drill's scratch dir, not the committed artifacts/ tree
        from mmlspark_tpu.core.telemetry import configure_flight_recorder
        configure_flight_recorder(directory=tmpdir)
        ledgers += scenario_clean(art, X, booster, args.seed)
        ledgers += scenario_shift(art, X, booster, args.seed)
        ledgers += scenario_nan(art, X, booster, args.seed)
        ledgers += scenario_canary(art, X, y, booster, args.seed,
                                   tmpdir)
    art["verdicts_total"] = len(ledgers)
    art["verdicts_pass"] = sum(1 for v in ledgers if v["pass"])
    art["healthy"] = art["verdicts_pass"] == art["verdicts_total"]
    art["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(art, fh, indent=1)
    print(f"\n{art['verdicts_pass']}/{art['verdicts_total']} verdicts "
          f"pass in {art['wall_s']}s -> {args.out}")
    return 0 if art["healthy"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
