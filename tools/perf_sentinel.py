"""Perf-regression sentinel (ISSUE 12): run the micro/serving bench
stages, compare against committed baselines with noise-aware
thresholds, and fail CI when a stage regressed.

The committed BENCH/``artifacts/bench_serving_*`` numbers were, until
now, only ever re-checked by a human re-running the full bench.  This
sentinel is the automated guard:

* **Stages** — fast (seconds-each) re-measurements of the hot paths
  the benches commit: the per-row JSON and binary wire codecs
  (identical methodology to ``bench_serving``'s ``codec_micro``), a
  closed-loop scoring-engine burst (client-observed p50), a tiny
  training fit (ms/tree), and the quantized histogram build at the
  ``bench_quant`` pin (ISSUE 17's low-bit hot path).  Every stage runs
  ``--k`` times and the MEDIAN is compared — a single descheduled run
  cannot fire the alarm.
* **Noise-aware thresholds** — a stage regresses only when the median
  exceeds the baseline by BOTH the relative factor (``--rel``,
  default 1.8x) and an absolute floor (per-unit: µs-scale stages need
  µs of real slowdown, not scheduler jitter).  A 2x real slowdown
  fires; machine-to-machine variance under ~80% does not.
* **Baselines** — a prior sentinel artifact (``--baseline``), or a
  committed ``bench_serving_r*.json`` (its ``codec_micro`` block maps
  onto the codec stages).  ``--calibrate`` records a fresh baseline
  without gating — the first run on a new box.
* **Verdict plumbing** — each regression journals a
  ``perf_regression`` event, the worst stage-vs-baseline ratio is
  published as the ``ns="perf"`` gauge ``worst_regression_ratio``
  (read by the ``perf_latency_budget`` SLO objective in
  ``core/slo.py``), the artifact embeds the SLO report, and the
  process exits NONZERO — the CI hook.
* **Overhead A/Bs** — enabled-vs-disabled p50 deltas on the
  closed-loop burst for the always-on profiler, the drift-sketch
  pipeline (ISSUE 15) and the streaming-ingest tap (ISSUE 18),
  recorded in the artifact (acceptance: < 3% each).

Seeded-fault hook: ``MMLSPARK_TPU_PERF_SLOWDOWN="stage=factor[,..]"``
stretches the named stage's measured region by real wall-clock sleeps
(the detection path sees a genuine slowdown, not a doctored number) —
the tier-1 sentinel test injects ``2.0`` and asserts the alarm fires.

CLI::

    python tools/perf_sentinel.py --baseline artifacts/bench_serving_r12.json \
        [--out artifacts/perf_sentinel_r12.json] [--k 5] [--rel 1.6] \
        [--stages codec_json,codec_binary,scoring_engine,train_micro,quantized_hist] \
        [--calibrate] [--skip-overhead]
"""

import argparse
import json
import os
import queue
import statistics
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SCHEMA = "mmlspark_tpu.perf_sentinel/v1"
SLOWDOWN_ENV = "MMLSPARK_TPU_PERF_SLOWDOWN"

#: absolute regression floors per unit — below these, a delta is
#: scheduler noise no matter the ratio
UNIT_FLOORS = {"us": 3.0, "ms": 0.3}


def _slowdowns():
    """Parse the seeded-fault env: ``{"stage": factor}``."""
    out = {}
    raw = os.environ.get(SLOWDOWN_ENV, "")
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, factor = part.partition("=")
        try:
            out[name.strip()] = float(factor)
        except ValueError:
            continue
    return out


def _stretch(t0: float, stage: str) -> None:
    """Apply the seeded slowdown to a measured region that started at
    ``t0``: sleep the extra wall time a genuinely ``factor``-times
    slower stage would have taken.  No-op without the env hook."""
    factor = _slowdowns().get(stage, 1.0)
    if factor > 1.0:
        time.sleep((time.perf_counter() - t0) * (factor - 1.0))


# ---------------------------------------------------------------- stages


def stage_codec_json(args):
    """µs/row: JSON park-message encode+decode (the JSON wire's
    per-row codec bill; methodology identical to bench_serving's
    ``codec_micro``)."""
    import numpy as np
    row = np.random.default_rng(3).normal(
        size=args.codec_features).astype(np.float32)
    payload = {"features": row.tolist()}
    reps = args.codec_reps
    t0 = time.perf_counter()
    for _ in range(reps):
        json.loads(json.dumps({"op": "park", "rid": "r",
                               "payload": payload}))
    _stretch(t0, "codec_json")
    return (time.perf_counter() - t0) / reps * 1e6, "us"


def stage_codec_binary(args):
    """µs/row: raw-float32 pack+unpack (the binary wire codec)."""
    import numpy as np
    from mmlspark_tpu.io import wire
    row = np.random.default_rng(3).normal(
        size=args.codec_features).astype(np.float32).reshape(1, -1)
    reps = args.codec_reps
    t0 = time.perf_counter()
    for _ in range(reps):
        wire.unpack_matrix(wire.pack_matrix("r", row))
    _stretch(t0, "codec_binary")
    return (time.perf_counter() - t0) / reps * 1e6, "us"


class _BurstServer:
    """Minimal closed-loop exchange harness (the LoopServer shape):
    every reply immediately re-arms a request, keeping the engine
    saturated; client-observed latencies accumulate in ``lat``."""

    def __init__(self, X, outstanding):
        self.X = X
        self.request_queue = queue.Queue()
        self.lock = threading.Lock()
        self.lat = []
        self.t_sent = {}
        self.outstanding = outstanding
        self.n = 0

    def pump(self):
        for _ in range(self.outstanding):
            self.send()

    def send(self):
        with self.lock:
            rid = str(self.n)
            self.n += 1
            self.t_sent[rid] = time.perf_counter()
        self.request_queue.put(
            (rid, {"features": self.X[self.n % len(self.X)].tolist()}))

    def _account(self, rid, now):
        t0 = self.t_sent.pop(rid, None)
        if t0 is not None:
            self.lat.append(now - t0)

    def reply(self, rid, val, status=200):
        with self.lock:
            self._account(rid, time.perf_counter())
        self.send()
        return True

    def reply_many(self, entries):
        now = time.perf_counter()
        with self.lock:
            for rid, _v, _s in entries:
                self._account(rid, now)
        for _ in entries:
            self.send()
        return len(entries)


_MODEL_CACHE = {}


def _model(args):
    """Train the sentinel's small scoring model once per process."""
    if "booster" in _MODEL_CACHE:
        return _MODEL_CACHE["booster"], _MODEL_CACHE["X"]
    import numpy as np
    from mmlspark_tpu.gbdt import LightGBMRegressor
    rng = np.random.default_rng(0)
    X = rng.normal(size=(800, 16)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2]).astype(np.float64)
    b = LightGBMRegressor(numIterations=args.model_trees, numLeaves=31,
                          parallelism="serial", verbosity=0).fit(
        {"features": X, "label": y}).getModel()
    _MODEL_CACHE["booster"] = b
    _MODEL_CACHE["X"] = X
    return b, X


def scoring_burst_p50(args, duration=None, warm_s=0.4, drift=False,
                      ingest_tap=None):
    """One closed-loop burst through a real ScoringEngine; returns the
    client-observed p50 in ms.  Shared by the ``scoring_engine`` stage
    and the profiler/sketch/ingest overhead A/Bs (and the tier-1
    overhead tests).  ``drift=True`` attaches a production-configured
    DriftMonitor (ISSUE 15) so the A/B measures the sketch hot path
    exactly as deployed — duty-cycle gate included; ``ingest_tap``
    plugs a streaming-ingest tap (ISSUE 18) into the engine."""
    import numpy as np
    from mmlspark_tpu.io.scoring import ColumnPlan, ScoringEngine
    b, X = _model(args)
    srv = _BurstServer(X, args.outstanding)
    predictor = b.predictor(backend="auto")
    drift_monitor = None
    if drift:
        from mmlspark_tpu.core.drift import DriftMonitor
        assert b.reference_profile is not None, \
            "sentinel model fit captured no reference profile"
        drift_monitor = DriftMonitor(b.reference_profile)
    factor = _slowdowns().get("scoring_engine", 1.0)
    if factor > 1.0:
        # seeded fault: a genuinely slower scorer (every call pays the
        # extra wall time), so detection rides the normal path
        inner = predictor

        def predictor(Xm, _inner=inner, _f=factor):
            t0 = time.perf_counter()
            out = _inner(Xm)
            time.sleep((time.perf_counter() - t0) * (_f - 1.0))
            return out

    eng = ScoringEngine(srv, predictor=predictor,
                        plan=ColumnPlan("features", X.shape[1]),
                        max_rows=64, latency_budget_ms=2.0,
                        num_scorers=1, num_repliers=0,
                        drift_monitor=drift_monitor,
                        ingest_tap=ingest_tap).start()
    try:
        srv.pump()
        time.sleep(warm_s)
        with srv.lock:
            srv.lat.clear()
        time.sleep(duration if duration is not None
                   else args.burst_duration)
        with srv.lock:
            lat = list(srv.lat)
    finally:
        eng.stop()
        if drift_monitor is not None:
            from mmlspark_tpu.core.drift import set_drift_monitor
            set_drift_monitor(None)
    if not lat:
        return float("nan")
    return float(np.percentile(np.asarray(lat), 50) * 1e3)


def stage_scoring_engine(args):
    """ms: closed-loop scoring-engine p50 (the serving hot path)."""
    return scoring_burst_p50(args), "ms"


def stage_train_micro(args):
    """ms/tree: tiny serial fit (the training hot path; compile cache
    warm after the first rep, so the median measures the steady
    state)."""
    import numpy as np
    from mmlspark_tpu.gbdt import LightGBMRegressor
    rng = np.random.default_rng(1)
    X = rng.normal(size=(500, 12)).astype(np.float32)
    y = (X[:, 0] - X[:, 1]).astype(np.float64)
    t0 = time.perf_counter()
    LightGBMRegressor(numIterations=args.train_trees, numLeaves=15,
                      parallelism="serial", verbosity=0).fit(
        {"features": X, "label": y})
    _stretch(t0, "train_micro")
    return (time.perf_counter() - t0) / args.train_trees * 1e3, "ms"


_QHIST_CACHE = {}


def _qhist_setup():
    """Inputs + jitted quantized-histogram builder at the committed
    bench_quant pin, built once per process (compile and data-gen stay
    out of every timed region)."""
    if _QHIST_CACHE:
        return _QHIST_CACHE
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mmlspark_tpu.ops import histogram as H
    n, f, B, mc = 32768, 50, 256, 127
    rng = np.random.default_rng(3)
    bins = jnp.asarray(rng.integers(0, B, size=(n, f), dtype=np.uint8))
    codes = rng.integers(-mc, mc + 1, size=(n, 2))
    gh = jnp.asarray(np.concatenate([codes, np.ones((n, 1))], 1),
                     jnp.int16)
    method = "native" if H._native_available() and B <= 256 else "segment"
    fn = jax.jit(lambda b, g: H.compute_histogram(
        b, g, B, method=method, max_code=mc))
    fn(bins, gh).block_until_ready()
    _QHIST_CACHE.update(fn=fn, bins=bins, gh=gh, method=method)
    return _QHIST_CACHE


def stage_quantized_hist(args):
    """ms: quantized histogram build (int16 grid codes, |code| <= 127
    — the packed-int64 single-add native mode when the FFI kernel is
    loaded) at the ``bench_quant`` pin n=32768, f=50, B=256.  Guards
    the ISSUE 17 hot path: the committed >=1.3x quantized-vs-f32 build
    win evaporates silently if this path regresses."""
    c = _qhist_setup()
    reps = args.qhist_reps
    t0 = time.perf_counter()
    for _ in range(reps):
        c["fn"](c["bins"], c["gh"]).block_until_ready()
    _stretch(t0, "quantized_hist")
    return (time.perf_counter() - t0) / reps * 1e3, "ms"


STAGES = {
    "codec_json": stage_codec_json,
    "codec_binary": stage_codec_binary,
    "scoring_engine": stage_scoring_engine,
    "train_micro": stage_train_micro,
    "quantized_hist": stage_quantized_hist,
}


# ------------------------------------------------------------ comparison


def run_stage(name, args):
    """Median-of-K measurement of one stage."""
    vals, unit = [], None
    for _ in range(args.k):
        v, unit = STAGES[name](args)
        vals.append(v)
    return {"median": round(statistics.median(vals), 4),
            "runs": [round(v, 4) for v in vals], "unit": unit}


def load_baselines(path):
    """Baseline medians per stage from a prior sentinel artifact OR a
    committed bench_serving artifact (its ``codec_micro`` block maps
    onto the codec stages).  Returns ``({stage: median}, kind)``."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") == SCHEMA:
        return ({name: ent["median"]
                 for name, ent in (doc.get("stages") or {}).items()
                 if isinstance(ent, dict) and "median" in ent},
                "perf_sentinel")
    micro = (doc.get("detail") or {}).get("codec_micro") or {}
    out = {}
    if "json_us_per_row" in micro:
        out["codec_json"] = float(micro["json_us_per_row"])
    if "binary_us_per_row" in micro:
        out["codec_binary"] = float(micro["binary_us_per_row"])
    return out, "bench_serving"


def compare(measured, baselines, rel, abs_frac=0.10):
    """The noise-aware verdict: a stage regresses when its median is
    over ``baseline * rel`` AND over the absolute floor (the larger of
    the per-unit floor and ``abs_frac`` of the baseline)."""
    regressions, checks = [], {}
    for name, ent in measured.items():
        base = baselines.get(name)
        if base is None:
            checks[name] = {"baseline": None, "ratio": None,
                            "regressed": False, "gated": False}
            continue
        floor = max(UNIT_FLOORS.get(ent["unit"], 0.0), abs_frac * base)
        ratio = ent["median"] / max(base, 1e-12)
        regressed = (ent["median"] > base * rel
                     and ent["median"] - base > floor)
        # the gauge-facing ratio: a sub-floor delta is scheduler noise
        # on a µs-scale stage, so it reads 1.0 — otherwise the
        # perf_latency_budget SLO would breach on a run this very
        # verdict calls healthy
        effective = (ratio if ratio <= 1.0
                     or ent["median"] - base > floor else 1.0)
        checks[name] = {"baseline": round(base, 4),
                        "ratio": round(ratio, 3),
                        "effective_ratio": round(effective, 3),
                        "abs_floor": round(floor, 4),
                        "regressed": regressed, "gated": True}
        if regressed:
            regressions.append({"stage": name,
                                "median": ent["median"],
                                "baseline": round(base, 4),
                                "ratio": round(ratio, 3),
                                "unit": ent["unit"]})
    return regressions, checks


def measure_profiler_overhead(args):
    """Enabled-vs-disabled A/B of the always-on profiler on the
    closed-loop scoring burst: interleaved reps, median p50 per arm.
    Restores the profiler's enabled state afterwards."""
    import statistics as st
    from mmlspark_tpu.core.profiler import get_profiler
    prof = get_profiler()
    was = prof.enabled
    p50 = {True: [], False: []}
    try:
        for _ in range(args.overhead_reps):
            for enabled in (True, False):
                prof.configure(enabled=enabled)
                p50[enabled].append(scoring_burst_p50(
                    args, duration=args.overhead_duration))
    finally:
        prof.configure(enabled=was)
    on, off = st.median(p50[True]), st.median(p50[False])
    pct = (on - off) / off * 100.0 if off > 0 else float("nan")
    return {"p50_ms_enabled": round(on, 4),
            "p50_ms_disabled": round(off, 4),
            "overhead_pct": round(pct, 2),
            "runs_enabled": [round(v, 4) for v in p50[True]],
            "runs_disabled": [round(v, 4) for v in p50[False]],
            "accept_overhead_lt_3pct": pct < 3.0}


def measure_sketch_overhead(args):
    """Drift-sketch-enabled vs disabled A/B on the closed-loop scoring
    burst (ISSUE 15 satellite): the same <3% p50 discipline the
    profiler overhead gate uses.  The enabled arm runs a
    production-configured DriftMonitor (2% duty-cycle gate, the
    deployed default) attached to the engine; interleaved reps,
    median p50 per arm."""
    import statistics as st
    p50 = {True: [], False: []}
    for _ in range(args.overhead_reps):
        for enabled in (True, False):
            p50[enabled].append(scoring_burst_p50(
                args, duration=args.overhead_duration,
                drift=enabled))
    on, off = st.median(p50[True]), st.median(p50[False])
    pct = (on - off) / off * 100.0 if off > 0 else float("nan")
    return {"p50_ms_enabled": round(on, 4),
            "p50_ms_disabled": round(off, 4),
            "overhead_pct": round(pct, 2),
            "runs_enabled": [round(v, 4) for v in p50[True]],
            "runs_disabled": [round(v, 4) for v in p50[False]],
            "accept_overhead_lt_3pct": pct < 3.0}


def measure_capacity_overhead(args):
    """Capacity-saturation-sampler enabled vs disabled A/B on the
    closed-loop scoring burst (ISSUE 20 satellite): the enabled arm
    constructs the engine with the saturation taps live (per-batch
    gauge stores + the queue_age histogram + the 1 Hz sampler
    ``ensure_capacity_sampler`` installs at engine start); the
    disabled arm flips ``capacity.configure(False)`` before
    construction, so the engine caches the off switch and a lingering
    sampler ticker no-ops.  Same <3% p50 discipline as the profiler /
    sketch / ingest gates; interleaved reps, median p50 per arm."""
    import statistics as st
    from mmlspark_tpu.core import capacity
    was = capacity.configure()
    p50 = {True: [], False: []}
    try:
        for _ in range(args.overhead_reps):
            for enabled in (True, False):
                capacity.configure(enabled=enabled)
                p50[enabled].append(scoring_burst_p50(
                    args, duration=args.overhead_duration))
    finally:
        capacity.configure(enabled=was)
        cm = capacity.peek_capacity_monitor()
        if cm is not None:
            cm.stop()   # the A/B's ticker must not shade later stages
    on, off = st.median(p50[True]), st.median(p50[False])
    pct = (on - off) / off * 100.0 if off > 0 else float("nan")
    return {"p50_ms_enabled": round(on, 4),
            "p50_ms_disabled": round(off, 4),
            "overhead_pct": round(pct, 2),
            "runs_enabled": [round(v, 4) for v in p50[True]],
            "runs_disabled": [round(v, 4) for v in p50[False]],
            "accept_overhead_lt_3pct": pct < 3.0}


def measure_ingest_overhead(args):
    """Ingest-tap-enabled vs disabled A/B on the closed-loop scoring
    burst (ISSUE 18 satellite): the enabled arm appends every scored
    batch — binned to the model's ladder, spilled past the segment
    bound — into a real IngestBuffer through the engine's
    ``ingest_tap`` seam.  Same <3% p50 discipline as the profiler and
    sketch gates; interleaved reps, median p50 per arm."""
    import statistics as st
    import tempfile

    import numpy as np
    from mmlspark_tpu.gbdt import fit_bin_mapper
    from mmlspark_tpu.io.ingest import IngestBuffer
    _b, X = _model(args)
    mapper = fit_bin_mapper(X, max_bin=63)
    p50 = {True: [], False: []}
    with tempfile.TemporaryDirectory() as td:
        ing = IngestBuffer(os.path.join(td, "ingest"), mapper,
                           window_rows=50000, reservoir_rows=512,
                           segment_rows=4096, register=False)

        def tap(rows, margins):
            # the drill-grade label join: a deployment substitutes its
            # own; the append cost being measured is identical
            ing.append(rows, np.asarray(margins, np.float64))

        for _ in range(args.overhead_reps):
            for enabled in (True, False):
                p50[enabled].append(scoring_burst_p50(
                    args, duration=args.overhead_duration,
                    ingest_tap=tap if enabled else None))
        rows_ingested = int(ing.rows_seen)
    on, off = st.median(p50[True]), st.median(p50[False])
    pct = (on - off) / off * 100.0 if off > 0 else float("nan")
    return {"p50_ms_enabled": round(on, 4),
            "p50_ms_disabled": round(off, 4),
            "overhead_pct": round(pct, 2),
            "rows_ingested": rows_ingested,
            "runs_enabled": [round(v, 4) for v in p50[True]],
            "runs_disabled": [round(v, 4) for v in p50[False]],
            "accept_overhead_lt_3pct": pct < 3.0}


# ---------------------------------------------------------------- main


def run(args):
    from mmlspark_tpu.core.profiling import StageStats
    from mmlspark_tpu.core.slo import get_monitor
    from mmlspark_tpu.core.telemetry import (get_journal, get_registry,
                                             host_info)

    stages = [s.strip() for s in args.stages.split(",") if s.strip()]
    unknown = [s for s in stages if s not in STAGES]
    if unknown:
        raise SystemExit(f"unknown stage(s) {unknown}; "
                         f"have {sorted(STAGES)}")
    measured = {}
    for name in stages:
        measured[name] = run_stage(name, args)
        print(f"  {name}: {measured[name]['median']}"
              f"{measured[name]['unit']} (runs "
              f"{measured[name]['runs']})", flush=True)

    baselines, baseline_kind = {}, None
    if args.baseline and not args.calibrate:
        baselines, baseline_kind = load_baselines(args.baseline)
    regressions, checks = compare(measured, baselines, args.rel)

    # verdict plumbing: the ns="perf" gauges feed the
    # perf_latency_budget SLO objective; every regression is journaled
    perf_stats = StageStats()
    worst = max((c["effective_ratio"] for c in checks.values()
                 if c.get("effective_ratio") is not None), default=0.0)
    perf_stats.set_gauge("worst_regression_ratio", worst)
    perf_stats.incr("perf_regressions", len(regressions))
    perf_stats.incr("perf_checks",
                    sum(1 for c in checks.values() if c["gated"]))
    for name, c in checks.items():
        if c.get("ratio") is not None:
            perf_stats.set_gauge(f"{name}_ratio", c["ratio"])
    get_registry().register("perf", perf_stats)
    for r in regressions:
        get_journal().emit("perf_regression", **r)
        print(f"PERF REGRESSION: {r['stage']} {r['median']}{r['unit']} "
              f"vs baseline {r['baseline']}{r['unit']} "
              f"({r['ratio']}x)", flush=True)

    overhead = None
    sketch_overhead = None
    ingest_overhead = None
    capacity_overhead = None
    if not args.skip_overhead:
        print("== profiler overhead A/B ==", flush=True)
        overhead = measure_profiler_overhead(args)
        print(json.dumps(overhead), flush=True)
        print("== drift-sketch overhead A/B ==", flush=True)
        sketch_overhead = measure_sketch_overhead(args)
        print(json.dumps(sketch_overhead), flush=True)
        print("== ingest-tap overhead A/B ==", flush=True)
        ingest_overhead = measure_ingest_overhead(args)
        print(json.dumps(ingest_overhead), flush=True)
        print("== capacity-sampler overhead A/B ==", flush=True)
        capacity_overhead = measure_capacity_overhead(args)
        print(json.dumps(capacity_overhead), flush=True)

    # sample the monitor twice so the gauge objective gets a window
    mon = get_monitor()
    mon.sample()
    time.sleep(0.05)
    slo = mon.report()

    artifact = {
        "schema": SCHEMA,
        "stages": measured,
        "checks": checks,
        "regressions": regressions,
        "baseline_source": args.baseline if baselines else None,
        "baseline_kind": baseline_kind,
        "calibrate": bool(args.calibrate),
        "rel_threshold": args.rel,
        "profiler_overhead": overhead,
        "sketch_overhead": sketch_overhead,
        "ingest_overhead": ingest_overhead,
        "capacity_overhead": capacity_overhead,
        "host": host_info(),
        "slo": {"healthy": slo["healthy"],
                "breaching": slo["breaching"],
                "perf_latency_budget":
                    slo["objectives"].get("perf_latency_budget")},
        "healthy": not regressions,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"artifact -> {args.out}", flush=True)
    print(json.dumps({"healthy": artifact["healthy"],
                      "regressions": [r["stage"] for r in regressions],
                      "worst_ratio": worst}), flush=True)
    return artifact


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="perf-regression sentinel over the committed "
                    "bench baselines (nonzero exit on regression)")
    ap.add_argument("--baseline",
                    default=os.path.join(_REPO, "artifacts",
                                         "perf_sentinel_r20.json"),
                    help="prior sentinel artifact or committed "
                         "bench_serving artifact (a bench artifact "
                         "gates only the codec stages its codec_micro "
                         "block covers; the committed sentinel "
                         "artifact carries ALL stage medians — "
                         "baselines are BOX-relative, so --calibrate "
                         "and re-point this when hardware changes)")
    ap.add_argument("--out", default=None, help="artifact JSON path")
    ap.add_argument("--stages",
                    default="codec_json,codec_binary,scoring_engine,"
                            "train_micro,quantized_hist")
    ap.add_argument("--k", type=int, default=5,
                    help="median-of-K runs per stage")
    ap.add_argument("--rel", type=float, default=1.8,
                    help="relative regression threshold")
    ap.add_argument("--calibrate", action="store_true",
                    help="record a baseline, gate nothing")
    ap.add_argument("--codec-reps", type=int, default=4000)
    ap.add_argument("--codec-features", type=int, default=64)
    ap.add_argument("--model-trees", type=int, default=60)
    ap.add_argument("--train-trees", type=int, default=10)
    ap.add_argument("--qhist-reps", type=int, default=5,
                    help="builds per quantized_hist rep (median over "
                         "--k reps of this many back-to-back builds)")
    ap.add_argument("--outstanding", type=int, default=32)
    ap.add_argument("--burst-duration", type=float, default=1.0)
    ap.add_argument("--overhead-reps", type=int, default=3)
    ap.add_argument("--overhead-duration", type=float, default=1.0)
    ap.add_argument("--skip-overhead", action="store_true")
    args = ap.parse_args(argv)
    if args.calibrate and not args.out:
        raise SystemExit("--calibrate records a baseline: pass --out "
                         "PATH or the measurement is discarded")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    artifact = run(args)
    return 0 if artifact["healthy"] else 1


if __name__ == "__main__":
    sys.exit(main())
