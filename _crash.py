import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS","") + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from sklearn.datasets import make_classification
X, y = make_classification(n_samples=2000, n_features=20, n_informative=10,
                           n_redundant=4, random_state=7, class_sep=0.8)
tbl = {"features": X, "label": y.astype(np.float64)}
from mmlspark_tpu.gbdt import LightGBMClassifier
for i in range(8):
    m = LightGBMClassifier(numIterations=10, numLeaves=15).fit(tbl)
    out = m.transform(tbl)
    print("run", i, "ok", len(m.getModel().trees))
