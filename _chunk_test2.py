import numpy as np, jax, jax.numpy as jnp, time
from mmlspark_tpu.ops.histogram import compute_histogram
B, n, f = 256, 400000, 50
rng = np.random.default_rng(1)
bins = jnp.asarray(rng.integers(0, B, size=(n, f)), jnp.int32)
gh = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
ref = None
def bench(tag, fn):
    global ref
    r = fn(bins, gh); jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(10): r = fn(bins, gh)
    jax.block_until_ready(r)
    ok = "?" if ref is None else f"{float(jnp.max(jnp.abs(r-ref))):.2e}"
    if ref is None: ref = r
    print(f"{tag}: {(time.perf_counter()-t0)/10*1e3:.2f} ms  maxdiff={ok}")
bench("m-only   ", jax.jit(lambda b, g: compute_histogram(b, g, B, method="dot16")))
bench("m+rc8192 ", jax.jit(lambda b, g: compute_histogram(b, g, B, method="dot16", row_chunk=8192)))
bench("m-only2  ", jax.jit(lambda b, g, mm="dot16": compute_histogram(b, g, B, method=mm)))
