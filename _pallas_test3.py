import numpy as np, jax, jax.numpy as jnp, time
from mmlspark_tpu.ops.histogram import compute_histogram
B = 256
rng = np.random.default_rng(1)
bins_s = jnp.asarray(rng.integers(0, B, size=(3000, 7)), jnp.int32)
gh_s = jnp.asarray(rng.integers(0, 3, size=(3000, 3)), jnp.float32)
ref = compute_histogram(bins_s, gh_s, B, method="segment")
out = compute_histogram(bins_s, gh_s, B, method="pallas")
print("int exact max abs diff:", float(jnp.max(jnp.abs(out - ref))))
n, f = 400000, 50
bins = jnp.asarray(rng.integers(0, B, size=(n, f)), jnp.int32)
gh = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
ref = None
def bench(tag, fn, iters=10):
    global ref
    r = fn(bins, gh); _ = np.asarray(r).sum()
    t0 = time.perf_counter(); _ = np.asarray(fn(bins, gh)).sum()
    base = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters): r = fn(bins, gh)
    d = float(jnp.max(jnp.abs(r - ref))) if ref is not None else 0.0
    tot = time.perf_counter() - t0
    if ref is None: ref = r
    print(f"{tag}: {(tot-base)/(iters-1)*1e3:.2f} ms/iter  maxdiff={d:.2e}")
bench("dot16      ", jax.jit(lambda b, g: compute_histogram(b, g, B, method="dot16")))
bench("pallas     ", jax.jit(lambda b, g: compute_histogram(b, g, B, method="pallas")))
bench("pallas_bf16", jax.jit(lambda b, g: compute_histogram(b, g, B, method="pallas_bf16")))
