import time, numpy as np, jax, jax.numpy as jnp
from mmlspark_tpu.ops.histogram import compute_histogram
from mmlspark_tpu.gbdt.grower import GrowerConfig, grow_tree
from mmlspark_tpu.gbdt.objectives import BinaryObjective
from mmlspark_tpu.gbdt.engine import _boost_step

n, f, B = 20000, 20, 256
rng = np.random.default_rng(0)
bins = jnp.asarray(rng.integers(0, B, size=(n, f)), jnp.int32)
gh = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)

for method in ("segment", "dot16", "onehot"):
    fn = jax.jit(lambda b, g, m=method: compute_histogram(b, g, B, method=m))
    r = fn(bins, gh); jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(20): r = fn(bins, gh)
    jax.block_until_ready(r)
    print(f"hist {method}: {(time.perf_counter()-t0)/20*1e3:.2f} ms")

cfg = GrowerConfig(num_leaves=31, num_bins=B, min_data_in_leaf=20, hist_method="auto")
fmask = jnp.ones(f, jnp.float32)
tree, rl = grow_tree(bins, gh.at[:, 2].set(1.0), fmask, cfg)
jax.block_until_ready(rl)
t0 = time.perf_counter()
for _ in range(5): tree, rl = grow_tree(bins, gh, fmask, cfg)
jax.block_until_ready(rl)
print(f"grow_tree: {(time.perf_counter()-t0)/5*1e3:.1f} ms")

# full boost step
obj = BinaryObjective()
labels = jnp.asarray((rng.random(n) > .5), jnp.float32)
w = jnp.ones(n, jnp.float32)
scores = jnp.zeros(n, jnp.float32)
ones = jnp.ones(n, jnp.float32)
tree, scores2 = _boost_step(bins, scores, labels, w, ones, fmask, obj, cfg, 0.1)
jax.block_until_ready(scores2)
t0 = time.perf_counter()
s = jnp.zeros(n, jnp.float32)
for _ in range(5): tree, s = _boost_step(bins, s, labels, w, ones, fmask, obj, cfg, 0.1)
jax.block_until_ready(s)
print(f"boost_step: {(time.perf_counter()-t0)/5*1e3:.1f} ms")
