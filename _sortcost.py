import numpy as np, jax, jax.numpy as jnp, time
n, f = 400000, 50
rng = np.random.default_rng(0)
mask = jnp.asarray(rng.random(n) > 0.5)
vals = jnp.asarray(rng.normal(size=n), jnp.float32)
bins = jnp.asarray(rng.integers(0, 256, size=(n, f)), jnp.int32)
def bench(tag, fn, *args, iters=10):
    r = fn(*args); _ = np.asarray(r).ravel()[:1]
    t0 = time.perf_counter(); _ = np.asarray(fn(*args)).ravel()[:1]
    base = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters): r = fn(*args)
    _ = np.asarray(r).ravel()[:1]
    print(f"{tag}: {(time.perf_counter()-t0-base)/(iters-1)*1e3:.2f} ms", flush=True)
bench("argsort-bool", jax.jit(lambda m: jnp.argsort(~m)), mask)
bench("top_k 80k", jax.jit(lambda v: jax.lax.top_k(v, 80000)[1]), vals)
bench("gather n/2 rows", jax.jit(lambda b, m: b[jnp.argsort(~m)[:n//2]]), bins, mask)
bench("cumsum+scatter", jax.jit(lambda m: jnp.zeros(n//2, jnp.int32).at[jnp.where(m, jnp.cumsum(m)-1, n//2)].set(jnp.arange(n), mode="drop")), mask)
