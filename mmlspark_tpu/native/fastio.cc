// Native IO engine for the binary datasource.
//
// TPU-native replacement for the reference's executor-side binary file
// reader (io/binary/BinaryFileReader.scala backed by Hadoop FS streams;
// expected path, UNVERIFIED -- SURVEY.md SS2.1): the JVM/Hadoop layer is
// re-imagined as a small C++ extension that scans directory trees and
// bulk-reads files on a std::thread pool with the GIL released, feeding
// host RAM at disk speed while the Python driver stays responsive.  The
// Python package falls back to pure-Python IO when this module is not
// built (mmlspark_tpu/native/__init__.py builds it on demand with g++).
//
// CPython C API only -- no pybind11 in this image.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <dirent.h>
#include <fnmatch.h>
#include <sys/stat.h>

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Entry {
  std::string path;
  long long size;
  double mtime;
};

bool ScanDir(const std::string& root, const char* pattern, bool recursive,
             std::vector<Entry>* out, std::string* err) {
  DIR* dir = opendir(root.c_str());
  if (!dir) {
    *err = "cannot open directory: " + root;
    return false;
  }
  std::vector<std::string> subdirs;
  struct dirent* de;
  std::vector<Entry> local;
  while ((de = readdir(dir)) != nullptr) {
    if (std::strcmp(de->d_name, ".") == 0 || std::strcmp(de->d_name, "..") == 0)
      continue;
    std::string full = root + "/" + de->d_name;
    struct stat lst;
    if (lstat(full.c_str(), &lst) != 0) continue;
    bool is_symlink = S_ISLNK(lst.st_mode);
    struct stat st;
    if (stat(full.c_str(), &st) != 0) continue;  // broken symlink etc.
    if (S_ISDIR(st.st_mode)) {
      // never recurse through directory symlinks (os.walk
      // followlinks=False semantics: no cycles, no duplicate rows)
      if (recursive && !is_symlink) subdirs.push_back(full);
    } else if (S_ISREG(st.st_mode)) {
      if (pattern == nullptr || fnmatch(pattern, de->d_name, 0) == 0) {
        local.push_back(Entry{full, static_cast<long long>(st.st_size),
                              static_cast<double>(st.st_mtime)});
      }
    }
  }
  closedir(dir);
  // deterministic order: files of this dir sorted, then subdirs sorted
  std::sort(local.begin(), local.end(),
            [](const Entry& a, const Entry& b) { return a.path < b.path; });
  out->insert(out->end(), local.begin(), local.end());
  std::sort(subdirs.begin(), subdirs.end());
  for (const auto& sd : subdirs) {
    if (!ScanDir(sd, pattern, recursive, out, err)) return false;
  }
  return true;
}

PyObject* py_scan_dir(PyObject*, PyObject* args) {
  const char* root;
  PyObject* pattern_obj;
  int recursive;
  if (!PyArg_ParseTuple(args, "sOp", &root, &pattern_obj, &recursive))
    return nullptr;
  const char* pattern = nullptr;
  if (pattern_obj != Py_None) {
    pattern = PyUnicode_AsUTF8(pattern_obj);
    if (!pattern) return nullptr;
  }
  std::vector<Entry> entries;
  std::string err;
  bool ok;
  Py_BEGIN_ALLOW_THREADS
  ok = ScanDir(root, pattern, recursive != 0, &entries, &err);
  Py_END_ALLOW_THREADS
  if (!ok) {
    PyErr_SetString(PyExc_OSError, err.c_str());
    return nullptr;
  }
  PyObject* list = PyList_New(static_cast<Py_ssize_t>(entries.size()));
  if (!list) return nullptr;
  for (Py_ssize_t i = 0; i < static_cast<Py_ssize_t>(entries.size()); ++i) {
    const Entry& e = entries[static_cast<size_t>(i)];
    PyObject* tup = Py_BuildValue("(sLd)", e.path.c_str(), e.size, e.mtime);
    if (!tup) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, i, tup);
  }
  return list;
}

// Read one file fully into a caller-provided buffer.  Returns bytes read
// or -1.
long long ReadWhole(const std::string& path, char* buf, long long cap) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return -1;
  long long total = 0;
  while (total < cap) {
    size_t got = std::fread(buf + total, 1,
                            static_cast<size_t>(cap - total), f);
    if (got == 0) break;
    total += static_cast<long long>(got);
  }
  std::fclose(f);
  return total;
}

PyObject* py_read_file(PyObject*, PyObject* args) {
  const char* path;
  if (!PyArg_ParseTuple(args, "s", &path)) return nullptr;
  struct stat st;
  if (stat(path, &st) != 0 || !S_ISREG(st.st_mode)) {
    PyErr_Format(PyExc_OSError, "cannot stat %s", path);
    return nullptr;
  }
  PyObject* bytes = PyBytes_FromStringAndSize(nullptr, st.st_size);
  if (!bytes) return nullptr;
  char* buf = PyBytes_AS_STRING(bytes);
  long long got;
  Py_BEGIN_ALLOW_THREADS
  got = ReadWhole(path, buf, static_cast<long long>(st.st_size));
  Py_END_ALLOW_THREADS
  if (got < 0) {
    Py_DECREF(bytes);
    PyErr_Format(PyExc_OSError, "cannot read %s", path);
    return nullptr;
  }
  if (got != st.st_size && _PyBytes_Resize(&bytes, got) != 0) return nullptr;
  return bytes;
}

// Bulk read on a thread pool, GIL released for the IO phase.
PyObject* py_read_files(PyObject*, PyObject* args) {
  PyObject* seq;
  int n_threads = 8;
  if (!PyArg_ParseTuple(args, "O|i", &seq, &n_threads)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "read_files expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);

  std::vector<std::string> paths;
  paths.reserve(static_cast<size_t>(n));
  std::vector<long long> sizes(static_cast<size_t>(n), 0);
  std::vector<PyObject*> outs(static_cast<size_t>(n), nullptr);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* p = PyUnicode_AsUTF8(PySequence_Fast_GET_ITEM(fast, i));
    if (!p) {
      Py_DECREF(fast);
      return nullptr;
    }
    paths.emplace_back(p);
  }
  // allocate exact-size bytes objects up front (needs the GIL), then fill
  // the buffers in parallel without it
  std::vector<char*> bufs(static_cast<size_t>(n), nullptr);
  for (Py_ssize_t i = 0; i < n; ++i) {
    struct stat st;
    long long sz =
        (stat(paths[static_cast<size_t>(i)].c_str(), &st) == 0 &&
         S_ISREG(st.st_mode))
            ? static_cast<long long>(st.st_size)
            : 0;
    sizes[static_cast<size_t>(i)] = sz;
    PyObject* b = PyBytes_FromStringAndSize(nullptr, sz);
    if (!b) {
      for (auto* o : outs) Py_XDECREF(o);
      Py_DECREF(fast);
      return nullptr;
    }
    outs[static_cast<size_t>(i)] = b;
    bufs[static_cast<size_t>(i)] = PyBytes_AS_STRING(b);
  }
  std::atomic<long long> next(0);
  std::atomic<int> failures(0);
  int workers = n_threads < 1 ? 1 : n_threads;
  Py_BEGIN_ALLOW_THREADS {
    std::vector<std::thread> pool;
    pool.reserve(static_cast<size_t>(workers));
    for (int w = 0; w < workers; ++w) {
      pool.emplace_back([&]() {
        while (true) {
          long long i = next.fetch_add(1);
          if (i >= static_cast<long long>(paths.size())) break;
          long long got = ReadWhole(paths[static_cast<size_t>(i)],
                                    bufs[static_cast<size_t>(i)],
                                    sizes[static_cast<size_t>(i)]);
          if (got != sizes[static_cast<size_t>(i)]) failures.fetch_add(1);
        }
      });
    }
    for (auto& t : pool) t.join();
  }
  Py_END_ALLOW_THREADS
  Py_DECREF(fast);
  if (failures.load() != 0) {
    for (auto* o : outs) Py_XDECREF(o);
    PyErr_SetString(PyExc_OSError,
                    "read_files: one or more files changed size or "
                    "failed to read");
    return nullptr;
  }
  PyObject* list = PyList_New(n);
  if (!list) {
    for (auto* o : outs) Py_XDECREF(o);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; ++i)
    PyList_SET_ITEM(list, i, outs[static_cast<size_t>(i)]);
  return list;
}

// MurmurHash3 x86 32-bit, bit-compatible with Spark's Murmur3_x86_32 on
// UTF-8 bytes (featurize/hashing.py documents the parity contract).
uint32_t Murmur3_32(const unsigned char* data, size_t len, uint32_t seed) {
  const uint32_t c1 = 0xCC9E2D51u, c2 = 0x1B873593u;
  uint32_t h = seed;
  size_t n4 = len / 4 * 4;
  for (size_t i = 0; i < n4; i += 4) {
    uint32_t k;
    std::memcpy(&k, data + i, 4);  // little-endian hosts only (x86/arm64)
    k *= c1;
    k = (k << 15) | (k >> 17);
    k *= c2;
    h ^= k;
    h = (h << 13) | (h >> 19);
    h = h * 5 + 0xE6546B64u;
  }
  if (n4 < len) {
    unsigned char tail[4] = {0, 0, 0, 0};
    std::memcpy(tail, data + n4, len - n4);
    uint32_t k;
    std::memcpy(&k, tail, 4);
    k *= c1;
    k = (k << 15) | (k >> 17);
    k *= c2;
    h ^= k;
  }
  h ^= static_cast<uint32_t>(len);
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

PyObject* py_murmur3_batch(PyObject*, PyObject* args) {
  PyObject* seq;
  int seed = 42;
  if (!PyArg_ParseTuple(args, "O|i", &seq, &seed)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "murmur3_batch expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject* list = PyList_New(n);
  if (!list) {
    Py_DECREF(fast);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    Py_ssize_t len = 0;
    const char* s =
        PyUnicode_AsUTF8AndSize(PySequence_Fast_GET_ITEM(fast, i), &len);
    if (!s) {
      Py_DECREF(fast);
      Py_DECREF(list);
      return nullptr;
    }
    uint32_t h = Murmur3_32(reinterpret_cast<const unsigned char*>(s),
                            static_cast<size_t>(len),
                            static_cast<uint32_t>(seed));
    // signed int32, like the JVM
    PyObject* v = PyLong_FromLong(static_cast<int32_t>(h));
    if (!v) {
      Py_DECREF(fast);
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, i, v);
  }
  Py_DECREF(fast);
  return list;
}

PyMethodDef kMethods[] = {
    {"murmur3_batch", py_murmur3_batch, METH_VARARGS,
     "murmur3_batch(terms, seed=42) -> [int32] (Spark Murmur3_x86_32)"},
    {"scan_dir", py_scan_dir, METH_VARARGS,
     "scan_dir(root, pattern_or_None, recursive) -> [(path, size, mtime)]"},
    {"read_file", py_read_file, METH_VARARGS, "read_file(path) -> bytes"},
    {"read_files", py_read_files, METH_VARARGS,
     "read_files(paths, n_threads=8) -> [bytes] (parallel, GIL released)"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "_fastio",
                       "native IO engine for the binary datasource",
                       -1, kMethods};

}  // namespace

PyMODINIT_FUNC PyInit__fastio() { return PyModule_Create(&kModule); }
