// XLA FFI custom-call gradient-histogram kernel (CPU backend).
//
// The first cut of the native CPU histogram used jax.pure_callback, which
// deadlocks on this box: the single-core XLA CPU runtime's worker waits
// on the Python callback while the callback waits for the runtime (seen
// as a stuck second fit in bench.py --force-cpu).  An XLA FFI custom
// call runs synchronously INSIDE the compiled program on the executing
// thread — no Python, no cross-thread handshake — and is the idiomatic
// native-kernel seam jax provides for exactly this.
//
// Same accumulation loop as LightGBM's ConstructHistograms
// (src/io/dense_bin.hpp; expected path, UNVERIFIED — SURVEY.md §3.1):
// one row pass, three fused adds per row-feature into an L2-resident
// (f, B, 3) float32 accumulator.  Masked rows (g == h == c == 0) skip.
//
// Built header-only against jaxlib's bundled xla/ffi/api headers; loaded
// with ctypes and registered via jax.ffi.pycapsule (no pybind11 in this
// image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error HistImpl(ffi::Buffer<ffi::U8> bins,
                           ffi::Buffer<ffi::F32> gh,
                           ffi::ResultBuffer<ffi::F32> out) {
  auto bd = bins.dimensions();
  if (bd.size() != 2 || gh.dimensions().size() != 2 ||
      out->dimensions().size() != 3) {
    return ffi::Error::InvalidArgument(
        "fasthist: need bins (n,f) u8, gh (n,3) f32, out (f,B,3) f32");
  }
  const int64_t n = bd[0];
  const int64_t f = bd[1];
  const int64_t B = out->dimensions()[1];
  const uint8_t* b = bins.typed_data();
  const float* g = gh.typed_data();
  float* o = out->typed_data();
  std::fill(o, o + f * B * 3, 0.f);
  for (int64_t i = 0; i < n; ++i) {
    const float gi = g[3 * i];
    const float hi = g[3 * i + 1];
    const float ci = g[3 * i + 2];
    if (gi == 0.f && hi == 0.f && ci == 0.f) continue;  // masked row
    const uint8_t* br = b + i * f;
    for (int64_t j = 0; j < f; ++j) {
      int64_t bin = br[j];
      if (bin >= B) bin = B - 1;  // safety clamp; mapper guarantees < B
      float* cell = o + (j * B + bin) * 3;
      cell[0] += gi;
      cell[1] += hi;
      cell[2] += ci;
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastHist, HistImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

// Segment histogram with a DYNAMIC offset/count straight off the
// DataPartition row permutation: no power-of-two bucket ladder, no
// lax.switch, no padding work — C++ loops exactly `cnt` rows.
// (bins (n,f) u8, gh (n,3) f32, row_order (m,) i32, meta (2,) i32
// [off, cnt]) -> out (f,B,3) f32.
static ffi::Error SegHistImpl(ffi::Buffer<ffi::U8> bins,
                              ffi::Buffer<ffi::F32> gh,
                              ffi::Buffer<ffi::S32> row_order,
                              ffi::Buffer<ffi::S32> meta,
                              ffi::ResultBuffer<ffi::F32> out) {
  const int64_t n = bins.dimensions()[0];
  const int64_t f = bins.dimensions()[1];
  const int64_t m = row_order.dimensions()[0];
  const int64_t B = out->dimensions()[1];
  const uint8_t* b = bins.typed_data();
  const float* g = gh.typed_data();
  const int32_t* ro = row_order.typed_data();
  int64_t off = meta.typed_data()[0];
  int64_t cnt = meta.typed_data()[1];
  if (off < 0) off = 0;
  if (off + cnt > m) cnt = m - off;
  float* o = out->typed_data();
  std::fill(o, o + f * B * 3, 0.f);
  // the permutation makes every row access random: prefetch a few rows
  // ahead so the DRAM fetch overlaps the current row's accumulate
  // (LightGBM's indexed ConstructHistograms does the same)
  constexpr int64_t kPrefetch = 8;
  for (int64_t i = 0; i < cnt; ++i) {
    if (i + kPrefetch < cnt) {
      const int64_t pr = ro[off + i + kPrefetch];
      if (pr >= 0 && pr < n) {
        __builtin_prefetch(b + pr * f);
        __builtin_prefetch(b + pr * f + f - 1);  // row tail (2nd line if any)
        __builtin_prefetch(g + 3 * pr);
      }
    }
    int64_t row = ro[off + i];
    if (row < 0 || row >= n) continue;  // pad sentinel
    const float gi = g[3 * row];
    const float hi = g[3 * row + 1];
    const float ci = g[3 * row + 2];
    if (gi == 0.f && hi == 0.f && ci == 0.f) continue;  // bagged out
    const uint8_t* br = b + row * f;
    for (int64_t j = 0; j < f; ++j) {
      int64_t bin = br[j];
      if (bin >= B) bin = B - 1;
      float* cell = o + (j * B + bin) * 3;
      cell[0] += gi;
      cell[1] += hi;
      cell[2] += ci;
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastSegHist, SegHistImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

// DataPartition::Split as one stable in-place pass (LightGBM
// src/io/data_partition.hpp analog; expected path, UNVERIFIED).  The
// leaf's contiguous row_order segment [off, off+cnt) is partitioned
// into left|right by the split column; input_output_aliases makes the
// row_order update zero-copy.  ``meta`` (4,) i32 = [off, cnt, thr,
// use_cat]; ``counts`` out (2,) i32 = [cnt_left, cnt_right].
static ffi::Error PartitionImpl(ffi::Buffer<ffi::S32> row_order,
                                ffi::Buffer<ffi::U8> col,
                                ffi::Buffer<ffi::S32> meta,
                                ffi::Buffer<ffi::U32> cat_bits,
                                ffi::ResultBuffer<ffi::S32> row_order_out,
                                ffi::ResultBuffer<ffi::S32> counts) {
  const int64_t m = row_order.dimensions()[0];
  const int64_t n = col.dimensions()[0];
  const int32_t* ro_in = row_order.typed_data();
  int32_t* ro = row_order_out->typed_data();
  if (ro != ro_in) std::copy(ro_in, ro_in + m, ro);  // alias miss: copy
  const uint8_t* c = col.typed_data();
  const int32_t* mt = meta.typed_data();
  int64_t off = mt[0];
  int64_t cnt = mt[1];
  const int32_t thr = mt[2];
  const bool use_cat = mt[3] != 0;
  const uint32_t* bits = cat_bits.typed_data();
  if (off < 0) off = 0;
  if (off + cnt > m) cnt = m - off;
  const int64_t max_bin = cat_bits.dimensions()[0] * 32;  // bitset span
  std::vector<int32_t> right;
  right.reserve(static_cast<size_t>(cnt));
  int64_t w = off;
  constexpr int64_t kPrefetch = 16;
  for (int64_t i = 0; i < cnt; ++i) {
    if (i + kPrefetch < cnt) {
      const int32_t pr = ro[off + i + kPrefetch];
      if (pr >= 0 && pr < n) __builtin_prefetch(c + pr);
    }
    const int32_t row = ro[off + i];
    int64_t bin = (row >= 0 && row < n) ? c[row] : 0;
    if (bin >= max_bin) bin = max_bin - 1;  // clamp, like the hist kernels
    const bool left = use_cat ? ((bits[bin >> 5] >> (bin & 31)) & 1u) != 0
                              : bin <= thr;
    if (left) {
      ro[w++] = row;
    } else {
      right.push_back(row);
    }
  }
  std::copy(right.begin(), right.end(), ro + w);
  counts->typed_data()[0] = static_cast<int32_t>(w - off);
  counts->typed_data()[1] = static_cast<int32_t>(right.size());
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastPartition, PartitionImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::U32>>()
        .Ret<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>());

// Numeric best-split scan over a (f, B, 3) histogram — the serial-path
// FindBestThreshold (LightGBM src/treelearner/feature_histogram.hpp
// analog; expected path, UNVERIFIED).  Same validity rules and
// first-occurrence (feature-major, bin-minor) argmax order as
// grower.find_best_split's numeric branch: left = bins <= b, last bin
// excluded, min_data_in_leaf / min_sum_hessian gates, gain =
// leaf_gain(l) + leaf_gain(r) - leaf_gain(parent) in the l1-threshold
// form.  The sequential f32 prefix sums here round differently from
// XLA's cumsum, so this kernel's contribution is the WINNING (feature,
// bin) — the Python wrapper recomputes the recorded gain on XLA's
// float trajectory (ops/histogram.py native_find_split).
// parent (3,) f32 = [g, h, c]; conf (6,) f32 = [min_data_in_leaf,
// min_sum_hessian, lambda_l1, lambda_l2, gain_floor, depth_ok];
// outs: gain (1,) f32, fb (2,) i32 = [feature, bin].
static inline float LeafGainL1(float g, float h, float l1, float l2) {
  float t = std::fabs(g) - l1;
  if (t < 0.f) t = 0.f;
  t = std::copysign(t, g);
  if (g == 0.f) t = 0.f;  // jnp.sign(0) == 0
  return (t * t) / (h + l2);
}

static ffi::Error SplitImpl(ffi::Buffer<ffi::F32> hist,
                            ffi::Buffer<ffi::F32> parent,
                            ffi::Buffer<ffi::F32> fmask,
                            ffi::Buffer<ffi::F32> conf,
                            ffi::ResultBuffer<ffi::F32> gain_out,
                            ffi::ResultBuffer<ffi::S32> fb_out) {
  const auto hd = hist.dimensions();
  if (hd.size() != 3 || hd[2] != 3) {
    return ffi::Error::InvalidArgument("fastsplit: hist must be (f,B,3)");
  }
  const int64_t f = hd[0];
  const int64_t B = hd[1];
  if (parent.element_count() < 3 || conf.element_count() < 6 ||
      fmask.element_count() < f) {
    return ffi::Error::InvalidArgument(
        "fastsplit: need parent (3,), conf (6,), fmask (f,)");
  }
  const float* h = hist.typed_data();
  const float pg = parent.typed_data()[0];
  const float ph = parent.typed_data()[1];
  const float pc = parent.typed_data()[2];
  const float* fm = fmask.typed_data();
  const float* cf = conf.typed_data();
  const float min_cnt = cf[0];
  const float min_hess = cf[1];
  const float l1 = cf[2];
  const float l2 = cf[3];
  const float gain_floor = cf[4];
  const bool depth_ok = cf[5] != 0.f;
  const float parent_gain = LeafGainL1(pg, ph, l1, l2);
  float best = -std::numeric_limits<float>::infinity();
  int32_t bf = 0, bb = 0;
  if (depth_ok) {
    for (int64_t j = 0; j < f; ++j) {
      if (!(fm[j] > 0.f)) continue;
      const float* hj = h + j * B * 3;
      float gl = 0.f, hl = 0.f, cl = 0.f;
      for (int64_t b = 0; b + 1 < B; ++b) {  // last bin excluded
        gl += hj[3 * b];
        hl += hj[3 * b + 1];
        cl += hj[3 * b + 2];
        const float gr = pg - gl;
        const float hr = ph - hl;
        const float cr = pc - cl;
        if (cl >= min_cnt && cr >= min_cnt && hl >= min_hess &&
            hr >= min_hess) {
          const float gain = LeafGainL1(gl, hl, l1, l2) +
                             LeafGainL1(gr, hr, l1, l2) - parent_gain;
          if (gain > best) {  // strict: first occurrence wins, like argmax
            best = gain;
            bf = static_cast<int32_t>(j);
            bb = static_cast<int32_t>(b);
          }
        }
      }
    }
  }
  gain_out->typed_data()[0] =
      best > gain_floor ? best
                        : -std::numeric_limits<float>::infinity();
  fb_out->typed_data()[0] = bf;
  fb_out->typed_data()[1] = bb;
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastSplit, SplitImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::S32>>());

// ---------------------------------------------------------------------------
// Quantized-gradient histograms (ISSUE 17).  gh holds int16 GRID CODES
// (per-round stochastic rounding, grower-side); accumulation is exact
// int32.  Two modes, selected by meta's `packed` flag (the JAX wrapper
// sets it from the static headroom bound ops/histogram.packed_accum_ok):
//
//   packed — the (g, h, count) triple is folded into ONE biased uint64
//     per row: [g + mc : 24 bits][h + mc : 24 bits][count : 16 bits],
//     and the inner loop does a SINGLE 64-bit add per row-feature into
//     an (f, B) uint64 scratch — a third of the adds and 8 bytes of
//     cell traffic instead of 12.  The bias keeps all fields
//     non-negative so field-carries cannot happen while
//     n * 2*max_code < 2^24 and n < 2^16.  Exactness contract per row:
//     count == 1 and |code| <= mc (the training invariant — the count
//     channel is the 0/1 bag mask and the quantizer clips).  Rows that
//     violate it (and count==0 rows) accumulate DIRECTLY into the int32
//     output instead, so the result is exact for any input; the final
//     unpack ADDS the scratch into the output.
//
//   unpacked — three int32 adds per row-feature, no scratch; used when
//     the packed bound fails.
namespace {

struct QAccum {
  int64_t f, B, mc;
  bool packed;
  int32_t* o;                  // (f, B, 3) int32, pre-zeroed
  std::vector<uint64_t> acc;   // (f, B) packed scratch (packed mode)

  void Init(int64_t f_, int64_t B_, int64_t mc_, bool packed_,
            int32_t* o_) {
    f = f_;
    B = B_;
    mc = mc_;
    packed = packed_;
    o = o_;
    std::fill(o, o + f * B * 3, 0);
    if (packed) acc.assign(static_cast<size_t>(f * B), 0ull);
  }

  inline void Row(const uint8_t* br, int32_t gi, int32_t hi, int32_t ci) {
    if (packed && ci == 1 && gi >= -mc && gi <= mc && hi >= -mc &&
        hi <= mc) {
      const uint64_t pv =
          (static_cast<uint64_t>(static_cast<uint32_t>(gi + mc)) << 40) |
          (static_cast<uint64_t>(static_cast<uint32_t>(hi + mc)) << 16) |
          1ull;
      uint64_t* a = acc.data();
      for (int64_t j = 0; j < f; ++j) {
        int64_t bin = br[j];
        if (bin >= B) bin = B - 1;
        a[j * B + bin] += pv;
      }
      return;
    }
    if (gi == 0 && hi == 0 && ci == 0) return;  // masked row
    for (int64_t j = 0; j < f; ++j) {
      int64_t bin = br[j];
      if (bin >= B) bin = B - 1;
      int32_t* cell = o + (j * B + bin) * 3;
      cell[0] += gi;
      cell[1] += hi;
      cell[2] += ci;
    }
  }

  void Finish() {
    if (!packed) return;
    const uint64_t* a = acc.data();
    for (int64_t c = 0; c < f * B; ++c) {
      const uint64_t v = a[c];
      if (v == 0) continue;
      const int64_t k = static_cast<int64_t>(v & 0xFFFFull);
      const int64_t hs =
          static_cast<int64_t>((v >> 16) & 0xFFFFFFull) - k * mc;
      const int64_t gs = static_cast<int64_t>(v >> 40) - k * mc;
      int32_t* cell = o + c * 3;
      cell[0] += static_cast<int32_t>(gs);
      cell[1] += static_cast<int32_t>(hs);
      cell[2] += static_cast<int32_t>(k);
    }
  }
};

}  // namespace

// (bins (n,f) u8, gh (n,3) s16, meta (2,) s32 [packed, max_code])
//   -> out (f,B,3) s32.
static ffi::Error QHistImpl(ffi::Buffer<ffi::U8> bins,
                            ffi::Buffer<ffi::S16> gh,
                            ffi::Buffer<ffi::S32> meta,
                            ffi::ResultBuffer<ffi::S32> out) {
  auto bd = bins.dimensions();
  if (bd.size() != 2 || gh.dimensions().size() != 2 ||
      out->dimensions().size() != 3 || meta.element_count() < 2) {
    return ffi::Error::InvalidArgument(
        "fastqhist: need bins (n,f) u8, gh (n,3) s16, meta (2,) s32, "
        "out (f,B,3) s32");
  }
  const int64_t n = bd[0];
  const int64_t f = bd[1];
  const int64_t B = out->dimensions()[1];
  const uint8_t* b = bins.typed_data();
  const int16_t* g = gh.typed_data();
  const bool packed = meta.typed_data()[0] != 0;
  const int64_t mc = meta.typed_data()[1];
  QAccum q;
  q.Init(f, B, mc, packed, out->typed_data());
  for (int64_t i = 0; i < n; ++i) {
    q.Row(b + i * f, g[3 * i], g[3 * i + 1], g[3 * i + 2]);
  }
  q.Finish();
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastQHist, QHistImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Arg<ffi::Buffer<ffi::S16>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>());

// Quantized segment histogram off the DataPartition permutation.
// (bins (n,f) u8, gh (n,3) s16, row_order (m,) s32, meta (4,) s32
// [off, cnt, packed, max_code]) -> out (f,B,3) s32.
static ffi::Error SegQHistImpl(ffi::Buffer<ffi::U8> bins,
                               ffi::Buffer<ffi::S16> gh,
                               ffi::Buffer<ffi::S32> row_order,
                               ffi::Buffer<ffi::S32> meta,
                               ffi::ResultBuffer<ffi::S32> out) {
  if (meta.element_count() < 4) {
    return ffi::Error::InvalidArgument(
        "fastsegqhist: meta must be (4,) s32 [off, cnt, packed, mc]");
  }
  const int64_t n = bins.dimensions()[0];
  const int64_t f = bins.dimensions()[1];
  const int64_t m = row_order.dimensions()[0];
  const int64_t B = out->dimensions()[1];
  const uint8_t* b = bins.typed_data();
  const int16_t* g = gh.typed_data();
  const int32_t* ro = row_order.typed_data();
  int64_t off = meta.typed_data()[0];
  int64_t cnt = meta.typed_data()[1];
  const bool packed = meta.typed_data()[2] != 0;
  const int64_t mc = meta.typed_data()[3];
  if (off < 0) off = 0;
  if (off + cnt > m) cnt = m - off;
  QAccum q;
  q.Init(f, B, mc, packed, out->typed_data());
  constexpr int64_t kPrefetch = 8;
  for (int64_t i = 0; i < cnt; ++i) {
    if (i + kPrefetch < cnt) {
      const int64_t pr = ro[off + i + kPrefetch];
      if (pr >= 0 && pr < n) {
        __builtin_prefetch(b + pr * f);
        __builtin_prefetch(b + pr * f + f - 1);
        __builtin_prefetch(g + 3 * pr);
      }
    }
    const int64_t row = ro[off + i];
    if (row < 0 || row >= n) continue;  // pad sentinel
    q.Row(b + row * f, g[3 * row], g[3 * row + 1], g[3 * row + 2]);
  }
  q.Finish();
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastSegQHist, SegQHistImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Arg<ffi::Buffer<ffi::S16>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>());
