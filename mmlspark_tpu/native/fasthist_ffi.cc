// XLA FFI custom-call gradient-histogram kernel (CPU backend).
//
// The first cut of the native CPU histogram used jax.pure_callback, which
// deadlocks on this box: the single-core XLA CPU runtime's worker waits
// on the Python callback while the callback waits for the runtime (seen
// as a stuck second fit in bench.py --force-cpu).  An XLA FFI custom
// call runs synchronously INSIDE the compiled program on the executing
// thread — no Python, no cross-thread handshake — and is the idiomatic
// native-kernel seam jax provides for exactly this.
//
// Same accumulation loop as LightGBM's ConstructHistograms
// (src/io/dense_bin.hpp; expected path, UNVERIFIED — SURVEY.md §3.1):
// one row pass, three fused adds per row-feature into an L2-resident
// (f, B, 3) float32 accumulator.  Masked rows (g == h == c == 0) skip.
//
// Built header-only against jaxlib's bundled xla/ffi/api headers; loaded
// with ctypes and registered via jax.ffi.pycapsule (no pybind11 in this
// image).

#include <algorithm>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error HistImpl(ffi::Buffer<ffi::U8> bins,
                           ffi::Buffer<ffi::F32> gh,
                           ffi::ResultBuffer<ffi::F32> out) {
  auto bd = bins.dimensions();
  if (bd.size() != 2 || gh.dimensions().size() != 2 ||
      out->dimensions().size() != 3) {
    return ffi::Error::InvalidArgument(
        "fasthist: need bins (n,f) u8, gh (n,3) f32, out (f,B,3) f32");
  }
  const int64_t n = bd[0];
  const int64_t f = bd[1];
  const int64_t B = out->dimensions()[1];
  const uint8_t* b = bins.typed_data();
  const float* g = gh.typed_data();
  float* o = out->typed_data();
  std::fill(o, o + f * B * 3, 0.f);
  for (int64_t i = 0; i < n; ++i) {
    const float gi = g[3 * i];
    const float hi = g[3 * i + 1];
    const float ci = g[3 * i + 2];
    if (gi == 0.f && hi == 0.f && ci == 0.f) continue;  // masked row
    const uint8_t* br = b + i * f;
    for (int64_t j = 0; j < f; ++j) {
      int64_t bin = br[j];
      if (bin >= B) bin = B - 1;  // safety clamp; mapper guarantees < B
      float* cell = o + (j * B + bin) * 3;
      cell[0] += gi;
      cell[1] += hi;
      cell[2] += ci;
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastHist, HistImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

// Fused gather + histogram: the DataPartition grower's per-split hot
// path histograms a leaf's contiguous row_order segment.  XLA's version
// materializes the gathered (size, f) sub-matrix in memory before the
// histogram reads it back; here the row indirection happens in the
// accumulation loop itself (PERF.md round-3 headroom note: the bucket
// gather costs as much as the histogram).  ``seg`` is the bucket-sized
// index slice, ``cnt`` (1,) i32 the number of live leaf rows at its
// head.
static ffi::Error HistGatherImpl(ffi::Buffer<ffi::U8> bins,
                                 ffi::Buffer<ffi::F32> gh,
                                 ffi::Buffer<ffi::S32> seg,
                                 ffi::Buffer<ffi::S32> cnt,
                                 ffi::ResultBuffer<ffi::F32> out) {
  auto bd = bins.dimensions();
  if (bd.size() != 2 || gh.dimensions().size() != 2 ||
      seg.dimensions().size() != 1 || out->dimensions().size() != 3) {
    return ffi::Error::InvalidArgument(
        "fasthist_gather: need bins (n,f) u8, gh (n,3) f32, seg (m,) "
        "i32, cnt (1,) i32, out (f,B,3) f32");
  }
  const int64_t n = bd[0];
  const int64_t f = bd[1];
  const int64_t m = seg.dimensions()[0];
  const int64_t B = out->dimensions()[1];
  const uint8_t* b = bins.typed_data();
  const float* g = gh.typed_data();
  const int32_t* s = seg.typed_data();
  int64_t live = cnt.typed_data()[0];
  if (live > m) live = m;
  float* o = out->typed_data();
  std::fill(o, o + f * B * 3, 0.f);
  for (int64_t i = 0; i < live; ++i) {
    int64_t row = s[i];
    if (row < 0 || row >= n) continue;  // pad sentinel
    const float gi = g[3 * row];
    const float hi = g[3 * row + 1];
    const float ci = g[3 * row + 2];
    if (gi == 0.f && hi == 0.f && ci == 0.f) continue;  // bagged out
    const uint8_t* br = b + row * f;
    for (int64_t j = 0; j < f; ++j) {
      int64_t bin = br[j];
      if (bin >= B) bin = B - 1;
      float* cell = o + (j * B + bin) * 3;
      cell[0] += gi;
      cell[1] += hi;
      cell[2] += ci;
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    MmlsparkFastHistGather, HistGatherImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::F32>>());
