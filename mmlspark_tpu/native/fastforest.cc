// Native forest traversal for Booster.predict on the CPU backend.
//
// TPU-native replacement for the reference's per-row JNI predict
// (LightGBMBooster.score -> LGBM_BoosterPredictForMat; expected path,
// UNVERIFIED -- SURVEY.md SS3.2, a known perf sore point there too).  The
// jitted gather-walk in booster.py is the accelerator path; on the CPU
// backend XLA lowers the fixed-depth walk to whole-array gathers per
// level, ~2.6 s for the bench shape where this early-exit row walk needs
// well under a second.
//
// Exactness contract: bitwise-identical margins to _predict_forest.  The
// walk uses the same float32 `x <= thr` decision (NaN -> right for
// numeric nodes), the same categorical bitset semantics as _cat_go_left
// (NaN -> default_left, negative / out-of-range categories -> right),
// and accumulates per-row tree values in the same tree order in float32,
// so every IEEE operation matches the XLA scan.
//
// CPython C API only -- no pybind11 in this image.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

struct Buf {
  Py_buffer view;
  bool held = false;
  ~Buf() {
    if (held) PyBuffer_Release(&view);
  }
  bool Get(PyObject* obj, const char* name, int itemsize,
           bool writable = false) {
    const int flags = PyBUF_C_CONTIGUOUS | PyBUF_FORMAT |
                      (writable ? PyBUF_WRITABLE : 0);
    if (PyObject_GetBuffer(obj, &view, flags) != 0) {
      return false;
    }
    held = true;
    if (view.itemsize != itemsize) {
      PyErr_Format(PyExc_TypeError, "%s: expected itemsize %d, got %zd", name,
                   itemsize, view.itemsize);
      return false;
    }
    return true;
  }
};

struct Forest {
  const int32_t* feat;     // (T, m)
  const float* thr;        // (T, m)
  const int32_t* left;     // (T, m)
  const int32_t* right;    // (T, m)
  const float* leaf;       // (T, L)
  const uint8_t* single;   // (T,)
  const int32_t* is_cat;   // (T, m)
  const int32_t* dleft;    // (T, m)
  const int32_t* cat_bnd;  // (T, C1)
  const uint32_t* cat_words;  // (T, W)
  int64_t T, m, L, C1, W;
  int K;
  bool has_cat;
};

inline bool CatGoLeft(float x, int32_t j, int32_t dleft_node,
                      const int32_t* bnd, int64_t C1, const uint32_t* words,
                      int64_t W) {
  if (std::isnan(x)) return dleft_node > 0;
  if (j < 0) j = 0;
  if (j > static_cast<int32_t>(C1) - 2) j = static_cast<int32_t>(C1) - 2;
  const int64_t b0 = bnd[j];
  const int64_t b1 = bnd[j + 1];
  // int32 truncation FIRST, then the sign gate, exactly like the XLA walk:
  // x in (-1, 0) truncates to category 0 (may go left); x <= -1 routes
  // right.  Values outside int32 range route right (the XLA convert's
  // wrap behavior there is garbage-in, not a contract).
  if (!(x > -2147483648.0f && x < 2147483648.0f)) return false;
  const int32_t c = static_cast<int32_t>(x);
  if (c < 0) return false;
  const int64_t widx = b0 + (c >> 5);
  if (widx < 0 || widx >= b1 || widx >= W) return false;
  return (words[widx] >> (c & 31)) & 1u;
}

void PredictRows(const Forest& fr, const float* X, int64_t f, int64_t r0,
                 int64_t r1, float* out) {
  for (int64_t i = r0; i < r1; ++i) {
    const float* xrow = X + i * f;
    float* orow = out + i * fr.K;
    for (int64_t t = 0; t < fr.T; ++t) {
      const int32_t* tfeat = fr.feat + t * fr.m;
      const float* tthr = fr.thr + t * fr.m;
      const int32_t* tleft = fr.left + t * fr.m;
      const int32_t* tright = fr.right + t * fr.m;
      int32_t node = fr.single[t] ? -1 : 0;
      // Corrupt-model hardening, matching the XLA walk where it has a
      // defined behavior: index clamps mirror XLA's clamping gather
      // semantics; the step bound (the XLA walk is a fixed-depth
      // fori_loop) turns a cyclic left/right graph into leaf 0 instead
      // of a hang.
      int64_t steps = 0;
      while (node >= 0) {
        if (node >= fr.m) node = static_cast<int32_t>(fr.m) - 1;
        if (++steps > fr.m) {
          node = -1;
          break;
        }
        int32_t fj = tfeat[node];
        if (fj < 0) fj = 0;
        if (fj >= f) fj = static_cast<int32_t>(f) - 1;
        const float x = xrow[fj];
        bool go_left;
        if (fr.has_cat && fr.is_cat[t * fr.m + node]) {
          go_left = CatGoLeft(x, static_cast<int32_t>(tthr[node]),
                              fr.dleft[t * fr.m + node],
                              fr.cat_bnd + t * fr.C1, fr.C1,
                              fr.cat_words + t * fr.W, fr.W);
        } else {
          go_left = x <= tthr[node];  // NaN -> right, as in the XLA walk
        }
        node = go_left ? tleft[node] : tright[node];
      }
      int64_t li = -static_cast<int64_t>(node) - 1;
      if (li >= fr.L) li = fr.L - 1;
      orow[t % fr.K] += fr.leaf[t * fr.L + li];
    }
  }
}

PyObject* PredictForest(PyObject*, PyObject* args) {
  PyObject *xo, *feato, *thro, *lefto, *righto, *leafo, *singleo, *is_cato,
      *dlefto, *bndo, *wordso, *outo;
  int K, has_cat, n_threads;
  if (!PyArg_ParseTuple(args, "OOOOOOOOOOOiiiO", &xo, &feato, &thro, &lefto,
                        &righto, &leafo, &singleo, &is_cato, &dlefto, &bndo,
                        &wordso, &K, &has_cat, &n_threads, &outo)) {
    return nullptr;
  }
  Buf x, feat, thr, left, right, leaf, single, is_cat, dleft, bnd, words, out;
  if (!x.Get(xo, "X", 4) || !feat.Get(feato, "feat", 4) ||
      !thr.Get(thro, "thr", 4) || !left.Get(lefto, "left", 4) ||
      !right.Get(righto, "right", 4) || !leaf.Get(leafo, "leaf", 4) ||
      !single.Get(singleo, "single", 1) || !is_cat.Get(is_cato, "is_cat", 4) ||
      !dleft.Get(dlefto, "dleft", 4) || !bnd.Get(bndo, "cat_bnd", 4) ||
      !words.Get(wordso, "cat_words", 4) ||
      !out.Get(outo, "out", 4, /*writable=*/true)) {
    return nullptr;
  }
  if (x.view.ndim != 2 || feat.view.ndim != 2 || leaf.view.ndim != 2 ||
      bnd.view.ndim != 2 || words.view.ndim != 2 || out.view.ndim != 2) {
    PyErr_SetString(PyExc_ValueError, "X/feat/leaf/cat_bnd/cat_words/out "
                                      "must be 2-D");
    return nullptr;
  }
  // Every per-node array must be (T, m) like feat, and every per-tree
  // array must lead with T — the walk indexes them all with feat's
  // extents, so a mismatch is an out-of-bounds read, not a softer bug.
  const int64_t Tn = feat.view.shape[0], mn = feat.view.shape[1];
  const struct { const Py_buffer* v; const char* name; } node_arrs[] = {
      {&thr.view, "thr"},       {&left.view, "left"},
      {&right.view, "right"},   {&is_cat.view, "is_cat"},
      {&dleft.view, "dleft"}};
  for (const auto& a : node_arrs) {
    if (a.v->ndim != 2 || a.v->shape[0] != Tn || a.v->shape[1] != mn) {
      PyErr_Format(PyExc_ValueError, "%s must have feat's shape (T, m)",
                   a.name);
      return nullptr;
    }
  }
  if (single.view.ndim != 1 || single.view.shape[0] != Tn ||
      leaf.view.shape[0] != Tn || bnd.view.shape[0] != Tn ||
      words.view.shape[0] != Tn) {
    PyErr_SetString(PyExc_ValueError,
                    "single/leaf/cat_bnd/cat_words must lead with T trees");
    return nullptr;
  }
  if (leaf.view.shape[1] < 1 || bnd.view.shape[1] < 2 ||
      words.view.shape[1] < 1 || x.view.shape[1] < 1 || K < 1) {
    PyErr_SetString(PyExc_ValueError,
                    "leaf/cat_bnd/cat_words/X widths and K must be >= 1");
    return nullptr;
  }
  Forest fr;
  fr.feat = static_cast<const int32_t*>(feat.view.buf);
  fr.thr = static_cast<const float*>(thr.view.buf);
  fr.left = static_cast<const int32_t*>(left.view.buf);
  fr.right = static_cast<const int32_t*>(right.view.buf);
  fr.leaf = static_cast<const float*>(leaf.view.buf);
  fr.single = static_cast<const uint8_t*>(single.view.buf);
  fr.is_cat = static_cast<const int32_t*>(is_cat.view.buf);
  fr.dleft = static_cast<const int32_t*>(dleft.view.buf);
  fr.cat_bnd = static_cast<const int32_t*>(bnd.view.buf);
  fr.cat_words = static_cast<const uint32_t*>(words.view.buf);
  fr.T = feat.view.shape[0];
  fr.m = feat.view.shape[1];
  fr.L = leaf.view.shape[1];
  fr.C1 = bnd.view.shape[1];
  fr.W = words.view.shape[1];
  fr.K = K;
  fr.has_cat = has_cat != 0;
  const int64_t n = x.view.shape[0];
  const int64_t f = x.view.shape[1];
  const float* X = static_cast<const float*>(x.view.buf);
  float* O = static_cast<float*>(out.view.buf);
  if (out.view.shape[0] != n || out.view.shape[1] != K) {
    PyErr_SetString(PyExc_ValueError, "out must be (n, K)");
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS;
  int nt = n_threads > 0 ? n_threads
                         : static_cast<int>(
                               std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (nt > 1 && n >= 4096) {
    std::vector<std::thread> pool;
    const int64_t step = (n + nt - 1) / nt;
    for (int w = 0; w < nt; ++w) {
      const int64_t r0 = w * step;
      const int64_t r1 = r0 + step < n ? r0 + step : n;
      if (r0 >= r1) break;
      pool.emplace_back(
          [&fr, X, f, r0, r1, O]() { PredictRows(fr, X, f, r0, r1, O); });
    }
    for (auto& th : pool) th.join();
  } else {
    PredictRows(fr, X, f, 0, n, O);
  }
  Py_END_ALLOW_THREADS;
  Py_RETURN_NONE;
}

PyMethodDef kMethods[] = {
    {"predict_forest", PredictForest, METH_VARARGS,
     "Early-exit forest margin accumulation into a preallocated (n, K) "
     "float32 output."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "_fastforest",
                       "Native forest scorer", -1, kMethods,
                       nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__fastforest() { return PyModule_Create(&kModule); }
