"""Native runtime extensions (C++), with build-on-demand and fallback.

The reference backs its IO layer with JVM/Hadoop native streams; here the
equivalent is a small C++ extension (``fastio.cc``) compiled on first use
with the in-image toolchain.  Public surface:

* ``available() -> bool`` — whether the extension loaded (or could be
  built); all callers must keep a pure-Python fallback.
* ``read_file(path) -> bytes``
* ``read_files(paths, n_threads=8) -> list[bytes]`` — thread-pool bulk
  read with the GIL released.
* ``scan_dir(root, pattern, recursive) -> [(path, size, mtime)]``

Set ``MMLSPARK_TPU_NO_NATIVE=1`` to force the Python fallbacks.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
from typing import List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_mod = None
_tried = False


def _so_path() -> str:
    tag = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_HERE, f"_fastio{tag}")


def _build() -> bool:
    """Compile fastio.cc with g++ (or cc) into the package directory."""
    src = os.path.join(_HERE, "fastio.cc")
    out = _so_path()
    include = sysconfig.get_paths()["include"]
    for cxx in ("g++", "c++", "clang++"):
        try:
            proc = subprocess.run(
                [cxx, "-O2", "-std=c++17", "-shared", "-fPIC",
                 f"-I{include}", src, "-o", out, "-pthread"],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if proc.returncode == 0:
            return True
    return False


def _load():
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    _tried = True
    if os.environ.get("MMLSPARK_TPU_NO_NATIVE"):
        return None
    if not os.path.exists(_so_path()) and not _build():
        return None
    try:
        sys.path.insert(0, _HERE)
        import _fastio  # noqa: PLC0415
        _mod = _fastio
    except ImportError:
        _mod = None
    finally:
        if _HERE in sys.path:
            sys.path.remove(_HERE)
    return _mod


def available() -> bool:
    return _load() is not None


def read_file(path: str) -> bytes:
    mod = _load()
    if mod is not None:
        return mod.read_file(path)
    with open(path, "rb") as f:
        return f.read()


def read_files(paths: List[str], n_threads: int = 8) -> List[bytes]:
    mod = _load()
    if mod is not None:
        return mod.read_files(list(paths), n_threads)
    return [read_file(p) for p in paths]


def murmur3_batch(terms: List[str], seed: int = 42) -> List[int]:
    """Spark-compatible Murmur3_x86_32 of each term's UTF-8 bytes, as
    signed int32 (C++ path only; callers gate on :func:`available` and
    fall back to featurize.hashing's pure-python murmur3_32)."""
    mod = _load()
    if mod is None:
        raise RuntimeError(
            "mmlspark_tpu.native extension unavailable; use the "
            "pure-python hasher (featurize.hashing.murmur3_32)")
    return mod.murmur3_batch(list(terms), seed)


def scan_dir(root: str, pattern: Optional[str] = None,
             recursive: bool = True) -> List[Tuple[str, int, float]]:
    mod = _load()
    if mod is not None:
        return mod.scan_dir(root, pattern, recursive)
    import fnmatch
    out: List[Tuple[str, int, float]] = []

    def walk(d: str):
        names = sorted(os.listdir(d))
        subdirs = []
        for name in names:
            full = os.path.join(d, name)
            if os.path.isdir(full):
                if not os.path.islink(full):   # no symlink-dir recursion
                    subdirs.append(full)
            elif os.path.isfile(full) and (
                    pattern is None or fnmatch.fnmatch(name, pattern)):
                st = os.stat(full)
                out.append((full, int(st.st_size), float(st.st_mtime)))
        if recursive:
            for sd in subdirs:
                walk(sd)

    walk(root)
    return out
