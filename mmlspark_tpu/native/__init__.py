"""Native runtime extensions (C++), with build-on-demand and fallback.

The reference backs its IO layer and compute hot loops with JVM/Hadoop
native streams and LightGBM C++; here the equivalents are small C++
extensions compiled on first use with the in-image toolchain:

* ``fastio.cc``  — directory scan / bulk parallel file read / murmur3.
* ``fastbin.cc`` — the BinMapper quantization inner loop
  (``bin_columns``), the single-core-hostile part of dataset prep.
* ``fasthist_ffi.cc`` — XLA FFI custom-call gradient-histogram kernel
  for the CPU backend's GBDT hot loop (``hist_ffi_handler``), compiled
  against jaxlib's bundled ``xla/ffi/api`` headers.

Public surface:

* ``available() -> bool`` — whether the IO extension loaded (or could be
  built); all callers must keep a pure-Python fallback.
* ``read_file(path) -> bytes``
* ``read_files(paths, n_threads=8) -> list[bytes]`` — thread-pool bulk
  read with the GIL released.
* ``scan_dir(root, pattern, recursive) -> [(path, size, mtime)]``
* ``bin_columns_available() -> bool`` / ``bin_columns(...)`` — native
  binning kernel (callers fall back to numpy searchsorted).

Set ``MMLSPARK_TPU_NO_NATIVE=1`` to force the Python fallbacks.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
from typing import List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_mods = {}


def _so_path(stem: str) -> str:
    tag = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_HERE, f"{stem}{tag}")


def _build(src_name: str, stem: str) -> bool:
    """Compile one .cc with g++ (or cc) into the package directory."""
    src = os.path.join(_HERE, src_name)
    out = _so_path(stem)
    include = sysconfig.get_paths()["include"]
    for cxx in ("g++", "c++", "clang++"):
        try:
            proc = subprocess.run(
                [cxx, "-O2", "-std=c++17", "-shared", "-fPIC",
                 f"-I{include}", src, "-o", out, "-pthread"],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if proc.returncode == 0:
            return True
    return False


def _fresh(out_path: str, src_path: str) -> bool:
    """A built artifact is fresh when it exists and is no older than its
    source (a missing source can't invalidate it)."""
    return (os.path.exists(out_path)
            and (not os.path.exists(src_path)
                 or os.path.getmtime(out_path) >= os.path.getmtime(src_path)))


def _load(stem: str = "_fastio", src_name: str = "fastio.cc"):
    if stem in _mods:
        return _mods[stem]
    _mods[stem] = None
    if os.environ.get("MMLSPARK_TPU_NO_NATIVE"):
        return None
    so = _so_path(stem)
    src = os.path.join(_HERE, src_name)
    # stale .so + failed rebuild (no compiler / read-only dir): still load
    # the old binary rather than silently losing the native path
    if not _fresh(so, src) and not _build(src_name, stem) \
            and not os.path.exists(so):
        return None
    try:
        sys.path.insert(0, _HERE)
        _mods[stem] = __import__(stem)
    except ImportError:
        _mods[stem] = None
    finally:
        if _HERE in sys.path:
            sys.path.remove(_HERE)
    return _mods[stem]


def available() -> bool:
    return _load() is not None


def bin_columns_available() -> bool:
    return _load("_fastbin", "fastbin.cc") is not None


def predict_forest_available() -> bool:
    return _load("_fastforest", "fastforest.cc") is not None


def predict_forest(X, feat, thr, left, right, leaf, single, is_cat, dleft,
                   cat_bnd, cat_words, num_class, has_cat, out,
                   n_threads: int = 0) -> None:
    """Native early-exit forest margin accumulation into ``out`` (n, K)
    float32; see fastforest.cc for the exactness contract vs the jitted
    walk.  Raises RuntimeError when the extension is unavailable
    (callers gate on :func:`predict_forest_available`)."""
    mod = _load("_fastforest", "fastforest.cc")
    if mod is None:
        raise RuntimeError("mmlspark_tpu.native._fastforest unavailable; "
                           "use the jitted _predict_forest path")
    mod.predict_forest(X, feat, thr, left, right, leaf, single, is_cat,
                       dleft, cat_bnd, cat_words, int(num_class),
                       int(bool(has_cat)), int(n_threads), out)


_FFI_LIB = None


def _build_ffi(src_name: str, stem: str) -> bool:
    """Compile an XLA FFI shared lib against jaxlib's bundled headers."""
    src = os.path.join(_HERE, src_name)
    # ".bin", not ".so": a bare .so in the package dir would be picked up
    # as a CPython extension module by pkgutil walkers (it isn't one)
    out = os.path.join(_HERE, f"{stem}.bin")
    try:
        try:
            from jax import ffi as _jffi        # jax >= 0.4.38
        except ImportError:
            from jax.extend import ffi as _jffi  # 0.4.3x series
        ffi_inc = _jffi.include_dir()
    except Exception:  # noqa: BLE001 - ancient jax
        return False
    for cxx in ("g++", "c++", "clang++"):
        try:
            proc = subprocess.run(
                [cxx, "-O2", "-std=c++17", "-shared", "-fPIC",
                 f"-I{ffi_inc}", src, "-o", out],
                capture_output=True, text=True, timeout=180)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if proc.returncode == 0:
            return True
    return False


def _ffi_lib():
    global _FFI_LIB
    if _FFI_LIB is None:
        _FFI_LIB = False
        if not os.environ.get("MMLSPARK_TPU_NO_NATIVE"):
            path = os.path.join(_HERE, "fasthist_ffi.bin")
            src = os.path.join(_HERE, "fasthist_ffi.cc")
            if _fresh(path, src) or _build_ffi("fasthist_ffi.cc",
                                               "fasthist_ffi"):
                import ctypes
                try:
                    _FFI_LIB = ctypes.cdll.LoadLibrary(path)
                except OSError:
                    _FFI_LIB = False
    return _FFI_LIB


def hist_ffi_handler():
    """ctypes function pointer for the XLA FFI histogram custom call
    (fasthist_ffi.cc), or None when the lib can't build/load.  Callers
    wrap it with ``jax.ffi.pycapsule`` and register under platform
    "cpu"."""
    lib = _ffi_lib()
    return getattr(lib, "MmlsparkFastHist", None) if lib else None


def seg_hist_ffi_handler():
    """Dynamic-offset segment histogram FFI handler (leaf hot path)."""
    lib = _ffi_lib()
    return getattr(lib, "MmlsparkFastSegHist", None) if lib else None


def partition_ffi_handler():
    """In-place DataPartition::Split FFI handler."""
    lib = _ffi_lib()
    return getattr(lib, "MmlsparkFastPartition", None) if lib else None


def split_ffi_handler():
    """Numeric best-split scan FFI handler (serial-path FindBestThreshold)."""
    lib = _ffi_lib()
    return getattr(lib, "MmlsparkFastSplit", None) if lib else None


def qhist_ffi_handler():
    """Quantized-gradient histogram FFI handler (ISSUE 17): int16 grid
    codes in, int32 accumulation out, with a packed-int64 single-add
    fast mode under the headroom bound (ops/histogram.packed_accum_ok)."""
    lib = _ffi_lib()
    return getattr(lib, "MmlsparkFastQHist", None) if lib else None


def seg_qhist_ffi_handler():
    """Quantized dynamic-offset segment histogram FFI handler."""
    lib = _ffi_lib()
    return getattr(lib, "MmlsparkFastSegQHist", None) if lib else None


def bin_columns(X, bext, nb, base, lo, scale, use_table, missing_bin,
                out) -> None:
    """Native BinMapper transform; see fastbin.cc for the argument
    contract.  Raises RuntimeError when the extension is unavailable
    (callers gate on :func:`bin_columns_available`)."""
    mod = _load("_fastbin", "fastbin.cc")
    if mod is None:
        raise RuntimeError("mmlspark_tpu.native._fastbin unavailable; use "
                           "the numpy searchsorted path")
    mod.bin_columns(X, bext, nb, base, lo, scale, use_table, missing_bin,
                    out)


def read_file(path: str) -> bytes:
    mod = _load()
    if mod is not None:
        return mod.read_file(path)
    with open(path, "rb") as f:
        return f.read()


def read_files(paths: List[str], n_threads: int = 8) -> List[bytes]:
    mod = _load()
    if mod is not None:
        return mod.read_files(list(paths), n_threads)
    return [read_file(p) for p in paths]


def murmur3_batch(terms: List[str], seed: int = 42) -> List[int]:
    """Spark-compatible Murmur3_x86_32 of each term's UTF-8 bytes, as
    signed int32 (C++ path only; callers gate on :func:`available` and
    fall back to featurize.hashing's pure-python murmur3_32)."""
    mod = _load()
    if mod is None:
        raise RuntimeError(
            "mmlspark_tpu.native extension unavailable; use the "
            "pure-python hasher (featurize.hashing.murmur3_32)")
    return mod.murmur3_batch(list(terms), seed)


def scan_dir(root: str, pattern: Optional[str] = None,
             recursive: bool = True) -> List[Tuple[str, int, float]]:
    mod = _load()
    if mod is not None:
        return mod.scan_dir(root, pattern, recursive)
    import fnmatch
    out: List[Tuple[str, int, float]] = []

    def walk(d: str):
        names = sorted(os.listdir(d))
        subdirs = []
        for name in names:
            full = os.path.join(d, name)
            if os.path.isdir(full):
                if not os.path.islink(full):   # no symlink-dir recursion
                    subdirs.append(full)
            elif os.path.isfile(full) and (
                    pattern is None or fnmatch.fnmatch(name, pattern)):
                st = os.stat(full)
                out.append((full, int(st.st_size), float(st.st_mtime)))
        if recursive:
            for sd in subdirs:
                walk(sd)

    walk(root)
    return out
