// Native feature-binning kernel for the GBDT BinMapper.
//
// TPU-native replacement for the quantization inner loop the reference
// runs inside LightGBM's C++ Dataset construction
// (LGBM_DatasetCreateFromMat -> DenseBin<...>::Push; expected path,
// UNVERIFIED -- SURVEY.md SS2.2, SS3.1): raw float features -> per-feature
// quantile bin indices.  numpy/torch searchsorted needs ~3 s for the
// 400k x 50 bench matrix on this box's single core; this kernel does the
// same mapping exactly in ~0.2 s via an interpolation-table hint plus a
// local probe, falling back to branch-free binary search where the hint
// table would degenerate.
//
// Exactness contract: callers pass float32 bounds ADJUSTED DOWNWARD to the
// largest float32 <= the true float64 bound, which makes (bound < v)
// decisions identical to float64 for every float32 input v (binning.py
// documents the proof).  float64 inputs use the raw float64 bounds.
//
// CPython C API only -- no pybind11 in this image.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>

namespace {

struct Buf {
  Py_buffer view;
  bool held = false;
  ~Buf() {
    if (held) PyBuffer_Release(&view);
  }
  bool Get(PyObject* obj, const char* name, int itemsize) {
    if (PyObject_GetBuffer(obj, &view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) !=
        0) {
      return false;
    }
    held = true;
    if (view.itemsize != itemsize) {
      PyErr_Format(PyExc_TypeError, "%s: expected itemsize %d, got %zd", name,
                   itemsize, view.itemsize);
      return false;
    }
    return true;
  }
};

// Shared kernel.  T is the raw feature type; BT the bound type (float for
// adjusted-f32 bounds, double for raw-f64 bounds).
template <typename T, typename BT>
void BinColumns(const T* x, int64_t n, int64_t f, const BT* bext, int64_t m,
                const int32_t* nb, const int32_t* base, int64_t cells,
                const float* lo, const float* scale, const uint8_t* use_table,
                int missing_bin, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const T* xrow = x + i * f;
    uint8_t* orow = out + i * f;
    for (int64_t j = 0; j < f; ++j) {
      T v = xrow[j];
      if (v != v) {  // NaN
        orow[j] = static_cast<uint8_t>(missing_bin);
        continue;
      }
      int32_t nbj = nb[j];
      if (nbj == 0) {
        orow[j] = 0;
        continue;
      }
      const BT* be = bext + j * m;
      int32_t b;
      if (use_table[j]) {
        // hint from the uniform grid, then probe.  The hint only has to
        // be *near* the answer: the two probe loops correct either way,
        // so float rounding in the k computation cannot misbin.
        float kf = (static_cast<float>(v) - lo[j]) * scale[j];
        // range-check BEFORE the int cast: casting non-finite or
        // out-of-range floats to int64 is UB (huge f64 inputs overflow the
        // f32 cast to +/-inf; !(kf >= 0) also catches NaN)
        int64_t k;
        if (!(kf >= 0.0f)) {
          k = 0;
        } else if (kf >= static_cast<float>(cells)) {
          k = cells - 1;
        } else {
          k = static_cast<int64_t>(kf);
        }
        b = base[j * cells + k];
        while (b > 0 && !(be[b - 1] < v)) --b;
        while (b < nbj && be[b] < v) ++b;
      } else {
        // first index with be[idx] >= v  ==  count of bounds < v
        b = static_cast<int32_t>(
            std::lower_bound(be, be + nbj, v,
                             [](BT a, T val) { return a < val; }) -
            be);
      }
      orow[j] = static_cast<uint8_t>(b);
    }
  }
}

// bin_columns(X, bext, nb, base, lo, scale, use_table, missing_bin, out)
//   X:         (n, f) float32 or float64, C-contiguous
//   bext:      (f, m) bounds, float32 (adjusted) for f32 X, float64 for f64
//   nb:        (f,)   int32   bounds per feature
//   base:      (f, C) int32   grid hint table (C may be 1 when unused)
//   lo, scale: (f,)   float32 grid origin / inverse cell width
//   use_table: (f,)   uint8   1 = grid+probe, 0 = binary search
//   out:       (n, f) uint8   written in place
PyObject* py_bin_columns(PyObject*, PyObject* args) {
  PyObject *xo, *bo, *nbo, *baseo, *loo, *scaleo, *uto, *outo;
  int missing_bin;
  if (!PyArg_ParseTuple(args, "OOOOOOOiO", &xo, &bo, &nbo, &baseo, &loo,
                        &scaleo, &uto, &missing_bin, &outo)) {
    return nullptr;
  }
  Buf xb;
  if (PyObject_GetBuffer(xo, &xb.view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) !=
      0) {
    return nullptr;
  }
  xb.held = true;
  bool is64 = xb.view.itemsize == 8;
  if (!is64 && xb.view.itemsize != 4) {
    PyErr_SetString(PyExc_TypeError, "X must be float32 or float64");
    return nullptr;
  }
  if (xb.view.ndim != 2) {
    PyErr_SetString(PyExc_TypeError, "X must be 2-D");
    return nullptr;
  }
  int64_t n = xb.view.shape[0], f = xb.view.shape[1];

  Buf bb, nbb, baseb, lob, scaleb, utb, outb;
  if (!bb.Get(bo, "bext", is64 ? 8 : 4)) return nullptr;
  if (!nbb.Get(nbo, "nb", 4)) return nullptr;
  if (!baseb.Get(baseo, "base", 4)) return nullptr;
  if (!lob.Get(loo, "lo", 4)) return nullptr;
  if (!scaleb.Get(scaleo, "scale", 4)) return nullptr;
  if (!utb.Get(uto, "use_table", 1)) return nullptr;
  if (!outb.Get(outo, "out", 1)) return nullptr;
  if (bb.view.ndim != 2 || bb.view.shape[0] != f || baseb.view.ndim != 2 ||
      baseb.view.shape[0] != f || outb.view.ndim != 2 ||
      outb.view.shape[0] != n || outb.view.shape[1] != f ||
      nbb.view.shape[0] != f || lob.view.shape[0] != f ||
      scaleb.view.shape[0] != f || utb.view.shape[0] != f) {
    PyErr_SetString(PyExc_ValueError, "bin_columns: shape mismatch");
    return nullptr;
  }
  if (outb.view.readonly) {
    PyErr_SetString(PyExc_ValueError, "out must be writable");
    return nullptr;
  }
  int64_t m = bb.view.shape[1];
  int64_t cells = baseb.view.shape[1];

  const auto* nb = static_cast<const int32_t*>(nbb.view.buf);
  const auto* base = static_cast<const int32_t*>(baseb.view.buf);
  const auto* lo = static_cast<const float*>(lob.view.buf);
  const auto* scale = static_cast<const float*>(scaleb.view.buf);
  const auto* ut = static_cast<const uint8_t*>(utb.view.buf);
  auto* out = static_cast<uint8_t*>(outb.view.buf);

  Py_BEGIN_ALLOW_THREADS;
  if (is64) {
    BinColumns<double, double>(static_cast<const double*>(xb.view.buf), n, f,
                               static_cast<const double*>(bb.view.buf), m, nb,
                               base, cells, lo, scale, ut, missing_bin, out);
  } else {
    BinColumns<float, float>(static_cast<const float*>(xb.view.buf), n, f,
                             static_cast<const float*>(bb.view.buf), m, nb,
                             base, cells, lo, scale, ut, missing_bin, out);
  }
  Py_END_ALLOW_THREADS;
  Py_RETURN_NONE;
}

PyMethodDef kMethods[] = {
    {"bin_columns", py_bin_columns, METH_VARARGS,
     "bin_columns(X, bext, nb, base, lo, scale, use_table, missing_bin, out)"
     " -> None (fills out in place)"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "_fastbin",
                       "native feature-binning kernel (BinMapper hot loop)",
                       -1, kMethods};

}  // namespace

PyMODINIT_FUNC PyInit__fastbin() { return PyModule_Create(&kModule); }
