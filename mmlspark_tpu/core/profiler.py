"""Continuous performance profiler — always-on cost attribution
(ISSUE 12).

The observability stack so far (telemetry, traces, SLOs, flight
recorder) can say *that* a request or a fit was slow, but not *why*:
there was no compile/dispatch attribution, no host-path phase profile,
and no automated detection when a change regresses the committed bench
numbers.  This module is the attribution half (the regression half is
``tools/perf_sentinel.py``); three sources, all cheap enough to stay on
in production:

* **Phase attribution** — the known hot paths feed
  :meth:`Profiler.record_phase` with durations they already measured
  (the scoring engine's form/decode/score/reply, the transport's
  encode/decode/wire-write, the GBDT engine's boost-chunk host glue,
  the fleet's fan-out/wait/reduce).  Phases accumulate into one
  :class:`~mmlspark_tpu.core.profiling.StageStats` — the same
  log-bucket histograms the rest of telemetry uses, so snapshots merge
  cross-process with :func:`~mmlspark_tpu.core.telemetry.
  merge_snapshots` and ``tools/perf_report.py`` can recompute exact
  percentiles over a whole topology.
* **JAX events** — a ``jax.monitoring`` duration listener accumulates
  per-event compile counts and cumulative seconds
  (``backend_compile``, ``jaxpr_trace``, ...), and a process-monotonic
  :meth:`compile_seq` lets any dispatch site classify its own calls as
  cache HIT vs MISS without touching jit internals: read the sequence
  before and after the call — if it moved, this dispatch compiled.
  :meth:`dispatch` records the split host-dispatch /
  materialization-wait timings (the ``block_until_ready``-style
  bracketing PERF.md's "per-dispatch host glue" hunt needs) plus the
  hit/miss ledger per site.  Device/HBM watermarks are sampled from
  ``device.memory_stats()`` where the backend exposes it (TPU/GPU;
  CPU returns none).
* **Sampling** — an OPT-IN ~100 Hz thread-stack sampler over the
  worker/pump threads producing collapsed-stack flamegraph lines
  (``a;b;c 42``).  Off by default; when on, a duty-cycle gate keeps
  its own cost under ~5% of a core no matter how slow
  ``sys._current_frames`` is on the host.

Exposition: the ``mmlspark_tpu_profile_*`` families join every
``/metrics`` scrape through the registry's exposition-provider hook
(see docs/observability.md §Profiling); :meth:`snapshot` is the
JSON-able block embedded in flight records and bench artifacts and
consumed by ``tools/perf_report.py``.

Overhead contract: with the profiler DISABLED every hook is one
attribute check; ENABLED, a phase record is a dict lookup plus one
log-bucket histogram insert (no allocation, no syscall).  The tier-1
overhead test pins the enabled-vs-disabled p50 delta of a closed-loop
scoring burst under 3%.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from .profiling import LatencyStats, StageStats
from .telemetry import (PREFIX, _fmt, _labels, current_fit_span,
                        get_journal, get_registry)

__all__ = ["Profiler", "get_profiler", "install_jax_hooks",
           "PROFILER_ENV"]

#: set to ``"0"`` to disable the always-on profiler process-wide (the
#: overhead A/B in tools/perf_sentinel.py and the tier-1 overhead test
#: flip Profiler.configure instead — same switch, no env round-trip)
PROFILER_ENV = "MMLSPARK_TPU_PROFILER"

#: jax.monitoring event key substring that marks an actual backend
#: compilation (a cache MISS somewhere in the process)
_COMPILE_EVENT = "backend_compile"


def _jax_backend_initialized(jax, prof: "Profiler") -> bool:
    """True only when the process ALREADY initialized a jax backend —
    never a trigger for that initialization.  Peeks the xla_bridge
    backend cache; on API drift, falls back to evidence the process
    compiled something (the monitoring listener saw an event)."""
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:  # noqa: BLE001 - private API moved
        return prof._compile_seq > 0 or bool(prof._jax_events)


def _short_event(name: str) -> str:
    """``/jax/core/compile/backend_compile_duration`` →
    ``backend_compile`` — the label value the exposition carries."""
    short = name.rsplit("/", 1)[-1]
    if short.endswith("_duration"):
        short = short[: -len("_duration")]
    return short


class Profiler:
    """Process-wide performance attribution.  One instance per process
    (:func:`get_profiler`); every hook is safe from any thread."""

    #: journal profile spans only when they exceed this (keeps the
    #: bounded journal ring from flooding with per-request spans);
    #: callers may force with ``journal=True``
    SPAN_JOURNAL_MS = 50.0

    def __init__(self, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get(PROFILER_ENV, "1") != "0"
        self.enabled = bool(enabled)
        #: phase timers — StageStats so the snapshot merges like every
        #: other telemetry source
        self.stats = StageStats()
        self._timers: Dict[str, LatencyStats] = {}
        self._lock = threading.Lock()
        #: jax.monitoring accumulation: short event name -> [n, total_s]
        self._jax_events: Dict[str, List[float]] = {}
        self._compile_seq = 0
        #: per-site dispatch ledger: site -> {"hits": n, "misses": n}
        self._dispatch: Dict[str, Dict[str, int]] = {}
        #: (device, kind) -> bytes, refreshed by sample_memory()
        self._mem: Dict[Tuple[str, str], float] = {}
        self._mem_t = 0.0
        # sampler state
        self._sampler_stop = threading.Event()
        self._sampler_thread: Optional[threading.Thread] = None
        self._samples = 0
        self._stacks: Dict[str, int] = {}
        self._stacks_cap = 4096

    # ---- configuration ----

    def configure(self, enabled: Optional[bool] = None) -> "Profiler":
        if enabled is not None:
            self.enabled = bool(enabled)
        return self

    # ---- phase attribution ----

    def timer(self, phase: str) -> LatencyStats:
        """Resolve the phase's histogram ONCE — per-frame/per-batch
        call sites cache the returned object and record directly
        (``if prof.enabled: t.record(dt)``), skipping the dict lookup
        and call overhead of :meth:`record_phase` on every hit."""
        t = self._timers.get(phase)
        if t is None:
            with self._lock:
                t = self._timers.setdefault(phase,
                                            self.stats.timer(phase))
        return t

    def alias(self, phase: str, timer: LatencyStats) -> None:
        """Expose an EXISTING histogram (one a hot path already
        records into — the scoring engine's stage timers, the
        transport's codec timers) under ``phase`` in the profile view.
        This is the zero-overhead attribution path: the phase shows up
        in ``mmlspark_tpu_profile_phase_seconds`` and the snapshot
        without a single extra record on the hot path.  Replaces any
        previous alias — the newest engine instance wins, matching the
        registry's namespace semantics."""
        with self._lock:
            self._timers[phase] = timer
            self.stats.adopt(phase, timer)

    def record_phase(self, phase: str, seconds: float) -> None:
        """Accumulate an already-measured duration under ``phase``.
        The hot paths call this with timings they measured anyway, so
        an enabled profiler adds one histogram insert per call and a
        disabled one adds a single attribute check."""
        if not self.enabled:
            return
        self.timer(phase).record(seconds)

    @contextmanager
    def phase(self, name: str):
        """Scoped timer for call sites that don't already clock
        themselves."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_phase(name, time.perf_counter() - t0)

    def span(self, name: str, seconds: float, journal: bool = False,
             record: bool = True, **ids) -> None:
        """Record a phase AND journal a ``profile_span`` event (with
        the current fit span and any caller ids — trace ids ride
        ``tid=``) when the span is slow enough to matter or the caller
        forces it.  This is what puts per-hop costs on the
        ``tools/trace_report.py`` timelines.  ``record=False`` journals
        only — for call sites whose phase is an ALIASED timer they
        already recorded into (a second record would double-count)."""
        if not self.enabled:
            return
        if record:
            self.record_phase(name, seconds)
        dur_ms = seconds * 1e3
        if journal or dur_ms >= self.SPAN_JOURNAL_MS:
            get_journal().emit("profile_span", phase=name,
                               dur_ms=round(dur_ms, 3),
                               fit=current_fit_span(), **ids)

    # ---- JAX events ----

    def _on_jax_duration(self, name: str, secs: float, **kw) -> None:
        """jax.monitoring duration listener (installed once per
        process by :func:`install_jax_hooks`)."""
        if not self.enabled:
            return
        short = _short_event(name)
        with self._lock:
            ent = self._jax_events.setdefault(short, [0, 0.0])
            ent[0] += 1
            ent[1] += float(secs)
            if _COMPILE_EVENT in short:
                self._compile_seq += 1

    def compile_seq(self) -> int:
        """Process-monotonic compile counter: bumped once per backend
        compilation.  Bracket any jitted call with it to classify the
        dispatch as cache hit (unchanged) or miss (moved)."""
        return self._compile_seq

    def count_dispatch(self, site: str, misses: int = 0) -> None:
        """Ledger-only dispatch accounting (the cheapest hook: one
        lock).  ``misses`` is the :meth:`compile_seq` delta over the
        bracketed call — 0 means the dispatch rode the compile cache.
        ONE dispatch contributes ONE ledger entry (hit or miss), no
        matter how many backend compiles its jaxpr triggered — the raw
        compile count lives in the ``jax_events`` family.  Caveat: the
        sequence is process-global, so a dispatch whose window overlaps
        ANOTHER site's compile (e.g. a refit while serving) is
        conservatively counted as a miss for this site."""
        with self._lock:
            ent = self._dispatch.setdefault(site,
                                            {"hits": 0, "misses": 0})
            if misses > 0:
                ent["misses"] += 1
            else:
                ent["hits"] += 1

    def dispatch(self, site: str, host_s: float, wait_s: float,
                 misses: int = 0) -> None:
        """One bracketed dispatch at ``site``: ``host_s`` is the wall
        time until the jitted call returned (tracing + dispatch glue,
        the PERF.md "host glue"), ``wait_s`` the further wall time
        until the result materialized (``block_until_ready`` /
        ``np.asarray`` bracketing — device compute plus D2H).
        ``misses`` is the :meth:`compile_seq` delta over the call.
        Per-batch call sites pre-resolve the two timers and call
        :meth:`count_dispatch` instead."""
        if not self.enabled:
            return
        self.record_phase(f"{site}.dispatch_host", host_s)
        self.record_phase(f"{site}.device_wait", wait_s)
        self.count_dispatch(site, misses)

    # ---- memory watermarks ----

    def record_memory(self, device: str, kind: str,
                      nbytes: float) -> None:
        with self._lock:
            self._mem[(str(device), str(kind))] = float(nbytes)

    def sample_memory(self, min_interval_s: float = 1.0) -> None:
        """Refresh device/HBM watermarks from ``device.memory_stats()``
        where the backend exposes it.  Rate-limited; a backend without
        memory stats (CPU) contributes nothing.  Never imports jax —
        only reads it if the process already did."""
        if not self.enabled:
            return
        jax = sys.modules.get("jax")
        if jax is None or not _jax_backend_initialized(jax, self):
            # imported-but-uninitialized jax: reading local_devices()
            # would INITIALIZE the backend as a side effect of a
            # metrics scrape (multi-second stall; on a TPU box it can
            # grab the chip in a process that scores natively) — skip
            return
        now = time.monotonic()
        with self._lock:
            if now - self._mem_t < min_interval_s:
                return
            self._mem_t = now
        try:
            for d in jax.local_devices():
                stats = (d.memory_stats()
                         if hasattr(d, "memory_stats") else None)
                if not stats:
                    continue
                label = f"{d.platform}:{d.id}"
                for kind in ("bytes_in_use", "peak_bytes_in_use",
                             "bytes_limit"):
                    if kind in stats:
                        self.record_memory(label, kind, stats[kind])
        except Exception:  # noqa: BLE001 - a watermark read must never
            pass           # hurt the path it observes

    # ---- stack sampler (opt-in) ----

    def start_sampler(self, hz: float = 100.0,
                      thread_prefixes: Optional[Tuple[str, ...]] = None,
                      max_stacks: int = 4096,
                      duty_cap: float = 0.05) -> "Profiler":
        """Start the opt-in collapsed-stack sampler: ~``hz`` snapshots
        of every (filtered) thread's Python stack per second.
        ``thread_prefixes`` limits sampling to threads whose name
        starts with one of them (default: every thread but the sampler
        itself).  ``duty_cap`` bounds the sampler's own CPU share: if a
        snapshot costs c seconds the next sleep is at least
        ``c * (1/duty_cap - 1)``, so a slow ``sys._current_frames`` on
        a big process degrades the RATE, never the host."""
        if self._sampler_thread is not None:
            return self
        self._sampler_stop.clear()
        interval = 1.0 / max(1e-3, float(hz))
        self._stacks_cap = int(max_stacks)

        def loop():
            me = threading.get_ident()
            while not self._sampler_stop.is_set():
                t0 = time.perf_counter()
                try:
                    names = {t.ident: t.name
                             for t in threading.enumerate()}
                    for ident, frame in sys._current_frames().items():
                        if ident == me:
                            continue
                        name = names.get(ident, "?")
                        if thread_prefixes is not None and not any(
                                name.startswith(p)
                                for p in thread_prefixes):
                            continue
                        parts: List[str] = []
                        f = frame
                        depth = 0
                        while f is not None and depth < 64:
                            code = f.f_code
                            parts.append(
                                f"{os.path.basename(code.co_filename)}"
                                f":{code.co_name}")
                            f = f.f_back
                            depth += 1
                        key = name + ";" + ";".join(reversed(parts))
                        with self._lock:
                            self._samples += 1
                            if key in self._stacks or \
                                    len(self._stacks) < self._stacks_cap:
                                self._stacks[key] = \
                                    self._stacks.get(key, 0) + 1
                            else:
                                self._stacks["<overflow>"] = \
                                    self._stacks.get("<overflow>", 0) + 1
                except Exception:  # noqa: BLE001 - sampling must never
                    pass           # take the process down
                cost = time.perf_counter() - t0
                self._sampler_stop.wait(
                    max(interval - cost, cost * (1.0 / duty_cap - 1.0)))

        self._sampler_thread = threading.Thread(
            target=loop, name="profile-sampler", daemon=True)
        self._sampler_thread.start()
        return self

    def stop_sampler(self) -> None:
        self._sampler_stop.set()
        t = self._sampler_thread
        if t is not None:
            t.join(timeout=5)
        self._sampler_thread = None

    def flamegraph_lines(self, top: Optional[int] = None) -> List[str]:
        """Collapsed-stack lines (``thread;frame;...;leaf count``) in
        descending count order — feed straight to ``flamegraph.pl`` or
        speedscope."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
        if top is not None:
            items = items[:top]
        return [f"{k} {v}" for k, v in items]

    # ---- snapshot / exposition ----

    def snapshot(self, top_stacks: int = 50) -> dict:
        """JSON-able profile block: phases (StageStats shape — merge
        with ``telemetry.merge_snapshots``), the compile/dispatch
        ledger, jax event accumulations, memory watermarks, and the
        sampler's top collapsed stacks.  Embedded in flight records and
        bench artifacts; ``tools/perf_report.py`` consumes it."""
        self.sample_memory()
        with self._lock:
            jax_events = {k: {"count": int(v[0]),
                              "total_s": round(v[1], 6)}
                          for k, v in self._jax_events.items()}
            dispatch = {k: dict(v) for k, v in self._dispatch.items()}
            mem = {f"{d}/{k}": v for (d, k), v in self._mem.items()}
            samples = self._samples
        return {
            "enabled": self.enabled,
            "phases": self.stats.snapshot(),
            "jax_events": jax_events,
            "compile_seq": self._compile_seq,
            "dispatch": dispatch,
            "memory_bytes": mem,
            "sampler": {"samples": samples,
                        "stacks": self.flamegraph_lines(top_stacks)},
        }

    def render_prometheus(self, prefix: str = PREFIX) -> str:
        """The ``mmlspark_tpu_profile_*`` families (appended to every
        registry render through ``register_exposition``)."""
        self.sample_memory()
        lines: List[str] = []

        def fam(suffix: str, typ: str, help_: str) -> str:
            name = f"{prefix}_profile_{suffix}"
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {typ}")
            return name

        n = fam("enabled", "gauge",
                "1 while the always-on profiler is recording.")
        lines.append(f"{n} {1 if self.enabled else 0}")

        snap = self.stats.snapshot()
        stages = snap.get("stages") or {}
        if stages:
            n = fam("phase_seconds", "histogram",
                    "Attributed wall time per named hot-path phase "
                    "(log-bucketed, cross-process mergeable).")
            for phase in sorted(stages):
                s = stages[phase]
                lab = {"phase": phase}
                buckets = s.get("buckets") or {}
                cum = 0
                for le, c in sorted(
                        ((le, c) for le, c in buckets.items()
                         if le != "+Inf"),
                        key=lambda kv: float(kv[0])):
                    cum += int(c)
                    lines.append(
                        f"{n}_bucket{_labels({**lab, 'le': le})} {cum}")
                lines.append(
                    f"{n}_bucket{_labels({**lab, 'le': '+Inf'})} "
                    f"{_fmt(s.get('count', 0))}")
                lines.append(
                    f"{n}_sum{_labels(lab)} "
                    f"{_fmt(s.get('total_s', 0.0))}")
                lines.append(
                    f"{n}_count{_labels(lab)} "
                    f"{_fmt(s.get('count', 0))}")

        with self._lock:
            jax_events = {k: (int(v[0]), float(v[1]))
                          for k, v in self._jax_events.items()}
            dispatch = {k: dict(v) for k, v in self._dispatch.items()}
            mem = dict(self._mem)
            samples = self._samples
        if dispatch:
            n = fam("dispatch_total", "counter",
                    "Bracketed jitted dispatches per site, split "
                    "compile-cache hit vs miss.")
            for site in sorted(dispatch):
                for outcome in ("hit", "miss"):
                    lines.append(
                        f"{n}{_labels({'site': site, 'outcome': outcome})}"
                        f" {dispatch[site].get(outcome + 's', 0)}")
        if jax_events:
            n = fam("jax_events_total", "counter",
                    "jax.monitoring event counts (backend_compile = "
                    "one real compilation).")
            for ev in sorted(jax_events):
                lines.append(f"{n}{_labels({'event': ev})} "
                             f"{jax_events[ev][0]}")
            n = fam("jax_seconds_total", "counter",
                    "Cumulative seconds per jax.monitoring event "
                    "(the compile-time ledger).")
            for ev in sorted(jax_events):
                lines.append(f"{n}{_labels({'event': ev})} "
                             f"{_fmt(round(jax_events[ev][1], 6))}")
        if mem:
            n = fam("memory_bytes", "gauge",
                    "Device memory watermarks where the backend "
                    "exposes memory_stats().")
            for (dev, kind) in sorted(mem):
                lines.append(
                    f"{n}{_labels({'device': dev, 'kind': kind})} "
                    f"{_fmt(mem[(dev, kind)])}")
        n = fam("sampler_samples_total", "counter",
                "Thread-stack samples taken by the opt-in sampler.")
        lines.append(f"{n} {samples}")
        return "\n".join(lines) + "\n"


_profiler = Profiler()
_jax_hooks_installed = threading.Event()
_jax_hooks_lock = threading.Lock()


def get_profiler() -> Profiler:
    """The process-global profiler every hot-path hook feeds.  Installs
    the jax.monitoring listener on first use if jax is already
    imported (idempotent; see :func:`install_jax_hooks`)."""
    if not _jax_hooks_installed.is_set() and "jax" in sys.modules:
        install_jax_hooks()
    return _profiler


def install_jax_hooks() -> bool:
    """Register the profiler's jax.monitoring duration listener ONCE
    per process (listeners cannot be unregistered individually, so the
    callback itself checks ``enabled``).  Returns True when installed
    (now or earlier), False when jax/monitoring is unavailable."""
    if _jax_hooks_installed.is_set():
        return True
    with _jax_hooks_lock:
        # re-check under the lock: listeners cannot be unregistered,
        # so a check-then-act race would double-count every compile
        # event for the life of the process
        if _jax_hooks_installed.is_set():
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _profiler._on_jax_duration)
        except Exception:  # noqa: BLE001 - no jax / API drift:
            return False   # profiler still works, sans compile events
        _jax_hooks_installed.set()
    return True


# the profile families join every /metrics scrape (one failing provider
# is skipped by the registry, never fatal to the scrape)
get_registry().register_exposition(
    "profile", lambda: _profiler.render_prometheus())
