"""SLO burn-rate monitor (ISSUE 8).

The serving and chaos layers *defend* implicit objectives — goodput,
deadline misses, shedding, transport health, heartbeat freshness — but
until now nothing in the repo *evaluated* them: the drills asserted
point facts and the dashboards showed raw counters.  This module closes
that loop with the multiwindow burn-rate discipline (Google SRE
workbook, ch. 5): each declared objective has an error budget
(``1 - target``), and the monitor reports how fast the budget is being
consumed over a FAST and a SLOW window.  A breach requires both windows
to burn — the fast window reacts quickly, the slow window filters
blips — which is what makes the verdict pageable rather than noisy.

Pieces:

* :class:`SLObjective` — one declared objective: either a RATIO over
  registry counters (``bad`` events / ``total`` events, e.g. expired /
  (rows + expired)) or a GAUGE freshness bound (fraction of samples
  where the gauge exceeded ``threshold`` — heartbeat staleness has no
  event counter to ratio over).
* :class:`SLOMonitor` — samples the process
  :class:`~mmlspark_tpu.core.telemetry.MetricsRegistry`, keeps a
  bounded ring of cumulative readings, computes windowed bad-ratios and
  burn rates, journals ``slo_burn`` / ``slo_recovered`` transition
  events, and renders the ``mmlspark_tpu_slo_*`` gauge families into
  every ``/metrics`` scrape (via the registry's exposition-provider
  hook).  ``/slo`` on every serving server returns
  :meth:`SLOMonitor.report` as JSON.
* :func:`default_objectives` — the objectives the production substrate
  implicitly defends, declared explicitly.

``tools/bench_serving.py`` and both chaos drills sample a monitor
through their load phases and embed its verdict in their artifacts, so
every committed run carries "was the SLO being burned, and how fast"
next to the raw numbers.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .telemetry import PREFIX, get_journal, get_registry

__all__ = ["SLObjective", "SLOMonitor", "default_objectives",
           "get_monitor", "set_monitor"]

#: (namespace, key) counter spec; ``key == "rows"`` reads the rows
#: counter, anything else reads ``counters[key]``
Spec = Tuple[str, str]


@dataclass
class SLObjective:
    """One declared service-level objective.

    Ratio form (``bad``/``total`` set): the windowed error rate is
    ``Δbad / Δtotal`` from registry counter deltas; ``target`` is the
    success objective (0.999 → 0.1% error budget).

    Gauge form (``gauge`` set): each monitor sample scores 1 when the
    gauge exceeds ``threshold``; the windowed error rate is the bad
    fraction of samples — "the heartbeat may be stale at most 1% of
    the time" has no event counter, only observations.
    """
    name: str
    target: float
    description: str = ""
    bad: Tuple[Spec, ...] = ()
    total: Tuple[Spec, ...] = ()
    gauge: Optional[Spec] = None
    threshold: float = 0.0

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - float(self.target))


def default_objectives() -> Tuple[SLObjective, ...]:
    """The objectives the serving/chaos stack implicitly defends."""
    # local import: capacity pulls in telemetry/profiling and this
    # module is imported during interpreter-level bootstrap paths
    from .capacity import SATURATION_ONSET_RATIO
    return (
        SLObjective(
            "scoring_goodput", 0.999,
            "scored rows vs requests degraded (shed or expired)",
            bad=(("scoring", "shed"), ("scoring", "expired")),
            total=(("scoring", "rows"), ("scoring", "shed"),
                   ("scoring", "expired"))),
        SLObjective(
            "scoring_deadline_miss", 0.999,
            "requests expired (504) past their deadline",
            bad=(("scoring", "expired"),),
            total=(("scoring", "rows"), ("scoring", "expired"))),
        SLObjective(
            "scoring_shed", 0.99,
            "requests shed (503) by admission control",
            bad=(("scoring", "shed"),),
            total=(("scoring", "rows"), ("scoring", "shed"))),
        SLObjective(
            "transport_retransmit", 0.99,
            "exchange frames needing retransmission",
            bad=(("transport", "retransmits"),),
            total=(("transport", "frames_sent"),)),
        SLObjective(
            "heartbeat_freshness", 0.99,
            "fraction of time the worst peer heartbeat stays fresh",
            gauge=("elastic", "heartbeat_age_ms"), threshold=2000.0),
        SLObjective(
            "feature_drift", 0.99,
            "worst per-feature PSI (live traffic vs the fit-time "
            "reference profile) staying under the drift threshold "
            "(core/drift.py publishes the gauge under ns='drift'; "
            "silent until a drift monitor is installed).  The "
            "threshold MATCHES DriftConfig.psi_threshold's default — "
            "the burn gate and the instantaneous alert gauge must "
            "agree on what 'drifted' means",
            gauge=("drift", "psi_worst"), threshold=0.25),
        SLObjective(
            "prediction_drift", 0.99,
            "prediction-margin PSI (live scoring output vs the "
            "fit-time training-margin sketch) staying under the "
            "drift threshold (silent until a drift monitor runs)",
            gauge=("drift", "psi_prediction"), threshold=0.25),
        SLObjective(
            "perf_latency_budget", 0.99,
            "perf-sentinel worst stage-vs-baseline ratio staying "
            "inside the latency budget (tools/perf_sentinel.py "
            "publishes the gauge; silent until a sentinel ran).  The "
            "threshold MATCHES the sentinel's relative regression "
            "gate (--rel, default 1.8) — a stricter SLO would breach "
            "on runs the sentinel itself calls healthy",
            gauge=("perf", "worst_regression_ratio"), threshold=1.8),
        SLObjective(
            "scoring_headroom", 0.99,
            "scoring load staying below the saturation-onset fraction "
            "of the estimated capacity knee (core/capacity.py "
            "publishes the headroom gauge under ns='capacity'; silent "
            "until a capacity monitor runs).  Burns BEFORE "
            "scoring_goodput does: headroom crosses onset while "
            "requests are still being answered in time, so the page "
            "says 'approaching saturation', not 'SLO violated'",
            gauge=("capacity", "headroom_scoring"),
            threshold=SATURATION_ONSET_RATIO),
        SLObjective(
            "transport_headroom", 0.99,
            "transport load staying below the saturation-onset "
            "fraction of the estimated wire-capacity knee (silent "
            "until a capacity monitor runs)",
            gauge=("capacity", "headroom_transport"),
            threshold=SATURATION_ONSET_RATIO),
    )


def _read_spec(snapshot: Dict[str, dict], specs: Sequence[Spec]
               ) -> float:
    out = 0.0
    for ns, key in specs:
        src = snapshot.get(ns)
        if not isinstance(src, dict):
            continue
        if key == "rows":
            out += float(src.get("rows", 0) or 0)
        else:
            out += float((src.get("counters") or {}).get(key, 0) or 0)
    return out


class SLOMonitor:
    """Windowed burn-rate evaluator over the metrics registry.

    ``sample()`` appends one cumulative reading per objective;
    ``evaluate()`` computes, per objective and per window, the bad
    ratio (``Δbad/Δtotal`` across the window's samples) and the burn
    rate (``bad_ratio / error_budget`` — burn 1.0 means the budget is
    being consumed exactly at the sustainable rate; burn 14.4 over the
    fast window means a 30-day budget dies in 2 days).  A breach
    requires BOTH windows above their thresholds.  Deterministic given
    its samples: tools drive ``sample()`` manually for reproducible
    artifacts, or ``start()`` a background ticker for live serving.
    """

    def __init__(self, objectives: Optional[Sequence[SLObjective]] = None,
                 registry=None, *,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 300.0,
                 fast_burn_threshold: float = 14.4,
                 slow_burn_threshold: float = 6.0,
                 capacity: int = 4096):
        self.objectives = tuple(objectives if objectives is not None
                                else default_objectives())
        self._registry = registry
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self._lock = threading.Lock()
        #: ring of (t_monotonic, {name: (cum_bad, cum_total)})
        self._samples: "deque[Tuple[float, Dict[str, Tuple[float, float]]]]" \
            = deque(maxlen=int(capacity))
        #: gauge objectives accumulate synthetic counters here (one
        #: observation per sample), so both forms window identically
        self._gauge_cum: Dict[str, Tuple[float, float]] = {}
        self._breached: Dict[str, bool] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- sampling ----

    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    def maybe_sample(self, min_interval_s: float = 0.5) -> None:
        """Take a sample unless one was taken within
        ``min_interval_s`` — the scrape-driven sampling mode: a
        deployment watched only through ``/metrics`` (no ticker, no
        ``/slo`` probes) still accumulates one reading per scrape, so
        the burn gauges move instead of rendering NaN forever."""
        with self._lock:
            if self._samples and (time.monotonic() - self._samples[-1][0]
                                  < min_interval_s):
                return
        self.sample()

    def sample(self, now: Optional[float] = None) -> None:
        """Take one reading of every objective's counters/gauges."""
        snap = self._reg().snapshot()
        t = time.monotonic() if now is None else float(now)
        reading: Dict[str, Tuple[float, float]] = {}
        with self._lock:
            for obj in self.objectives:
                if obj.gauge is not None:
                    ns, key = obj.gauge
                    src = snap.get(ns)
                    val = None
                    if isinstance(src, dict):
                        val = (src.get("gauges") or {}).get(key)
                    cb, ct = self._gauge_cum.get(obj.name, (0.0, 0.0))
                    if val is not None:
                        cb += 1.0 if float(val) > obj.threshold else 0.0
                        ct += 1.0
                    self._gauge_cum[obj.name] = (cb, ct)
                    reading[obj.name] = (cb, ct)
                else:
                    reading[obj.name] = (_read_spec(snap, obj.bad),
                                         _read_spec(snap, obj.total))
            self._samples.append((t, reading))

    # ---- evaluation ----

    def _window_ratio(self, name: str, window_s: float,
                      samples) -> Tuple[Optional[float], float]:
        """(bad_ratio or None when the window saw no events, Δtotal)
        over the trailing ``window_s``."""
        if len(samples) < 2:
            return None, 0.0
        t_end, end = samples[-1]
        base = samples[0]
        for t, reading in samples:
            if t <= t_end - window_s:
                base = (t, reading)      # newest sample OUTSIDE window
            else:
                break
        b0, t0 = base[1].get(name, (0.0, 0.0))
        b1, t1 = end.get(name, (0.0, 0.0))
        dtotal = max(0.0, t1 - t0)
        if dtotal <= 0:
            return None, 0.0
        dbad = min(dtotal, max(0.0, b1 - b0))
        return dbad / dtotal, dtotal

    def evaluate(self) -> Dict[str, dict]:
        """Per-objective burn verdicts; journals ``slo_burn`` /
        ``slo_recovered`` on breach transitions."""
        with self._lock:
            samples = list(self._samples)
        out: Dict[str, dict] = {}
        transitions: List[Tuple[str, bool, dict]] = []
        for obj in self.objectives:
            fast, n_fast = self._window_ratio(
                obj.name, self.fast_window_s, samples)
            slow, n_slow = self._window_ratio(
                obj.name, self.slow_window_s, samples)
            burn_fast = (fast / obj.budget) if fast is not None else None
            burn_slow = (slow / obj.budget) if slow is not None else None
            breach = (burn_fast is not None and burn_slow is not None
                      and burn_fast > self.fast_burn_threshold
                      and burn_slow > self.slow_burn_threshold)
            rec = {
                "target": obj.target,
                "budget": obj.budget,
                "bad_ratio_fast": None if fast is None
                else round(fast, 6),
                "bad_ratio_slow": None if slow is None
                else round(slow, 6),
                "burn_rate_fast": None if burn_fast is None
                else round(burn_fast, 3),
                "burn_rate_slow": None if burn_slow is None
                else round(burn_slow, 3),
                "events_fast": n_fast,
                "events_slow": n_slow,
                "breach": breach,
            }
            out[obj.name] = rec
            # transition detection is read-compare-write on _breached:
            # under the lock, or two concurrent evaluators (ticker +
            # scrape) double-journal one onset or lose a recovery
            with self._lock:
                was = self._breached.get(obj.name, False)
                if breach != was:
                    self._breached[obj.name] = breach
                    transitions.append((obj.name, breach, rec))
        for name, breach, rec in transitions:
            get_journal().emit(
                "slo_burn" if breach else "slo_recovered", slo=name,
                burn_fast=rec["burn_rate_fast"],
                burn_slow=rec["burn_rate_slow"],
                target=rec["target"])
        return out

    def report(self) -> dict:
        """Sample + evaluate — the ``/slo`` route body and the shape
        the tools embed in their artifacts."""
        self.sample()
        verdicts = self.evaluate()
        return {
            "objectives": verdicts,
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
            "burn_thresholds": {"fast": self.fast_burn_threshold,
                                "slow": self.slow_burn_threshold},
            "samples": len(self._samples),
            "breaching": sorted(n for n, v in verdicts.items()
                                if v["breach"]),
            "healthy": not any(v["breach"] for v in verdicts.values()),
        }

    # ---- exposition ----

    def render_prometheus(self, prefix: str = PREFIX) -> str:
        """The ``mmlspark_tpu_slo_*`` gauge families (appended to every
        registry render through ``register_exposition``).  Each render
        also samples (rate-limited): a Prometheus-only deployment gets
        scrape-driven readings with no ticker or ``/slo`` probes."""
        self.maybe_sample()
        verdicts = self.evaluate()
        lines: List[str] = []

        def fam(suffix: str, help_: str) -> str:
            name = f"{prefix}_slo_{suffix}"
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            return name

        n = fam("objective", "Declared success objective (target).")
        for obj in self.objectives:
            lines.append(f'{n}{{slo="{obj.name}"}} {obj.target}')
        n = fam("bad_ratio",
                "Windowed error rate (bad events / total events).")
        for name, v in verdicts.items():
            for w in ("fast", "slow"):
                r = v[f"bad_ratio_{w}"]
                lines.append(
                    f'{n}{{slo="{name}",window="{w}"}} '
                    f'{"NaN" if r is None else r}')
        n = fam("burn_rate",
                "Error-budget burn rate (1.0 = sustainable).")
        for name, v in verdicts.items():
            for w in ("fast", "slow"):
                r = v[f"burn_rate_{w}"]
                lines.append(
                    f'{n}{{slo="{name}",window="{w}"}} '
                    f'{"NaN" if r is None else r}')
        n = fam("breach",
                "1 while both windows burn above threshold.")
        for name, v in verdicts.items():
            lines.append(
                f'{n}{{slo="{name}"}} {1 if v["breach"] else 0}')
        return "\n".join(lines) + "\n"

    # ---- background ticker ----

    def start(self, tick_s: float = 1.0) -> "SLOMonitor":
        self._stop.clear()

        def loop():
            while not self._stop.wait(tick_s):
                try:
                    self.sample()
                    self.evaluate()
                except Exception:  # noqa: BLE001 - the monitor must
                    pass           # outlive a transient registry error

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="slo-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_monitor_lock = threading.Lock()
_monitor: Optional[SLOMonitor] = None


def get_monitor() -> SLOMonitor:
    """The process-global monitor the ``/slo`` route reports and the
    ``/metrics`` exposition carries (created on first use with the
    default objectives; replace with :func:`set_monitor`)."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            set_monitor_locked(SLOMonitor())
        return _monitor


def set_monitor(monitor: SLOMonitor) -> SLOMonitor:
    """Install ``monitor`` as the process-global one (re-pointing the
    registry's ``slo`` exposition at it)."""
    with _monitor_lock:
        return set_monitor_locked(monitor)


def set_monitor_locked(monitor: SLOMonitor) -> SLOMonitor:
    global _monitor
    _monitor = monitor
    get_registry().register_exposition(
        "slo", lambda: _monitor.render_prometheus()
        if _monitor is not None else "")
    return monitor
