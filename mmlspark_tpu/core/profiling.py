"""Device-level tracing — the framework's profiling subsystem.

The reference's observability story is the Spark UI plus the ``Timer``
pipeline stage (SURVEY.md §5.1); the TPU-native equivalent is a
``jax.profiler`` trace (Perfetto/TensorBoard-readable, captures every XLA
op with device timestamps).  This module makes that a first-class,
in-package capability rather than a side tool:

* :func:`trace` — context manager; wrap any region to capture a device
  trace into a directory.
* :func:`summarize_trace` — parse the written trace (no TensorBoard
  needed) into per-op device-time totals, the same aggregation
  ``tools/profile_boost_step.py`` prints.
* ``LightGBMBase.setProfileTraceDir(dir)`` — traces the whole ``fit``
  (engine hooks through :func:`maybe_trace`).

The committed evidence chain in PERF.md (129 → 87 ms/tree) was produced
with exactly these aggregations.

Serving adds a second, host-side need: per-stage wall-clock counters for
the scoring hot path (queue wait / decode / score / reply), cheap enough
to stay on in production.  :class:`LatencyStats` is a thread-safe
streaming accumulator over a FIXED log-bucketed histogram (ISSUE 8):
counts per logarithmic latency bucket instead of the old 4096-sample
ring, so two workers' snapshots MERGE exactly (bucket counts sum;
percentiles recompute from the summed buckets) — averaging or
max-ing per-worker p99s, the only option a sample ring allowed, is not
a percentile of the combined population.  :class:`StageStats` groups
named stages plus a rows counter so ``ScoringEngine.stats()`` can
report rows/s and p50/p99 without a profiler attached.
"""

from __future__ import annotations

import glob
import gzip
import json
import math
import os
import threading
import time
from bisect import bisect_left
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

# -- log-bucket ladder -------------------------------------------------------

#: multiplicative bucket growth: 2**0.25 bounds the relative error of a
#: bucket-midpoint percentile estimate to ~±9% — tight enough for an SLO
#: readout, coarse enough that a stage's occupied buckets stay few
HIST_GROWTH = 2.0 ** 0.25
#: lowest bucket upper bound (10 µs); the top finite bound is
#: ``HIST_GROWTH**(HIST_BUCKETS-1)`` above it (~300 s) — everything
#: slower lands in the +Inf overflow bucket
HIST_FLOOR = 1e-5
HIST_BUCKETS = 100

#: upper (``le``) bounds of the finite buckets, ascending
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    HIST_FLOOR * HIST_GROWTH ** i for i in range(HIST_BUCKETS))
#: stable string keys for the bucket bounds — the wire/snapshot
#: representation (identical across processes because the ladder is a
#: module constant, never computed from data)
LE_STRS: Tuple[str, ...] = tuple(
    format(b, ".6g") for b in BUCKET_BOUNDS) + ("+Inf",)
_LE_INDEX = {s: i for i, s in enumerate(LE_STRS)}


def bucket_index(seconds: float) -> int:
    """Index into ``LE_STRS`` of the bucket holding ``seconds`` (the
    first bound >= the value; the last index is the +Inf overflow)."""
    return bisect_left(BUCKET_BOUNDS, seconds)


def _bucket_mid(i: int) -> float:
    """Representative value (geometric midpoint) for bucket ``i`` —
    the percentile estimate returned for ranks landing in it."""
    if i >= HIST_BUCKETS:                       # +Inf overflow
        return BUCKET_BOUNDS[-1] * math.sqrt(HIST_GROWTH)
    return BUCKET_BOUNDS[i] / math.sqrt(HIST_GROWTH)


def percentile_from_buckets(buckets: Dict[str, int], q: float) -> float:
    """q-th percentile (0-100), in seconds, of a sparse ``{le: count}``
    bucket dict (the ``snapshot()["buckets"]`` shape).  Deterministic in
    the bucket counts alone, so summing two sources' buckets and calling
    this is EXACTLY the percentile of the combined population at the
    ladder's resolution — the property ``merge_snapshots`` relies on."""
    total = 0
    per_idx: List[Tuple[int, int]] = []
    for le, c in buckets.items():
        i = _LE_INDEX.get(le)
        if i is None or not c:
            continue
        per_idx.append((i, int(c)))
        total += int(c)
    if total <= 0:
        return 0.0
    rank = max(1, math.ceil(q / 100.0 * total))
    cum = 0
    for i, c in sorted(per_idx):
        cum += c
        if cum >= rank:
            return _bucket_mid(i)
    return _bucket_mid(per_idx[-1][0] if per_idx else 0)


class LatencyStats:
    """Thread-safe streaming latency accumulator over the fixed
    log-bucket ladder.

    Keeps exact count/total plus one integer per occupied bucket —
    O(1) per record, bounded memory, and (unlike the sample ring it
    replaced) MERGEABLE: ``snapshot()["buckets"]`` from any number of
    workers can be key-wise summed and the percentiles recomputed
    exactly for the combined population.

    Two views coexist: the CUMULATIVE buckets (the exposition's
    ``_bucket`` rows and the merge representation — Prometheus
    consumers ``rate()`` them for any window they like), and a
    RECENT-WINDOW pair of bucket epochs rotated every
    ``window_s`` seconds that the ``p50_ms``/``p99_ms`` snapshot keys
    are estimated from — a latency SLO watches *current* tail latency,
    and a lifetime-cumulative estimate would dilute a regression under
    millions of historical fast samples (the property the old sample
    ring had, kept).  ``capacity`` is accepted and ignored for
    backward compatibility with the ring-buffer signature.
    """

    #: half-window for the recent-percentile epochs: estimates span
    #: the last 1-2 windows' samples
    WINDOW_S = 60.0

    __slots__ = ("_lock", "_count", "_total", "_buckets", "_recent",
                 "_prev", "_epoch_t")

    def __init__(self, capacity: int = 4096):
        del capacity                    # ring-era knob, no longer used
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._buckets = [0] * len(LE_STRS)
        self._recent = [0] * len(LE_STRS)
        self._prev = [0] * len(LE_STRS)
        self._epoch_t = time.monotonic()

    def _roll_locked(self) -> None:
        elapsed = time.monotonic() - self._epoch_t
        if elapsed < self.WINDOW_S:
            return
        if elapsed >= 2 * self.WINDOW_S:
            # a traffic gap longer than the whole window: BOTH epochs
            # are stale — shifting would present the pre-gap epoch as
            # "recent" for another window
            self._prev = [0] * len(LE_STRS)
        else:
            self._prev = self._recent
        self._recent = [0] * len(LE_STRS)
        self._epoch_t = time.monotonic()

    def record(self, seconds: float) -> None:
        i = bucket_index(seconds)
        with self._lock:
            self._roll_locked()
            self._count += 1
            self._total += seconds
            self._buckets[i] += 1
            self._recent[i] += 1

    @property
    def count(self) -> int:
        return self._count

    def _window_counts_locked(self):
        """Recent-window bucket counts (last 1-2 epochs), falling back
        to the cumulative buckets when the window is empty (e.g. right
        after a rotation with no fresh traffic) so percentiles degrade
        to the lifetime estimate instead of reading 0."""
        self._roll_locked()
        window = [a + b for a, b in zip(self._recent, self._prev)]
        return window if any(window) else list(self._buckets)

    def percentile(self, q: float) -> float:
        """q-th percentile (0-100) over the recent window, in seconds
        (bucket-midpoint estimate, ~±9% relative; same estimator as
        ``snapshot()`` — both delegate to
        :func:`percentile_from_buckets`)."""
        with self._lock:
            counts = self._window_counts_locked()
        return percentile_from_buckets(
            {LE_STRS[i]: c for i, c in enumerate(counts) if c}, q)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            count, total = self._count, self._total
            counts = list(self._buckets)
            window = self._window_counts_locked()
        sparse = {LE_STRS[i]: c for i, c in enumerate(counts) if c}
        wsparse = {LE_STRS[i]: c for i, c in enumerate(window) if c}
        return {
            "count": count,
            "total_s": round(total, 6),
            "mean_ms": round(total / count * 1e3, 4) if count else 0.0,
            "p50_ms": round(
                percentile_from_buckets(wsparse, 50) * 1e3, 4),
            "p99_ms": round(
                percentile_from_buckets(wsparse, 99) * 1e3, 4),
            "buckets": sparse,
        }


class StageStats:
    """Named :class:`LatencyStats` per pipeline stage + a rows counter.

    The scoring engine instruments every hop (queue wait, decode, score,
    reply, end-to-end) through one of these; ``snapshot()`` is the
    JSON-able stats surface ``ScoringEngine.stats()`` exposes and
    ``tools/bench_serving.py`` records into its artifact.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: Dict[str, LatencyStats] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._rows = 0
        self._t_first: Optional[float] = None
        self._t_last = 0.0

    def timer(self, stage: str) -> LatencyStats:
        with self._lock:
            stats = self._stages.get(stage)
            if stats is None:
                stats = self._stages[stage] = LatencyStats()
            return stats

    def adopt(self, stage: str, stats: LatencyStats) -> None:
        """Expose an EXISTING :class:`LatencyStats` under ``stage`` —
        the histogram object is SHARED, not copied, so records made by
        its original owner show up here with zero extra hot-path work
        (the profiler's alias mechanism, ISSUE 12).  Replaces any
        previous timer of that name."""
        with self._lock:
            self._stages[stage] = stats

    @contextmanager
    def time(self, stage: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timer(stage).record(time.perf_counter() - t0)

    def incr(self, name: str, n: int = 1) -> None:
        """Bump a named event counter (``n=0`` pre-registers the name so
        a snapshot shows an explicit zero instead of a missing key —
        the resilience counters ``shed``/``expired``/``salvaged``/
        ``restarted`` are seeded this way by the scoring engine, so
        "no degradation happened" is observable, not ambiguous)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def set_gauge(self, name: str, value: float) -> None:
        """Record a point-in-time level (last-write-wins) — e.g. the
        elastic watchdog's worst peer heartbeat age, where "how stale
        NOW" matters and a count or latency distribution would not."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def add_rows(self, n: int) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._t_first is None:
                self._t_first = now
            self._t_last = now
            self._rows += n

    @property
    def rows(self) -> int:
        return self._rows

    def _rows_per_s_locked(self) -> float:
        if self._t_first is None or self._t_last <= self._t_first:
            return 0.0
        return self._rows / (self._t_last - self._t_first)

    def rows_per_s(self) -> float:
        with self._lock:
            return self._rows_per_s_locked()

    def snapshot(self) -> Dict[str, object]:
        # one lock acquisition for the WHOLE top-level read: reading
        # self._rows and calling rows_per_s() after release could pair a
        # newer row count with an older window (or vice versa), so a
        # concurrent add_rows() made rows and rows_per_s mutually
        # inconsistent in one snapshot
        with self._lock:
            stages = dict(self._stages)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            rows = self._rows
            rows_per_s = self._rows_per_s_locked()
        return {
            "rows": rows,
            "rows_per_s": round(rows_per_s, 2),
            "counters": counters,
            "gauges": gauges,
            "stages": {name: s.snapshot() for name, s in stages.items()},
        }


@contextmanager
def trace(out_dir: str):
    """Capture a ``jax.profiler`` trace of the wrapped region."""
    import jax
    os.makedirs(out_dir, exist_ok=True)
    with jax.profiler.trace(out_dir):
        yield


@contextmanager
def maybe_trace(out_dir: Optional[str]):
    """:func:`trace` when ``out_dir`` is set; no-op otherwise (the shape
    engine code wants: one `with` either way)."""
    if not out_dir:
        yield
        return
    with trace(out_dir):
        yield


def summarize_trace(out_dir: str, top: int = 25
                    ) -> List[Tuple[float, str]]:
    """Aggregate device-op durations from the newest perfetto JSON export
    under ``out_dir``.  Returns ``[(total_ms, op_name), ...]`` sorted
    descending, with one trailing ``(total_device_ms,
    "total_device_ms")`` summary row (the whole-trace device time —
    what the committed PERF.md evidence compares across runs); empty
    when no trace file exists.

    "Newest" is by mtime: the profiler names exports by timestamp
    strings whose lexicographic order diverges from chronology across
    hosts/sessions (and a re-run into the same dir must win)."""
    paths = glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        return []
    newest = max(paths, key=lambda p: (os.path.getmtime(p), p))
    with gzip.open(newest, "rt") as fh:
        data = json.load(fh)
    events = data.get("traceEvents", [])
    agg: Dict[Tuple[int, str], float] = defaultdict(float)
    for e in events:
        if e.get("ph") == "X" and "dur" in e:
            agg[(e.get("pid", 0), e.get("name", "?"))] += e["dur"]
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
    dev_pids = [p for p, nm in pid_names.items()
                if "TPU" in nm or "Device" in nm or "/device" in nm]
    if not dev_pids:
        by_pid: Dict[int, float] = defaultdict(float)
        for (pid, _), d in agg.items():
            by_pid[pid] += d
        dev_pids = [max(by_pid, key=by_pid.get)] if by_pid else []
    rows = sorted(((d / 1e3, name) for (pid, name), d in agg.items()
                   if pid in dev_pids), reverse=True)
    total_ms = round(sum(ms for ms, _ in rows), 3)
    return rows[:top] + [(total_ms, "total_device_ms")]
