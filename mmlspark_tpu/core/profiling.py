"""Device-level tracing — the framework's profiling subsystem.

The reference's observability story is the Spark UI plus the ``Timer``
pipeline stage (SURVEY.md §5.1); the TPU-native equivalent is a
``jax.profiler`` trace (Perfetto/TensorBoard-readable, captures every XLA
op with device timestamps).  This module makes that a first-class,
in-package capability rather than a side tool:

* :func:`trace` — context manager; wrap any region to capture a device
  trace into a directory.
* :func:`summarize_trace` — parse the written trace (no TensorBoard
  needed) into per-op device-time totals, the same aggregation
  ``tools/profile_boost_step.py`` prints.
* ``LightGBMBase.setProfileTraceDir(dir)`` — traces the whole ``fit``
  (engine hooks through :func:`maybe_trace`).

The committed evidence chain in PERF.md (129 → 87 ms/tree) was produced
with exactly these aggregations.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple


@contextmanager
def trace(out_dir: str):
    """Capture a ``jax.profiler`` trace of the wrapped region."""
    import jax
    os.makedirs(out_dir, exist_ok=True)
    with jax.profiler.trace(out_dir):
        yield


@contextmanager
def maybe_trace(out_dir: Optional[str]):
    """:func:`trace` when ``out_dir`` is set; no-op otherwise (the shape
    engine code wants: one `with` either way)."""
    if not out_dir:
        yield
        return
    with trace(out_dir):
        yield


def summarize_trace(out_dir: str, top: int = 25
                    ) -> List[Tuple[float, str]]:
    """Aggregate device-op durations from the newest perfetto JSON export
    under ``out_dir``.  Returns ``[(total_ms, op_name), ...]`` sorted
    descending; empty when no trace file exists."""
    paths = glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        return []
    with gzip.open(sorted(paths)[-1], "rt") as fh:
        data = json.load(fh)
    events = data.get("traceEvents", [])
    agg: Dict[Tuple[int, str], float] = defaultdict(float)
    for e in events:
        if e.get("ph") == "X" and "dur" in e:
            agg[(e.get("pid", 0), e.get("name", "?"))] += e["dur"]
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
    dev_pids = [p for p, nm in pid_names.items()
                if "TPU" in nm or "Device" in nm or "/device" in nm]
    if not dev_pids:
        by_pid: Dict[int, float] = defaultdict(float)
        for (pid, _), d in agg.items():
            by_pid[pid] += d
        dev_pids = [max(by_pid, key=by_pid.get)] if by_pid else []
    rows = sorted(((d / 1e3, name) for (pid, name), d in agg.items()
                   if pid in dev_pids), reverse=True)
    return rows[:top]
