"""Device-level tracing — the framework's profiling subsystem.

The reference's observability story is the Spark UI plus the ``Timer``
pipeline stage (SURVEY.md §5.1); the TPU-native equivalent is a
``jax.profiler`` trace (Perfetto/TensorBoard-readable, captures every XLA
op with device timestamps).  This module makes that a first-class,
in-package capability rather than a side tool:

* :func:`trace` — context manager; wrap any region to capture a device
  trace into a directory.
* :func:`summarize_trace` — parse the written trace (no TensorBoard
  needed) into per-op device-time totals, the same aggregation
  ``tools/profile_boost_step.py`` prints.
* ``LightGBMBase.setProfileTraceDir(dir)`` — traces the whole ``fit``
  (engine hooks through :func:`maybe_trace`).

The committed evidence chain in PERF.md (129 → 87 ms/tree) was produced
with exactly these aggregations.

Serving adds a second, host-side need: per-stage wall-clock counters for
the scoring hot path (queue wait / decode / score / reply), cheap enough
to stay on in production.  :class:`LatencyStats` is a thread-safe
streaming accumulator with ring-buffer percentiles; :class:`StageStats`
groups named stages plus a rows counter so ``ScoringEngine.stats()`` can
report rows/s and p50/p99 without a profiler attached.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple


class LatencyStats:
    """Thread-safe streaming latency accumulator.

    Keeps exact count/total plus a ring buffer of the most recent
    ``capacity`` samples for percentile estimates — O(1) per record, no
    unbounded growth, good enough for serving dashboards (percentiles
    reflect the recent window, which is what a latency SLO watches).
    """

    __slots__ = ("_lock", "_count", "_total", "_ring", "_cap", "_pos")

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._cap = capacity
        self._ring: List[float] = []
        self._pos = 0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            if len(self._ring) < self._cap:
                self._ring.append(seconds)
            else:
                self._ring[self._pos] = seconds
                self._pos = (self._pos + 1) % self._cap

    @property
    def count(self) -> int:
        return self._count

    @staticmethod
    def _pct(window: List[float], q: float) -> float:
        """Nearest-rank percentile of a pre-sorted window, in seconds."""
        if not window:
            return 0.0
        i = min(len(window) - 1,
                max(0, round(q / 100.0 * (len(window) - 1))))
        return window[i]

    def percentile(self, q: float) -> float:
        """q-th percentile (0-100) over the recent window, in seconds."""
        with self._lock:
            window = sorted(self._ring)
        return self._pct(window, q)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            count, total = self._count, self._total
            window = sorted(self._ring)
        return {
            "count": count,
            "total_s": round(total, 6),
            "mean_ms": round(total / count * 1e3, 4) if count else 0.0,
            "p50_ms": round(self._pct(window, 50) * 1e3, 4),
            "p99_ms": round(self._pct(window, 99) * 1e3, 4),
        }


class StageStats:
    """Named :class:`LatencyStats` per pipeline stage + a rows counter.

    The scoring engine instruments every hop (queue wait, decode, score,
    reply, end-to-end) through one of these; ``snapshot()`` is the
    JSON-able stats surface ``ScoringEngine.stats()`` exposes and
    ``tools/bench_serving.py`` records into its artifact.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: Dict[str, LatencyStats] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._rows = 0
        self._t_first: Optional[float] = None
        self._t_last = 0.0

    def timer(self, stage: str) -> LatencyStats:
        with self._lock:
            stats = self._stages.get(stage)
            if stats is None:
                stats = self._stages[stage] = LatencyStats()
            return stats

    @contextmanager
    def time(self, stage: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timer(stage).record(time.perf_counter() - t0)

    def incr(self, name: str, n: int = 1) -> None:
        """Bump a named event counter (``n=0`` pre-registers the name so
        a snapshot shows an explicit zero instead of a missing key —
        the resilience counters ``shed``/``expired``/``salvaged``/
        ``restarted`` are seeded this way by the scoring engine, so
        "no degradation happened" is observable, not ambiguous)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def set_gauge(self, name: str, value: float) -> None:
        """Record a point-in-time level (last-write-wins) — e.g. the
        elastic watchdog's worst peer heartbeat age, where "how stale
        NOW" matters and a count or latency distribution would not."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def add_rows(self, n: int) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._t_first is None:
                self._t_first = now
            self._t_last = now
            self._rows += n

    @property
    def rows(self) -> int:
        return self._rows

    def _rows_per_s_locked(self) -> float:
        if self._t_first is None or self._t_last <= self._t_first:
            return 0.0
        return self._rows / (self._t_last - self._t_first)

    def rows_per_s(self) -> float:
        with self._lock:
            return self._rows_per_s_locked()

    def snapshot(self) -> Dict[str, object]:
        # one lock acquisition for the WHOLE top-level read: reading
        # self._rows and calling rows_per_s() after release could pair a
        # newer row count with an older window (or vice versa), so a
        # concurrent add_rows() made rows and rows_per_s mutually
        # inconsistent in one snapshot
        with self._lock:
            stages = dict(self._stages)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            rows = self._rows
            rows_per_s = self._rows_per_s_locked()
        return {
            "rows": rows,
            "rows_per_s": round(rows_per_s, 2),
            "counters": counters,
            "gauges": gauges,
            "stages": {name: s.snapshot() for name, s in stages.items()},
        }


@contextmanager
def trace(out_dir: str):
    """Capture a ``jax.profiler`` trace of the wrapped region."""
    import jax
    os.makedirs(out_dir, exist_ok=True)
    with jax.profiler.trace(out_dir):
        yield


@contextmanager
def maybe_trace(out_dir: Optional[str]):
    """:func:`trace` when ``out_dir`` is set; no-op otherwise (the shape
    engine code wants: one `with` either way)."""
    if not out_dir:
        yield
        return
    with trace(out_dir):
        yield


def summarize_trace(out_dir: str, top: int = 25
                    ) -> List[Tuple[float, str]]:
    """Aggregate device-op durations from the newest perfetto JSON export
    under ``out_dir``.  Returns ``[(total_ms, op_name), ...]`` sorted
    descending, with one trailing ``(total_device_ms,
    "total_device_ms")`` summary row (the whole-trace device time —
    what the committed PERF.md evidence compares across runs); empty
    when no trace file exists.

    "Newest" is by mtime: the profiler names exports by timestamp
    strings whose lexicographic order diverges from chronology across
    hosts/sessions (and a re-run into the same dir must win)."""
    paths = glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        return []
    newest = max(paths, key=lambda p: (os.path.getmtime(p), p))
    with gzip.open(newest, "rt") as fh:
        data = json.load(fh)
    events = data.get("traceEvents", [])
    agg: Dict[Tuple[int, str], float] = defaultdict(float)
    for e in events:
        if e.get("ph") == "X" and "dur" in e:
            agg[(e.get("pid", 0), e.get("name", "?"))] += e["dur"]
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
    dev_pids = [p for p, nm in pid_names.items()
                if "TPU" in nm or "Device" in nm or "/device" in nm]
    if not dev_pids:
        by_pid: Dict[int, float] = defaultdict(float)
        for (pid, _), d in agg.items():
            by_pid[pid] += d
        dev_pids = [max(by_pid, key=by_pid.get)] if by_pid else []
    rows = sorted(((d / 1e3, name) for (pid, name), d in agg.items()
                   if pid in dev_pids), reverse=True)
    total_ms = round(sum(ms for ms, _ in rows), 3)
    return rows[:top] + [(total_ms, "total_device_ms")]
