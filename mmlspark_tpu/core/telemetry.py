"""Unified telemetry — the framework's observability subsystem.

The reference stack's observability story is the Spark UI plus the
``Timer`` pipeline stage (SURVEY.md §5.1).  This port grew three
DISCONNECTED stats surfaces instead — ``StageStats`` in the scoring
engine, the module-global ``train_stats`` in the GBDT engine, and the
elastic watchdog's heartbeat gauges — with no export endpoint and no way
to correlate a slow request with what actually happened.  This module
federates them (ISSUE 5):

* :class:`MetricsRegistry` — a process-wide registry of named stats
  sources (anything with a ``snapshot()`` in the
  :class:`~mmlspark_tpu.core.profiling.StageStats` shape), rendered as
  Prometheus text exposition for the ``/metrics`` route every serving
  server exposes (pull-model metrics, Prometheus-style).
* :class:`EventJournal` — a bounded, thread-safe event ring (optionally
  mirrored to a JSONL file): span begin/end, shed/expired/salvage,
  checkpoint save/resume/discard, peer_lost.  ``tools/trace_report.py``
  reconstructs per-request and per-fit timelines from it
  (Dapper-style correlated tracing, minus the distributed collector).
* Trace identity — :func:`new_trace_id` mints ids; a scoring request's
  trace id is the ``_trace_id`` its client sent, else the request id
  minted at admission (so every request is traceable without opt-in).
  A fit's span id is process-global (:func:`current_fit_span`) so the
  checkpoint writer and the heartbeat lease can stamp it without
  threading an argument through the whole engine.

Metric naming scheme (see docs/observability.md):

==============================================  =======  ==================
family                                          type     labels
==============================================  =======  ==================
``mmlspark_tpu_rows_total``                     counter  ``ns``
``mmlspark_tpu_rows_per_second``                gauge    ``ns``
``mmlspark_tpu_events_total``                   counter  ``ns``, ``event``
``mmlspark_tpu_gauge``                          gauge    ``ns``, ``name``
``mmlspark_tpu_stage_latency_seconds``          summary  ``ns``, ``stage``
==============================================  =======  ==================

``ns`` is the registry namespace (``scoring``, ``train``, ``elastic``,
``serving_exchange``, ``worker<N>``/``workers`` for the multiprocess
topology's per-worker and aggregated blocks).

Everything here is stdlib-only and import-light: the serving hot path
and the training loop both call into it.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional

PREFIX = "mmlspark_tpu"

# -- Prometheus text exposition ---------------------------------------------

#: family -> (type, help); summaries additionally emit _sum/_count rows
_FAMILIES = (
    ("rows_total", "counter", "Rows processed by this source."),
    ("rows_per_second", "gauge",
     "Rows/s over the source's active window."),
    ("events_total", "counter",
     "Named event counters (shed/expired/salvaged/restarted, "
     "ckpt_saved/ckpt_resumed/..., heartbeat_stalls/peer_lost, ...)."),
    ("gauge", "gauge",
     "Point-in-time levels (heartbeat_age_ms, ms_per_tree, ...)."),
    ("stage_latency_seconds", "summary",
     "Per-stage wall-clock latency (quantiles over the recent window)."),
)


def _esc(v: Any) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: Any) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if f != f:                       # NaN
        return "NaN"
    if f == float("inf"):            # before int(f): int(inf) raises,
        return "+Inf"                # and one inf gauge must not 503
    if f == float("-inf"):           # the whole scrape
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(d: Dict[str, Any]) -> str:
    return "{" + ",".join(f'{k}="{_esc(v)}"'
                          for k, v in sorted(d.items())) + "}"


def render_prometheus(snapshots: Dict[str, dict],
                      prefix: str = PREFIX) -> str:
    """Render ``{namespace: StageStats.snapshot()-shaped dict}`` as
    Prometheus text exposition (format 0.0.4).  Unknown/missing snapshot
    keys are skipped, never fatal — a scrape must not 500 because one
    source misbehaved."""
    rows: Dict[str, List[str]] = {fam: [] for fam, _, _ in _FAMILIES}
    for ns in sorted(snapshots):
        snap = snapshots[ns]
        if not isinstance(snap, dict):
            continue
        lab = {"ns": ns}
        if "rows" in snap:
            rows["rows_total"].append(
                f"{prefix}_rows_total{_labels(lab)} "
                f"{_fmt(snap.get('rows', 0))}")
            rows["rows_per_second"].append(
                f"{prefix}_rows_per_second{_labels(lab)} "
                f"{_fmt(snap.get('rows_per_s', 0.0))}")
        for name in sorted(snap.get("counters") or {}):
            rows["events_total"].append(
                f"{prefix}_events_total"
                f"{_labels({**lab, 'event': name})} "
                f"{_fmt(snap['counters'][name])}")
        for name in sorted(snap.get("gauges") or {}):
            rows["gauge"].append(
                f"{prefix}_gauge{_labels({**lab, 'name': name})} "
                f"{_fmt(snap['gauges'][name])}")
        for stage in sorted(snap.get("stages") or {}):
            s = snap["stages"][stage]
            if not isinstance(s, dict):
                continue
            slab = {**lab, "stage": stage}
            base = f"{prefix}_stage_latency_seconds"
            for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
                rows["stage_latency_seconds"].append(
                    f"{base}{_labels({**slab, 'quantile': q})} "
                    f"{_fmt(s.get(key, 0.0) / 1e3)}")
            rows["stage_latency_seconds"].append(
                f"{base}_sum{_labels(slab)} {_fmt(s.get('total_s', 0.0))}")
            rows["stage_latency_seconds"].append(
                f"{base}_count{_labels(slab)} {_fmt(s.get('count', 0))}")
    out: List[str] = []
    for fam, typ, help_ in _FAMILIES:
        if not rows[fam]:
            continue
        out.append(f"# HELP {prefix}_{fam} {help_}")
        out.append(f"# TYPE {prefix}_{fam} {typ}")
        out.extend(rows[fam])
    return "\n".join(out) + "\n" if out else "# no metrics registered\n"


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Merge several StageStats snapshots into one aggregate (the
    "workers" total block of a multiprocess scrape): rows and counters
    SUM, rows/s sums (concurrent sources), gauges take the WORST value
    — max for age/level-style gauges, MIN for up-style gauges (``*_up``
    health booleans, where 1 is healthy and one degraded member must
    show in the aggregate) — stage count/total sum (mean recomputed)
    and percentiles take the max across sources: percentile sketches
    don't merge, and the conservative bound is the honest one for an
    SLO readout."""
    out: dict = {"rows": 0, "rows_per_s": 0.0, "counters": {},
                 "gauges": {}, "stages": {}}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        out["rows"] += int(snap.get("rows", 0) or 0)
        out["rows_per_s"] = round(
            out["rows_per_s"] + float(snap.get("rows_per_s", 0.0) or 0.0),
            2)
        for k, v in (snap.get("counters") or {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + v
        for k, v in (snap.get("gauges") or {}).items():
            if k.endswith("_up"):
                out["gauges"][k] = min(
                    out["gauges"].get(k, float("inf")), v)
            else:
                out["gauges"][k] = max(
                    out["gauges"].get(k, float("-inf")), v)
        for stage, s in (snap.get("stages") or {}).items():
            if not isinstance(s, dict):
                continue
            agg = out["stages"].setdefault(
                stage, {"count": 0, "total_s": 0.0, "mean_ms": 0.0,
                        "p50_ms": 0.0, "p99_ms": 0.0})
            agg["count"] += int(s.get("count", 0) or 0)
            agg["total_s"] = round(
                agg["total_s"] + float(s.get("total_s", 0.0) or 0.0), 6)
            agg["p50_ms"] = max(agg["p50_ms"], s.get("p50_ms", 0.0))
            agg["p99_ms"] = max(agg["p99_ms"], s.get("p99_ms", 0.0))
            if agg["count"]:
                agg["mean_ms"] = round(
                    agg["total_s"] / agg["count"] * 1e3, 4)
    return out


class MetricsRegistry:
    """Process-wide federation of named stats sources.

    A source is anything exposing ``snapshot() -> dict`` in the
    :class:`~mmlspark_tpu.core.profiling.StageStats` shape (a plain
    pre-built snapshot dict also works).  ``register`` REPLACES an
    existing namespace — the newest engine/watchdog instance wins, which
    is what a scrape of a restarted component should see."""

    def __init__(self, prefix: str = PREFIX):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._sources: Dict[str, Any] = {}

    def register(self, namespace: str, source: Any) -> Any:
        with self._lock:
            self._sources[namespace] = source
        return source

    def unregister(self, namespace: str) -> None:
        with self._lock:
            self._sources.pop(namespace, None)

    def namespaces(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._sources.items())
        out: Dict[str, dict] = {}
        for ns, src in items:
            try:
                out[ns] = (src.snapshot() if hasattr(src, "snapshot")
                           else dict(src))
            except Exception:  # noqa: BLE001 - one bad source must not
                continue       # fail the whole scrape
        return out

    def render_prometheus(self,
                          extra: Optional[Dict[str, dict]] = None) -> str:
        """Render every registered source (plus ``extra`` pre-built
        snapshot blocks — the multiprocess driver passes its workers'
        reported stats here) as Prometheus text."""
        snaps = self.snapshot()
        if extra:
            snaps.update(extra)
        return render_prometheus(snaps, self.prefix)


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every ``/metrics`` route renders."""
    return _registry


# -- event journal -----------------------------------------------------------


class EventJournal:
    """Bounded, thread-safe event ring with optional JSONL mirroring.

    ``emit`` stamps each record with a wall-clock ``ts`` and a
    process-monotonic ``seq`` (total order within one process; readers
    merging journals from several processes sort by ``(ts, seq)``).
    The in-memory ring is bounded (``capacity``), so an always-on
    journal can never grow without bound; :meth:`configure` additionally
    appends every record to a JSONL file for post-mortem reads."""

    def __init__(self, capacity: int = 8192, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=int(capacity))
        self._seq = 0
        self._fh = None
        if path:
            self.configure(path)

    def configure(self, path: Optional[str]) -> None:
        """Mirror subsequent events to ``path`` (append mode); ``None``
        stops mirroring.  Ring behavior is unchanged either way."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            if path:
                self._fh = open(path, "a", encoding="utf-8")

    def emit(self, ev: str, **fields) -> dict:
        rec: dict = {"ts": round(time.time(), 6), "ev": ev}
        rec.update(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec, default=str) + "\n")
                    self._fh.flush()
                except (OSError, ValueError):
                    pass   # a full disk must not kill the hot path
        return rec

    @contextmanager
    def span(self, name: str, **fields):
        """Emit ``<name>_begin`` / ``<name>_end`` (with ``dur_ms``)
        around the wrapped region."""
        t0 = time.perf_counter()
        self.emit(f"{name}_begin", **fields)
        try:
            yield
        finally:
            self.emit(f"{name}_end",
                      dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
                      **fields)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int = 50) -> List[dict]:
        with self._lock:
            return list(self._ring)[-int(n):]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, path: str) -> int:
        """Write the current ring to ``path`` as JSONL; returns the
        number of records written."""
        events = self.events()
        with open(path, "w", encoding="utf-8") as fh:
            for rec in events:
                fh.write(json.dumps(rec, default=str) + "\n")
        return len(events)


def read_journal(path: str) -> List[dict]:
    """Read a JSONL journal; malformed lines (torn tail after a crash)
    are skipped, not fatal — a post-mortem reader must read what's
    there."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


_journal = EventJournal()


def get_journal() -> EventJournal:
    """The process-global journal the engines emit into."""
    return _journal


# -- trace identity ----------------------------------------------------------


def new_trace_id() -> str:
    """A fresh 16-hex-char trace/span id."""
    return uuid.uuid4().hex[:16]


#: process-global (NOT thread-local) on purpose: the heartbeat watchdog
#: thread and the checkpoint writer both stamp the span of the fit the
#: process is running, which is a process-level fact (``train_stats`` is
#: process-global for the same reason).  Concurrent fits in one process
#: would interleave stamps — as they already interleave counters.
_current_fit = {"span": None}


def set_current_fit_span(span: Optional[str]) -> None:
    _current_fit["span"] = span


def current_fit_span() -> Optional[str]:
    return _current_fit["span"]
