"""Unified telemetry — the framework's observability subsystem.

The reference stack's observability story is the Spark UI plus the
``Timer`` pipeline stage (SURVEY.md §5.1).  This port grew three
DISCONNECTED stats surfaces instead — ``StageStats`` in the scoring
engine, the module-global ``train_stats`` in the GBDT engine, and the
elastic watchdog's heartbeat gauges — with no export endpoint and no way
to correlate a slow request with what actually happened.  This module
federates them (ISSUE 5):

* :class:`MetricsRegistry` — a process-wide registry of named stats
  sources (anything with a ``snapshot()`` in the
  :class:`~mmlspark_tpu.core.profiling.StageStats` shape), rendered as
  Prometheus text exposition for the ``/metrics`` route every serving
  server exposes (pull-model metrics, Prometheus-style).
* :class:`EventJournal` — a bounded, thread-safe event ring (optionally
  mirrored to a JSONL file): span begin/end, shed/expired/salvage,
  checkpoint save/resume/discard, peer_lost.  ``tools/trace_report.py``
  reconstructs per-request and per-fit timelines from it
  (Dapper-style correlated tracing, minus the distributed collector).
* Trace identity — :func:`new_trace_id` mints ids; a scoring request's
  trace id is the ``_trace_id`` its client sent, else the request id
  minted at admission (so every request is traceable without opt-in).
  A fit's span id is process-global (:func:`current_fit_span`) so the
  checkpoint writer and the heartbeat lease can stamp it without
  threading an argument through the whole engine.

Metric naming scheme (see docs/observability.md):

==============================================  =========  ==================
family                                          type       labels
==============================================  =========  ==================
``mmlspark_tpu_rows_total``                     counter    ``ns``
``mmlspark_tpu_rows_per_second``                gauge      ``ns``
``mmlspark_tpu_events_total``                   counter    ``ns``, ``event``
``mmlspark_tpu_gauge``                          gauge      ``ns``, ``name``
``mmlspark_tpu_stage_latency_seconds``          histogram  ``ns``, ``stage``, ``le``
==============================================  =========  ==================

(Plus the ``mmlspark_tpu_slo_*`` families rendered by
:mod:`mmlspark_tpu.core.slo` through the registry's exposition-provider
hook.)  ``ns`` is the registry namespace (``scoring``, ``train``,
``elastic``, ``serving_exchange``, ``worker<N>``/``workers`` for the
multiprocess topology's per-worker and aggregated blocks).

Stage latencies are log-bucketed histograms
(:class:`~mmlspark_tpu.core.profiling.LatencyStats`): the ``_bucket``
rows carry cumulative counts with ``le`` upper bounds, which is what
makes :func:`merge_snapshots` EXACT across workers — bucket counts sum,
and the aggregate percentile is recomputed from the summed buckets
instead of averaging per-worker estimates (ISSUE 8; "The Tail at
Scale" aggregation discipline).

This module additionally hosts the **crash flight recorder**
(:func:`record_flight`): on a worker death, chaos verdict failure or
unhandled engine exception, the journal tail + latest metrics
exposition + per-thread stacks are dumped atomically to a bounded,
rotated ``artifacts/flightrec_*.json`` set, so every post-mortem is
self-contained.

Everything here is stdlib-only and import-light: the serving hot path
and the training loop both call into it.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterable, List, Optional

from .profiling import percentile_from_buckets

PREFIX = "mmlspark_tpu"

# -- Prometheus text exposition ---------------------------------------------

#: family -> (type, help); histograms additionally emit
#: _bucket/_sum/_count rows
_FAMILIES = (
    ("rows_total", "counter", "Rows processed by this source."),
    ("rows_per_second", "gauge",
     "Rows/s over the source's active window."),
    ("events_total", "counter",
     "Named event counters (shed/expired/salvaged/restarted, "
     "ckpt_saved/ckpt_resumed/..., heartbeat_stalls/peer_lost, ...)."),
    ("gauge", "gauge",
     "Point-in-time levels (heartbeat_age_ms, ms_per_tree, ...)."),
    ("stage_latency_seconds", "histogram",
     "Per-stage wall-clock latency (log-bucketed, cross-worker "
     "mergeable)."),
)


def _esc(v: Any) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: Any) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if f != f:                       # NaN
        return "NaN"
    if f == float("inf"):            # before int(f): int(inf) raises,
        return "+Inf"                # and one inf gauge must not 503
    if f == float("-inf"):           # the whole scrape
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(d: Dict[str, Any]) -> str:
    return "{" + ",".join(f'{k}="{_esc(v)}"'
                          for k, v in sorted(d.items())) + "}"


def render_prometheus(snapshots: Dict[str, dict],
                      prefix: str = PREFIX) -> str:
    """Render ``{namespace: StageStats.snapshot()-shaped dict}`` as
    Prometheus text exposition (format 0.0.4).  Unknown/missing snapshot
    keys are skipped, never fatal — a scrape must not 500 because one
    source misbehaved."""
    rows: Dict[str, List[str]] = {fam: [] for fam, _, _ in _FAMILIES}
    for ns in sorted(snapshots):
        snap = snapshots[ns]
        if not isinstance(snap, dict):
            continue
        lab = {"ns": ns}
        if "rows" in snap:
            rows["rows_total"].append(
                f"{prefix}_rows_total{_labels(lab)} "
                f"{_fmt(snap.get('rows', 0))}")
            rows["rows_per_second"].append(
                f"{prefix}_rows_per_second{_labels(lab)} "
                f"{_fmt(snap.get('rows_per_s', 0.0))}")
        for name in sorted(snap.get("counters") or {}):
            rows["events_total"].append(
                f"{prefix}_events_total"
                f"{_labels({**lab, 'event': name})} "
                f"{_fmt(snap['counters'][name])}")
        for name in sorted(snap.get("gauges") or {}):
            rows["gauge"].append(
                f"{prefix}_gauge{_labels({**lab, 'name': name})} "
                f"{_fmt(snap['gauges'][name])}")
        for stage in sorted(snap.get("stages") or {}):
            s = snap["stages"][stage]
            if not isinstance(s, dict):
                continue
            slab = {**lab, "stage": stage}
            base = f"{prefix}_stage_latency_seconds"
            count = s.get("count", 0)
            # cumulative _bucket rows over the sparse occupied bounds
            # (Prometheus histograms allow any bound subset as long as
            # counts are cumulative and +Inf is present); snapshots
            # without buckets (hand-built test dicts, version-skewed
            # beacons) still render a valid +Inf-only histogram
            buckets = s.get("buckets") or {}
            cum = 0
            for le, c in sorted(
                    ((le, c) for le, c in buckets.items()
                     if le != "+Inf"),
                    key=lambda kv: float(kv[0])):
                cum += int(c)
                rows["stage_latency_seconds"].append(
                    f"{base}_bucket{_labels({**slab, 'le': le})} "
                    f"{cum}")
            rows["stage_latency_seconds"].append(
                f"{base}_bucket{_labels({**slab, 'le': '+Inf'})} "
                f"{_fmt(count)}")
            rows["stage_latency_seconds"].append(
                f"{base}_sum{_labels(slab)} {_fmt(s.get('total_s', 0.0))}")
            rows["stage_latency_seconds"].append(
                f"{base}_count{_labels(slab)} {_fmt(count)}")
    out: List[str] = []
    for fam, typ, help_ in _FAMILIES:
        if not rows[fam]:
            continue
        out.append(f"# HELP {prefix}_{fam} {help_}")
        out.append(f"# TYPE {prefix}_{fam} {typ}")
        out.extend(rows[fam])
    return "\n".join(out) + "\n" if out else "# no metrics registered\n"


#: point-in-time gauges whose cross-process aggregate is the SUM —
#: backlog/occupancy COUNTS where the fleet-wide total is the operable
#: number (total queued requests, total in-flight fan-outs), not the
#: single deepest member.  Level/ratio-style gauges (ages, busy
#: fractions, headroom ratios) stay max — summing two 0.6 busy
#: fractions into 1.2 would be nonsense.  Keyed by metric name so a
#: beacon from an older worker merges under the same policy as a local
#: snapshot (ISSUE 20 satellite).
GAUGE_SUM_NAMES = frozenset({
    "queue_depth", "fanout_inflight", "shards_awaited",
})
GAUGE_SUM_SUFFIXES = ("_depth", "_inflight")


def gauge_merge_mode(name: str) -> str:
    """``"min"`` | ``"sum"`` | ``"max"`` — the cross-process merge
    policy for a point-in-time gauge, keyed by its metric name:
    ``*_up`` health booleans take min (one degraded member must show),
    depth/in-flight backlog counts sum (the aggregate is the total
    backlog), everything else takes max (the worst level)."""
    if name.endswith("_up"):
        return "min"
    if name in GAUGE_SUM_NAMES or name.endswith(GAUGE_SUM_SUFFIXES):
        return "sum"
    return "max"


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Merge several StageStats snapshots into one aggregate (the
    "workers" total block of a multiprocess scrape): rows and counters
    SUM, rows/s sums (concurrent sources), gauges merge under the
    name-keyed :func:`gauge_merge_mode` policy — MIN for up-style
    health booleans (``*_up``, where 1 is healthy and one degraded
    member must show in the aggregate), SUM for depth/in-flight
    backlog counts (per-worker queue depths are point-in-time levels,
    but the fleet-wide backlog is their total — taking the max under-
    reported it), MAX for every other level-style gauge (ages, ratios,
    occupancies).  Stage latencies merge EXACTLY: the
    log-bucket counts every :class:`~mmlspark_tpu.core.profiling.
    LatencyStats` snapshot carries are key-wise summed and the
    aggregate p50/p99 recomputed from the combined buckets — the
    percentile OF the combined population at ladder resolution, not an
    average or max of per-worker estimates (ISSUE 8).  A source with no
    ``buckets`` (hand-built dicts, version-skewed beacons) degrades
    that stage to the old conservative max-of-percentiles bound."""
    out: dict = {"rows": 0, "rows_per_s": 0.0, "counters": {},
                 "gauges": {}, "stages": {}}
    bucketless: Dict[str, bool] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        out["rows"] += int(snap.get("rows", 0) or 0)
        out["rows_per_s"] = round(
            out["rows_per_s"] + float(snap.get("rows_per_s", 0.0) or 0.0),
            2)
        for k, v in (snap.get("counters") or {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + v
        for k, v in (snap.get("gauges") or {}).items():
            mode = gauge_merge_mode(k)
            if mode == "min":
                out["gauges"][k] = min(
                    out["gauges"].get(k, float("inf")), v)
            elif mode == "sum":
                out["gauges"][k] = out["gauges"].get(k, 0) + v
            else:
                out["gauges"][k] = max(
                    out["gauges"].get(k, float("-inf")), v)
        for stage, s in (snap.get("stages") or {}).items():
            if not isinstance(s, dict):
                continue
            agg = out["stages"].setdefault(
                stage, {"count": 0, "total_s": 0.0, "mean_ms": 0.0,
                        "p50_ms": 0.0, "p99_ms": 0.0, "buckets": {}})
            agg["count"] += int(s.get("count", 0) or 0)
            agg["total_s"] = round(
                agg["total_s"] + float(s.get("total_s", 0.0) or 0.0), 6)
            agg["p50_ms"] = max(agg["p50_ms"], s.get("p50_ms", 0.0))
            agg["p99_ms"] = max(agg["p99_ms"], s.get("p99_ms", 0.0))
            if isinstance(s.get("buckets"), dict):
                for le, c in s["buckets"].items():
                    agg["buckets"][le] = agg["buckets"].get(le, 0) \
                        + int(c)
            elif s.get("count"):
                bucketless[stage] = True
            if agg["count"]:
                agg["mean_ms"] = round(
                    agg["total_s"] / agg["count"] * 1e3, 4)
    for stage, agg in out["stages"].items():
        if bucketless.get(stage):
            # mixed bucketed/bucketless sources: a partial bucket set
            # under the full count would render every bucketless
            # sample as a >300s +Inf outlier — drop the buckets so the
            # stage degrades to a +Inf-only histogram consistently
            # with its conservative max-of-percentiles bound
            agg.pop("buckets", None)
        elif agg["buckets"]:
            agg["p50_ms"] = round(
                percentile_from_buckets(agg["buckets"], 50) * 1e3, 4)
            agg["p99_ms"] = round(
                percentile_from_buckets(agg["buckets"], 99) * 1e3, 4)
    return out


class MetricsRegistry:
    """Process-wide federation of named stats sources.

    A source is anything exposing ``snapshot() -> dict`` in the
    :class:`~mmlspark_tpu.core.profiling.StageStats` shape (a plain
    pre-built snapshot dict also works).  ``register`` REPLACES an
    existing namespace — the newest engine/watchdog instance wins, which
    is what a scrape of a restarted component should see."""

    def __init__(self, prefix: str = PREFIX):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._sources: Dict[str, Any] = {}
        self._expositions: Dict[str, Callable[[], str]] = {}

    def register(self, namespace: str, source: Any) -> Any:
        with self._lock:
            self._sources[namespace] = source
        return source

    def unregister(self, namespace: str) -> None:
        with self._lock:
            self._sources.pop(namespace, None)

    def register_exposition(self, name: str,
                            provider: Callable[[], str]) -> None:
        """Register a raw-exposition provider: ``provider()`` returns
        Prometheus text appended verbatim to every render.  This is how
        families OUTSIDE the StageStats shape (the SLO monitor's
        ``mmlspark_tpu_slo_*``) join the scrape without forcing their
        data through a snapshot dict."""
        with self._lock:
            self._expositions[name] = provider

    def unregister_exposition(self, name: str) -> None:
        with self._lock:
            self._expositions.pop(name, None)

    def namespaces(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._sources.items())
        out: Dict[str, dict] = {}
        for ns, src in items:
            try:
                out[ns] = (src.snapshot() if hasattr(src, "snapshot")
                           else dict(src))
            except Exception:  # noqa: BLE001 - one bad source must not
                continue       # fail the whole scrape
        return out

    def render_prometheus(self,
                          extra: Optional[Dict[str, dict]] = None) -> str:
        """Render every registered source (plus ``extra`` pre-built
        snapshot blocks — the multiprocess driver passes its workers'
        reported stats here) as Prometheus text, then append every
        registered exposition provider's families (one failing
        provider is skipped, never fatal to the scrape)."""
        snaps = self.snapshot()
        if extra:
            snaps.update(extra)
        text = render_prometheus(snaps, self.prefix)
        with self._lock:
            providers = list(self._expositions.items())
        for name, provider in providers:
            try:
                block = provider()
            except Exception:  # noqa: BLE001 - scrape must not 500
                continue
            if block:
                if not text.endswith("\n"):
                    text += "\n"
                text += block if block.endswith("\n") else block + "\n"
        return text


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every ``/metrics`` route renders."""
    return _registry


# -- event journal -----------------------------------------------------------


class EventJournal:
    """Bounded, thread-safe event ring with optional JSONL mirroring.

    ``emit`` stamps each record with a wall-clock ``ts``, the emitting
    ``pid`` (so merged multi-process journals attribute every event to
    its process) and a process-monotonic ``seq`` (total order within
    one process; readers merging journals from several processes sort
    by ``(ts, seq)``).  The in-memory ring is bounded (``capacity``),
    so an always-on journal can never grow without bound;
    :meth:`configure` additionally appends every record to a JSONL file
    for post-mortem reads, with size-capped rotation — when the mirror
    exceeds ``max_bytes`` it is renamed to ``<path>.1`` (replacing any
    previous ``.1``) and a fresh file starts, so the on-disk footprint
    is bounded by ~2x the cap (ISSUE 8 satellite)."""

    def __init__(self, capacity: int = 8192, path: Optional[str] = None,
                 max_bytes: int = 8 << 20):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=int(capacity))
        self._seq = 0
        self._fh = None
        self._path: Optional[str] = None
        self._max_bytes = int(max_bytes)
        self._written = 0
        if path:
            self.configure(path, max_bytes=max_bytes)

    def configure(self, path: Optional[str],
                  max_bytes: Optional[int] = None) -> None:
        """Mirror subsequent events to ``path`` (append mode); ``None``
        stops mirroring.  ``max_bytes`` caps the mirror file before it
        rotates to ``<path>.1``.  Ring behavior is unchanged either
        way."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            self._path = path or None
            if max_bytes is not None:
                self._max_bytes = int(max_bytes)
            if path:
                self._fh = open(path, "a", encoding="utf-8")
                try:
                    self._written = os.path.getsize(path)
                except OSError:
                    self._written = 0

    def _rotate_locked(self) -> None:
        """Close the mirror, shift it to ``.1`` (dropping the previous
        ``.1``), and reopen fresh.  Called under ``self._lock``."""
        try:
            self._fh.close()
        except OSError:
            pass
        try:
            os.replace(self._path, self._path + ".1")
        except OSError:
            pass   # rotation is best-effort; keep appending regardless
        try:
            self._fh = open(self._path, "a", encoding="utf-8")
        except OSError:
            self._fh = None
        self._written = 0

    def emit(self, ev: str, **fields) -> dict:
        rec: dict = {"ts": round(time.time(), 6), "ev": ev,
                     "pid": os.getpid()}
        rec.update(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            if self._fh is not None:
                try:
                    line = json.dumps(rec, default=str) + "\n"
                    self._fh.write(line)
                    self._fh.flush()
                    self._written += len(line)
                    if self._path and self._written > self._max_bytes:
                        self._rotate_locked()
                except (OSError, ValueError):
                    pass   # a full disk must not kill the hot path
        return rec

    @contextmanager
    def span(self, name: str, **fields):
        """Emit ``<name>_begin`` / ``<name>_end`` (with ``dur_ms``)
        around the wrapped region."""
        t0 = time.perf_counter()
        self.emit(f"{name}_begin", **fields)
        try:
            yield
        finally:
            self.emit(f"{name}_end",
                      dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
                      **fields)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int = 50) -> List[dict]:
        with self._lock:
            return list(self._ring)[-int(n):]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, path: str) -> int:
        """Write the current ring to ``path`` as JSONL, fsync'd —
        a dump is a post-mortem artifact, and a crash right after it
        must not leave a torn or page-cache-only file; returns the
        number of records written."""
        events = self.events()
        with open(path, "w", encoding="utf-8") as fh:
            for rec in events:
                fh.write(json.dumps(rec, default=str) + "\n")
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass
        return len(events)


def read_journal(path: str) -> List[dict]:
    """Read a JSONL journal; malformed lines (torn tail after a crash)
    are skipped, not fatal — a post-mortem reader must read what's
    there."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


_journal = EventJournal()


def get_journal() -> EventJournal:
    """The process-global journal the engines emit into."""
    return _journal


#: env var naming a directory every process (driver AND spawned
#: workers, which inherit the environment) mirrors its journal into —
#: the cross-process trace story depends on each side's journal being
#: readable after the fact
JOURNAL_DIR_ENV = "MMLSPARK_TPU_JOURNAL_DIR"


def mirror_journal_from_env(tag: str = "") -> Optional[str]:
    """If :data:`JOURNAL_DIR_ENV` is set, mirror this process's global
    journal to ``<dir>/journal_<tag>_<pid>.jsonl`` and return the path
    (``None`` when the env var is unset or the directory unusable).
    Worker entrypoints call this at startup so a driver-side tool can
    merge driver+worker journals into one cross-process timeline."""
    jdir = os.environ.get(JOURNAL_DIR_ENV)
    if not jdir:
        return None
    try:
        os.makedirs(jdir, exist_ok=True)
        name = f"journal_{tag}_{os.getpid()}.jsonl" if tag \
            else f"journal_{os.getpid()}.jsonl"
        path = os.path.join(jdir, name)
        _journal.configure(path)
        return path
    except OSError:
        return None


# -- crash flight recorder ---------------------------------------------------


FLIGHTREC_DIR_ENV = "MMLSPARK_TPU_FLIGHTREC_DIR"

_flight_lock = threading.Lock()
_flight_cfg = {"dir": None, "cap": 8, "min_interval_s": 5.0}
_flight_last: Dict[str, float] = {}


def configure_flight_recorder(directory: Optional[str] = None,
                              cap: Optional[int] = None,
                              min_interval_s: Optional[float] = None
                              ) -> None:
    """Set where flight records land (default: ``$MMLSPARK_TPU_
    FLIGHTREC_DIR`` or ``artifacts/``), how many are kept before the
    oldest rotate out, and the per-reason dump throttle."""
    with _flight_lock:
        if directory is not None:
            _flight_cfg["dir"] = directory
        if cap is not None:
            _flight_cfg["cap"] = max(1, int(cap))
        if min_interval_s is not None:
            _flight_cfg["min_interval_s"] = float(min_interval_s)


def _thread_stacks() -> Dict[str, str]:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        out[label] = "".join(traceback.format_stack(frame))
    return out


def record_flight(reason: str, context: Optional[dict] = None,
                  journal_tail: int = 400) -> Optional[str]:
    """Crash flight recorder (ISSUE 8): atomically dump the journal
    tail, the latest metrics exposition and every thread's stack to
    ``<dir>/flightrec_<utc>_<reason>_<pid>.json`` so a post-mortem is
    self-contained — no scrape to replay, no journal to hunt down.

    Bounded on every axis: the journal tail is capped, dumps of the
    same ``reason`` are throttled to one per ``min_interval_s``, and at
    most ``cap`` records are kept (oldest rotated out).  Never raises —
    a failing recorder must not worsen the crash it is recording.
    Returns the path written, or ``None`` when throttled/failed."""
    try:
        now = time.time()
        with _flight_lock:
            last = _flight_last.get(reason, 0.0)
            if now - last < _flight_cfg["min_interval_s"]:
                return None
            _flight_last[reason] = now
            directory = (_flight_cfg["dir"]
                         or os.environ.get(FLIGHTREC_DIR_ENV)
                         or "artifacts")
            cap = _flight_cfg["cap"]
        os.makedirs(directory, exist_ok=True)
        try:
            metrics = get_registry().render_prometheus()
        except Exception:  # noqa: BLE001
            metrics = "# metrics render failed\n"
        try:
            # the profiler lives one import down (it imports this
            # module); a flight record carries its snapshot so a
            # post-mortem has the cost attribution at crash time too
            from .profiler import get_profiler
            profile = get_profiler().snapshot()
        except Exception:  # noqa: BLE001 - recorder must not fail
            profile = None
        rec = {
            "reason": reason,
            "ts": round(now, 6),
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                 time.gmtime(now)),
            "pid": os.getpid(),
            "context": context or {},
            "fit_span": current_fit_span(),
            "journal_tail": get_journal().tail(journal_tail),
            "metrics_exposition": metrics,
            "profile": profile,
            "threads": _thread_stacks(),
        }
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:40]
        stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime(now))
        path = os.path.join(
            directory,
            f"flightrec_{stamp}_{int((now % 1) * 1e6):06d}"
            f"_{safe}_{os.getpid()}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(rec, fh, indent=1, default=str)
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass
        os.replace(tmp, path)
        # rotation: keep the newest `cap` records
        try:
            recs = sorted(
                (p for p in os.listdir(directory)
                 if p.startswith("flightrec_") and p.endswith(".json")),
                key=lambda p: os.path.getmtime(
                    os.path.join(directory, p)))
            for p in recs[:-cap]:
                os.unlink(os.path.join(directory, p))
        except OSError:
            pass
        return path
    except Exception:  # noqa: BLE001 - the recorder must never make a
        return None    # crash worse


# -- trace identity ----------------------------------------------------------


def host_info() -> dict:
    """Host CPU readings for bench/sentinel artifacts (ISSUE 12):
    ``cores_effective`` is what this process may actually RUN on —
    ``sched_getaffinity`` sees cgroup/affinity caps the advertised
    ``cpu_count`` does not.  ONE definition so the fleet-scaling gate,
    the bench host block, and the perf sentinel can never diverge on
    what "a core" means."""
    return {
        "cpu_count": os.cpu_count(),
        "cores_effective": (len(os.sched_getaffinity(0))
                            if hasattr(os, "sched_getaffinity")
                            else os.cpu_count()),
    }


def new_trace_id() -> str:
    """A fresh 16-hex-char trace/span id."""
    return uuid.uuid4().hex[:16]


#: process-global (NOT thread-local) on purpose: the heartbeat watchdog
#: thread and the checkpoint writer both stamp the span of the fit the
#: process is running, which is a process-level fact (``train_stats`` is
#: process-global for the same reason).  Concurrent fits in one process
#: would interleave stamps — as they already interleave counters.
_current_fit = {"span": None}


def set_current_fit_span(span: Optional[str]) -> None:
    _current_fit["span"] = span


def current_fit_span() -> Optional[str]:
    return _current_fit["span"]
