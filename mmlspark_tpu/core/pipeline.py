"""Estimator/Transformer/Pipeline protocol.

TPU-native analog of Spark ML's ``Pipeline`` stack that the reference builds
every component on (SURVEY.md §1 L2; reference core/contracts, expected paths,
UNVERIFIED).  Differences from the JVM original, by design:

* ``fit``/``transform`` take any supported table flavor (pandas / Arrow /
  dict-of-arrays / DataTable) and return the same flavor — see
  :mod:`mmlspark_tpu.core.schema`.
* Persistence is directory-based (JSON params + npz arrays) instead of
  Spark's ``MLWritable`` Parquet metadata — see
  :mod:`mmlspark_tpu.core.serialize`.
* ``Wrappable`` codegen is unnecessary (stages are already Python); in its
  place every concrete stage self-registers into ``STAGE_REGISTRY`` which the
  structural fuzzing tests iterate (SURVEY.md §4's "FuzzingTest" meta-suite).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Type

from .params import Params
from .schema import DataTable, TableLike, from_table, to_table
from . import serialize

# public stages only — drives fuzzing coverage enforcement (SURVEY.md §4)
STAGE_REGISTRY: Dict[str, Type["PipelineStage"]] = {}
# every concrete subclass — drives persistence class resolution; keyed both
# by (module, name) and by bare name (first registrant wins the bare key)
_ALL_STAGES: Dict[Any, Type["PipelineStage"]] = {}


class PipelineStage(Params):
    """Base of every stage.  Concrete subclasses auto-register."""

    #: subclasses may set False to opt out of the public registry (test stubs)
    _registrable = True

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        if cls.__dict__.get("__abstractstage__", False):
            return
        _ALL_STAGES[(cls.__module__, cls.__name__)] = cls
        # Bare-name fallback for persistence across module moves; first
        # registrant wins so later stubs cannot shadow a public stage.
        _ALL_STAGES.setdefault(cls.__name__, cls)
        if not cls.__name__.startswith("_") and cls._registrable:
            STAGE_REGISTRY[cls.__name__] = cls

    # -- persistence ---------------------------------------------------------

    def save(self, path: str, overwrite: bool = False) -> None:
        serialize.save_stage(self, path, overwrite=overwrite)

    def write(self):  # Spark-API compatibility shim
        return serialize.StageWriter(self)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        stage = serialize.load_stage(path)
        if not isinstance(stage, cls):
            raise TypeError(
                f"Loaded {type(stage).__name__}, expected {cls.__name__}")
        return stage

    @classmethod
    def read(cls):  # Spark-API compatibility shim
        return serialize.StageReader(cls)

    # -- optional hooks for stages holding non-Param state -------------------

    def _save_extra(self, path: str) -> None:
        """Persist non-Param state (arrays, vocab, ...) under ``path``."""

    def _load_extra(self, path: str) -> None:
        """Restore non-Param state saved by :meth:`_save_extra`."""


class Transformer(PipelineStage):
    __abstractstage__ = True

    def transform(self, dataset: TableLike) -> TableLike:
        table = to_table(dataset)
        out = self._transform(table)
        return from_table(out, dataset)

    def _transform(self, table: DataTable) -> DataTable:
        raise NotImplementedError


class Estimator(PipelineStage):
    __abstractstage__ = True

    def fit(self, dataset: TableLike, params: Optional[Dict[str, Any]] = None
            ) -> "Model":
        est = self.copy(params) if params else self
        table = to_table(dataset)
        model = est._fit(table)
        return model

    def _fit(self, table: DataTable) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""
    __abstractstage__ = True


class Pipeline(Estimator):
    """Chains stages; Estimators are fit in sequence, like Spark ML Pipeline."""

    def __init__(self, stages: Optional[List[PipelineStage]] = None, **kwargs):
        super().__init__(**kwargs)
        self._stages: List[PipelineStage] = list(stages or [])

    def setStages(self, stages: List[PipelineStage]) -> "Pipeline":
        self._stages = list(stages)
        return self

    def getStages(self) -> List[PipelineStage]:
        return list(self._stages)

    def _fit(self, table: DataTable) -> "PipelineModel":
        fitted: List[Transformer] = []
        current = table
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                model = stage._fit(current)
                fitted.append(model)
                if i < len(self._stages) - 1:
                    current = model._transform(current)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(self._stages) - 1:
                    current = stage._transform(current)
            else:
                raise TypeError(
                    f"Pipeline stage {i} is neither Estimator nor Transformer: "
                    f"{type(stage).__name__}")
        return PipelineModel(fitted)

    def _save_extra(self, path: str) -> None:
        serialize.save_stage_list(self._stages, os.path.join(path, "stages"))

    def _load_extra(self, path: str) -> None:
        self._stages = serialize.load_stage_list(os.path.join(path, "stages"))


class PipelineModel(Model):
    def __init__(self, stages: Optional[List[Transformer]] = None, **kwargs):
        super().__init__(**kwargs)
        self._stages: List[Transformer] = list(stages or [])

    @property
    def stages(self) -> List[Transformer]:
        return list(self._stages)

    def _transform(self, table: DataTable) -> DataTable:
        for stage in self._stages:
            table = stage._transform(table)
        return table

    def _save_extra(self, path: str) -> None:
        serialize.save_stage_list(self._stages, os.path.join(path, "stages"))

    def _load_extra(self, path: str) -> None:
        self._stages = serialize.load_stage_list(os.path.join(path, "stages"))
