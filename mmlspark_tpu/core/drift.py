"""Streaming drift monitor — live traffic vs the fit-time reference
profile (ISSUE 15 tentpole).

:class:`DriftMonitor` sits on the scoring hot path (the engine hands it
the already-decoded float32 batch and the margins it just scored),
maintains live :mod:`~mmlspark_tpu.core.sketch` sketches behind a
duty-cycle gate, and continuously compares them against the
:class:`~mmlspark_tpu.core.sketch.ReferenceProfile` captured at fit
time:

* **PSI / JS per feature** and for the prediction-margin distribution,
  plus null-rate deltas and out-of-training-range ratios.
* **Gauges** (``psi_worst`` / ``psi_prediction`` / ``null_delta_worst``
  / ``oor_worst``) published through the monitor's StageStats-shaped
  ``snapshot()`` so the :mod:`~mmlspark_tpu.core.slo` gauge objectives
  (``feature_drift`` / ``prediction_drift``) and the
  :class:`~mmlspark_tpu.io.rollout.RolloutController`'s live-traffic
  drift objective read them exactly like every other gauge.
* **Journal events** — ``drift_onset`` when a signal (a feature or the
  prediction distribution) crosses its PSI threshold with enough live
  evidence, ``drift_recovered`` when it drops back; onsets also write a
  crash-flight record so the post-mortem carries the scene.
* **Cross-process merging** — ``snapshot()["counters"]`` flattens the
  sketch tallies under stable keys (``f<j>.b<i>`` / ``f<j>.nan`` /
  ``m.b<i>`` ...), so the existing
  :func:`~mmlspark_tpu.core.telemetry.merge_snapshots` sums them
  EXACTLY like StageStats counters — the multiprocess stats beacon and
  ``tools/drift_report.py`` recompute divergences from the merged
  counts, never an average of per-worker PSIs.

Overhead contract (same discipline as the profiler's sampler): each
``observe`` measures its own cost and arms a cooldown of
``cost * (1/duty - 1)`` seconds, so the sketch work is bounded to a
``duty`` fraction of wall time no matter the traffic rate; batches
inside the cooldown only bump the ``rows_skipped`` counter.  The perf
sentinel A/Bs the whole path enabled-vs-disabled under a <3% p50 gate.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from .sketch import (ReferenceProfile, StreamSketch, js_divergence,
                     merge_sketch_snapshots, psi)
from .telemetry import (PREFIX, _fmt, _labels, get_journal,
                        get_registry, record_flight)

log = logging.getLogger(__name__)

__all__ = ["DriftConfig", "DriftMonitor", "drift_report_from_counters",
           "get_drift_monitor", "peek_drift_monitor",
           "set_drift_monitor", "sketches_from_counters"]

#: registry namespace the process-global monitor federates under
DRIFT_NS = "drift"


@dataclass
class DriftConfig:
    """Monitor knobs (docs/observability.md §Drift)."""
    #: duty-cycle cap on the sketch-update cost share of wall time —
    #: 2% keeps the whole path inside the perf sentinel's <3% p50
    #: overhead gate with margin for the per-batch fixed cost
    duty: float = 0.02
    #: PSI above this flags a feature as drifting
    psi_threshold: float = 0.25
    #: PSI above this flags the prediction distribution
    prediction_psi_threshold: float = 0.25
    #: absolute null-rate increase (live − reference) that flags a
    #: feature regardless of PSI (a NaN storm is a quality incident
    #: even while the non-null values still look on-distribution)
    null_delta_threshold: float = 0.10
    #: minimum live rows per signal before any verdict — PSI over a
    #: handful of rows is noise, and a false page is the one thing the
    #: clean-traffic drill forbids
    min_rows: int = 200
    #: re-evaluation cadence (evaluations are O(f · buckets), far
    #: heavier than an observe — never per batch)
    eval_interval_s: float = 1.0
    #: recency half-window: drift VERDICTS are computed over the last
    #: 1–2 windows of traffic (two rotating sketch epochs, exactly the
    #: LatencyStats discipline) so a shift that starts after days of
    #: clean history is judged against recent rows, not diluted under
    #: millions of historical ones; the CUMULATIVE counters the scrape
    #: merges keep the all-time totals regardless
    window_s: float = 600.0


class DriftMonitor:
    """Live sketches + reference comparison + alert state machine.

    Thread-safe; ``observe`` is the only hot-path entry point and is
    safe to call from several scoring workers at once.
    """

    GAUGE_SEED = ("psi_worst", "psi_prediction", "null_delta_worst",
                  "oor_worst")

    def __init__(self, profile: ReferenceProfile,
                 config: Optional[DriftConfig] = None, *,
                 enabled: bool = True):
        self.profile = profile
        self.cfg = config or DriftConfig()
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        # three sketch generations (LatencyStats' epoch discipline):
        # verdicts read prev+recent (the last 1-2 windows); rotation
        # folds the outgoing epoch into the cumulative sketch, so the
        # scrape counters always carry the exact all-time totals
        self._cum = profile.live_matrix_sketch()
        self._cum_m = profile.live_margin_sketch()
        self._recent = profile.live_matrix_sketch()
        self._recent_m = profile.live_margin_sketch()
        self._prev = None
        self._prev_m = None
        self._epoch_t = time.monotonic()
        # async sketch pipeline (the <3% overhead contract): the hot
        # path only gate-checks, copies the batch (a few KB) and
        # enqueues; a daemon drain thread does the actual
        # searchsorted/bincount work, so a sketch update never stalls
        # a scoring worker (and the closed-loop pipeline behind it)
        self._q: "queue.Queue" = queue.Queue(maxsize=8)
        self._last_cost = 1e-3
        self._thread: Optional[threading.Thread] = None
        self._thread_stop = threading.Event()
        self._rows_observed = 0
        self._rows_skipped = 0
        self._next_ok = 0.0
        self._last_eval = 0.0
        self._report: Dict[str, Any] = {}
        self._gauges: Dict[str, float] = {
            k: 0.0 for k in self.GAUGE_SEED}
        self._alerting: Dict[str, bool] = {}
        # reference dist vectors resolved once — evaluate() is called
        # on a cadence, but why re-ravel the profile every time
        self._ref_feats = [profile.ref_feature(j)
                           for j in range(profile.num_features)]
        self._ref_margin = profile.ref_margin()

    # -- hot path ------------------------------------------------------------

    def _roll_locked(self) -> None:
        """Rotate the recency epochs (called under the lock): the
        outgoing epoch merges into the cumulative sketch — counters
        lose nothing — and after a traffic gap of 2+ windows BOTH
        epochs are stale and fold away (the LatencyStats rule)."""
        elapsed = time.monotonic() - self._epoch_t
        if elapsed < self.cfg.window_s:
            return
        if self._prev is not None:
            self._cum.merge(self._prev)
            self._cum_m.merge(self._prev_m)
        if elapsed >= 2 * self.cfg.window_s:
            self._cum.merge(self._recent)
            self._cum_m.merge(self._recent_m)
            self._recent = self.profile.live_matrix_sketch()
            self._recent_m = self.profile.live_margin_sketch()
            self._prev = None
            self._prev_m = None
        else:
            self._prev = self._recent
            self._prev_m = self._recent_m
            self._recent = self.profile.live_matrix_sketch()
            self._recent_m = self.profile.live_margin_sketch()
        self._epoch_t = time.monotonic()

    def observe(self, X, margins=None) -> bool:
        """Offer one scored batch (decoded float32 rows + the margins
        they scored to).  Returns True when the batch was accepted for
        sketching, False when the duty-cycle gate (or a full queue)
        skipped it.  Never raises — a drift-observation bug must not
        fail a scoring batch.

        Hot-path contract: one LOCK-FREE clock read against
        ``_next_ok``, then (gate open) a defensive copy of the batch
        and a non-blocking enqueue — the searchsorted/bincount sketch
        work runs on the monitor's daemon drain thread, never inline
        with scoring.  Skip accounting is best-effort (plain, unlocked
        increments): a racing pair of workers can under-count
        ``rows_skipped`` or both slip through one gate window, which
        costs one extra queued update, not correctness."""
        if not self.enabled:
            return False
        now = time.perf_counter()
        if now < self._next_ok:
            try:
                self._rows_skipped += len(X)
            except TypeError:
                pass
            return False
        try:
            X = np.asarray(X)
            if X.ndim != 2:
                return False
            n = int(X.shape[0])
            item = (np.array(X, np.float32, copy=True),
                    None if margins is None
                    else np.array(margins, copy=True), n)
            self._q.put_nowait(item)
        except queue.Full:
            self._rows_skipped += n
            return False
        except Exception:  # noqa: BLE001 - observation is advisory
            log.exception("drift observe failed; batch skipped")
            return False
        # provisional cooldown from the LAST measured update cost (the
        # drain thread refines it after this update actually runs) so a
        # burst cannot flood the queue inside one gate window
        duty = max(1e-4, float(self.cfg.duty))
        self._next_ok = now + self._last_cost * (1.0 / duty - 1.0)
        self._ensure_thread()
        return True

    def _ensure_thread(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._drain, name="drift-sketch",
                    daemon=True)
                self._thread.start()

    def _drain(self) -> None:
        """Daemon worker: apply queued batch updates to the sketches
        and keep the duty-cycle cooldown honest with measured costs."""
        while not self._thread_stop.is_set():
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                if item is None:
                    return
                X, margins, n = item
                with self._lock:
                    self._roll_locked()
                    t0 = time.perf_counter()
                    self._recent.update(X)
                    if margins is not None:
                        self._recent_m.update(margins)
                    self._rows_observed += n
                    cost = time.perf_counter() - t0
                self._last_cost = cost
                duty = max(1e-4, float(self.cfg.duty))
                self._next_ok = time.perf_counter() \
                    + cost * (1.0 / duty - 1.0)
            except Exception:  # noqa: BLE001 - one bad batch must not
                log.exception("drift sketch update failed")
            finally:
                self._q.task_done()

    def flush(self, timeout: float = 2.0) -> bool:
        """Wait (bounded) until every queued batch has been sketched —
        control-plane callers (reports, drills, tests) read AFTER the
        async pipeline drained.  Returns False on timeout."""
        deadline = time.monotonic() + timeout
        # Queue.join() has no timeout; unfinished_tasks counts queued
        # AND in-flight items (decremented by task_done), which is
        # exactly the "work outstanding" signal a bounded wait needs
        while self._q.unfinished_tasks:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True

    def close(self) -> None:
        """Stop the drain thread (idempotent; queued work is
        abandoned).  Monitors are normally process-lifetime — this is
        for tests and tools that create many."""
        self._thread_stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    # -- sketch views --------------------------------------------------------

    def _parts_locked(self, window_only: bool):
        parts = [] if window_only else [(self._cum, self._cum_m)]
        if self._prev is not None:
            parts.append((self._prev, self._prev_m))
        parts.append((self._recent, self._recent_m))
        return parts

    def _merged_locked(self, window_only: bool):
        """(feature sketches, margin sketch) merged over the chosen
        epochs; a window with no traffic degrades to the lifetime
        view instead of judging an empty sketch."""
        parts = self._parts_locked(window_only)
        feats: List[StreamSketch] = []
        for j in range(self.profile.num_features):
            lo, hi = self.profile.feature_span(j)
            snap = merge_sketch_snapshots(
                [p[0].features[j].snapshot() for p in parts])
            feats.append(StreamSketch.from_snapshot(
                snap, self.profile.feature_edges[j], lo, hi))
        margin = StreamSketch.from_snapshot(
            merge_sketch_snapshots([p[1].snapshot() for p in parts]),
            self.profile.margin_edges)
        if window_only and margin.total == 0 \
                and all(f.total == 0 for f in feats):
            return self._merged_locked(False)
        return feats, margin

    # -- evaluation ----------------------------------------------------------

    def _signal_reports(self) -> List[Dict[str, Any]]:
        """Per-signal comparison rows (features + ``_prediction_``),
        judged over the recent 1-2 windows (lifetime fallback when the
        window is empty)."""
        rows: List[Dict[str, Any]] = []
        with self._lock:
            self._roll_locked()
            live_feats, live_margin = self._merged_locked(True)
        for j, live in enumerate(live_feats):
            ref = self._ref_feats[j]
            rows.append(self._compare(
                self.profile.feature_names[j], ref, live,
                feature_index=j))
        rows.append(self._compare("_prediction_", self._ref_margin,
                                  live_margin, feature_index=None))
        return rows

    def _compare(self, name: str, ref: StreamSketch,
                 live: StreamSketch,
                 feature_index: Optional[int]) -> Dict[str, Any]:
        rows = live.total
        enough = rows >= self.cfg.min_rows
        p = psi(ref.dist_counts(), live.dist_counts()) if enough \
            else 0.0
        js = js_divergence(ref.dist_counts(), live.dist_counts()) \
            if enough else 0.0
        null_ref = ref.null_rate()
        null_live = live.null_rate()
        rec = {
            "signal": name,
            "feature_index": feature_index,
            "rows": rows,
            "enough_rows": enough,
            "psi": round(p, 6),
            "js": round(js, 6),
            "null_rate_ref": round(null_ref, 6),
            "null_rate_live": round(null_live, 6),
            "null_delta": round(null_live - null_ref, 6),
            "oor_rate": round(live.oor_rate(), 6),
            "mean_ref": round(ref.mean, 6),
            "mean_live": round(live.mean, 6),
            "quantiles_ref": [round(ref.quantile(q), 6)
                              for q in (0.1, 0.5, 0.9)],
            "quantiles_live": [round(live.quantile(q), 6)
                               for q in (0.1, 0.5, 0.9)],
        }
        thr = self.cfg.prediction_psi_threshold \
            if name == "_prediction_" else self.cfg.psi_threshold
        rec["alert"] = bool(enough and (
            p > thr
            or (feature_index is not None
                and rec["null_delta"] > self.cfg.null_delta_threshold)))
        return rec

    def evaluate(self, force: bool = False) -> Dict[str, Any]:
        """Recompute the drift report (rate-limited unless ``force``),
        refresh the gauges, and journal alert transitions."""
        now = time.monotonic()
        with self._lock:
            if not force and self._report \
                    and now - self._last_eval < self.cfg.eval_interval_s:
                return self._report
            self._last_eval = now
        signals = self._signal_reports()
        feat = [s for s in signals if s["feature_index"] is not None]
        pred = signals[-1]
        worst = max(feat, key=lambda s: s["psi"], default=None)
        gauges = {
            "psi_worst": max((s["psi"] for s in feat), default=0.0),
            "psi_prediction": pred["psi"],
            "null_delta_worst": max(
                (s["null_delta"] for s in feat), default=0.0),
            "oor_worst": max((s["oor_rate"] for s in feat),
                             default=0.0),
        }
        report = {
            "signals": signals,
            "worst_feature": worst["signal"] if worst else None,
            "alerting": sorted(s["signal"] for s in signals
                               if s["alert"]),
            "gauges": {k: round(v, 6) for k, v in gauges.items()},
            "rows_observed": self._rows_observed,
            "rows_skipped": self._rows_skipped,
            "thresholds": {
                "psi": self.cfg.psi_threshold,
                "prediction_psi": self.cfg.prediction_psi_threshold,
                "null_delta": self.cfg.null_delta_threshold,
                "min_rows": self.cfg.min_rows,
            },
        }
        transitions = []
        with self._lock:
            self._gauges.update(gauges)
            self._report = report
            for s in signals:
                was = self._alerting.get(s["signal"], False)
                if s["alert"] != was:
                    self._alerting[s["signal"]] = s["alert"]
                    transitions.append(s)
        for s in transitions:
            ev = {"signal": s["signal"], "psi": s["psi"],
                  "null_delta": s["null_delta"], "rows": s["rows"]}
            if s["alert"]:
                get_journal().emit("drift_onset", **ev)
                record_flight("drift_onset", ev)
            else:
                get_journal().emit("drift_recovered", **ev)
        return report

    def report(self) -> Dict[str, Any]:
        """Drained, freshly-evaluated report (the control-plane read)."""
        self.flush()
        return self.evaluate(force=True)

    # -- telemetry surfaces --------------------------------------------------

    @staticmethod
    def _flat_counters(feature_snaps: List[dict],
                       margin_snap: dict) -> Dict[str, int]:
        """The cross-process wire form: every sketch tally flattened
        under stable keys so plain counter summing
        (:func:`~mmlspark_tpu.core.telemetry.merge_snapshots`) IS
        sketch merging.  Keys: ``f<j>.b<i>`` bucket counts,
        ``f<j>.{n,nan,below,above}`` tallies, ``m.*`` for the margin
        sketch."""
        out: Dict[str, int] = {}

        def emit(prefix: str, snap: dict) -> None:
            out[f"{prefix}.n"] = int(snap.get("n", 0) or 0)
            for k in ("nan", "below", "above"):
                out[f"{prefix}.{k}"] = int(snap.get(k, 0) or 0)
            for b, c in (snap.get("buckets") or {}).items():
                out[f"{prefix}.b{b}"] = int(c)

        for j, snap in enumerate(feature_snaps):
            emit(f"f{j}", snap)
        emit("m", margin_snap)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """StageStats-shaped block for the metrics registry / worker
        stats beacon: counters carry the flattened sketch counts (sum
        across workers = the merged sketch), gauges the current PSI
        readings (max across workers = the worst arm — the
        ``merge_snapshots`` gauge convention)."""
        self.evaluate()
        with self._lock:
            feats, margin = self._merged_locked(False)
            counters = self._flat_counters(
                [f.snapshot() for f in feats], margin.snapshot())
            counters["rows_observed"] = self._rows_observed
            counters["rows_skipped"] = self._rows_skipped
            gauges = dict(self._gauges)
        return {"rows": self._rows_observed, "rows_per_s": 0.0,
                "counters": counters, "gauges": gauges, "stages": {}}

    def render_prometheus(self, prefix: str = PREFIX) -> str:
        """The ``mmlspark_tpu_drift_*`` families (appended to the
        process scrape through ``register_exposition``)."""
        report = self.evaluate()
        lines: List[str] = []

        def fam(suffix: str, typ: str, help_: str) -> str:
            name = f"{prefix}_drift_{suffix}"
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {typ}")
            return name

        n = fam("enabled", "gauge",
                "1 while a drift monitor is observing this process's "
                "scoring traffic.")
        lines.append(f"{n} {1 if self.enabled else 0}")
        n = fam("rows_total", "counter",
                "Rows sketched vs skipped by the duty-cycle gate.")
        lines.append(f'{n}{_labels({"state": "observed"})} '
                     f'{report["rows_observed"]}')
        lines.append(f'{n}{_labels({"state": "skipped"})} '
                     f'{report["rows_skipped"]}')
        sigs = report["signals"]
        n = fam("psi", "gauge",
                "Population Stability Index per signal (features + "
                "_prediction_), live vs fit-time reference.")
        for s in sigs:
            lines.append(f'{n}{_labels({"signal": s["signal"]})} '
                         f'{_fmt(s["psi"])}')
        n = fam("js", "gauge",
                "Jensen-Shannon divergence (base 2) per signal.")
        for s in sigs:
            lines.append(f'{n}{_labels({"signal": s["signal"]})} '
                         f'{_fmt(s["js"])}')
        n = fam("null_rate", "gauge",
                "Null (NaN/missing) rate per signal and source.")
        for s in sigs:
            lines.append(
                f'{n}{_labels({"signal": s["signal"], "src": "reference"})}'
                f' {_fmt(s["null_rate_ref"])}')
            lines.append(
                f'{n}{_labels({"signal": s["signal"], "src": "live"})}'
                f' {_fmt(s["null_rate_live"])}')
        n = fam("out_of_range_ratio", "gauge",
                "Fraction of live finite values outside the training "
                "edge span.")
        for s in sigs:
            if s["feature_index"] is not None:
                lines.append(f'{n}{_labels({"signal": s["signal"]})} '
                             f'{_fmt(s["oor_rate"])}')
        n = fam("alert", "gauge",
                "1 while the signal is over its drift threshold "
                "(instantaneous; the SLO burn gate adds the windowed "
                "verdict).")
        for s in sigs:
            lines.append(f'{n}{_labels({"signal": s["signal"]})} '
                         f'{1 if s["alert"] else 0}')
        return "\n".join(lines) + "\n"


# -- merged-counter readers ---------------------------------------------------


def sketches_from_counters(counters: Dict[str, Any],
                           profile: ReferenceProfile):
    """Inverse of ``DriftMonitor.snapshot()``'s counter flattening:
    rebuild per-feature + margin :class:`StreamSketch` objects from a
    (possibly cross-process-merged) ``counters`` dict.  This is how
    ``tools/drift_report.py`` and the drill read a merged scrape."""
    def collect(prefix: str) -> Dict[str, Any]:
        snap: Dict[str, Any] = {"buckets": {}}
        plen = len(prefix) + 1
        for k, v in counters.items():
            if not k.startswith(prefix + "."):
                continue
            sub = k[plen:]
            if sub.startswith("b") and sub[1:].isdigit():
                snap["buckets"][sub[1:]] = int(v)
            else:
                snap[sub] = int(v)
        return snap

    feats = []
    for j in range(profile.num_features):
        lo, hi = profile.feature_span(j)
        feats.append(StreamSketch.from_snapshot(
            collect(f"f{j}"), profile.feature_edges[j], lo, hi))
    margin = StreamSketch.from_snapshot(collect("m"),
                                        profile.margin_edges)
    return feats, margin


def drift_report_from_counters(counters: Dict[str, Any],
                               profile: ReferenceProfile,
                               config: Optional[DriftConfig] = None
                               ) -> Dict[str, Any]:
    """Full drift report off merged counters (the driver-side /
    offline view over any number of workers' summed snapshots)."""
    mon = DriftMonitor(profile, config)
    feats, margin = sketches_from_counters(counters, profile)
    for sk, live in zip(mon._cum.features, feats):
        sk.merge(live)
    mon._cum_m.merge(margin)
    mon._rows_observed = int(counters.get("rows_observed", 0) or 0)
    mon._rows_skipped = int(counters.get("rows_skipped", 0) or 0)
    return mon.evaluate(force=True)


# -- process-global wiring ----------------------------------------------------


_monitor_lock = threading.Lock()
_monitor: Optional[DriftMonitor] = None


def set_drift_monitor(monitor: Optional[DriftMonitor]
                      ) -> Optional[DriftMonitor]:
    """Install ``monitor`` as the process-global drift monitor: it
    federates under ``ns="drift"`` in the metrics registry (which is
    what the SLO gauge objectives and the worker stats beacon read) and
    renders the ``mmlspark_tpu_drift_*`` families into every scrape.
    ``None`` uninstalls."""
    global _monitor
    with _monitor_lock:
        _monitor = monitor
        reg = get_registry()
        if monitor is None:
            reg.unregister(DRIFT_NS)
            reg.unregister_exposition("drift")
        else:
            reg.register(DRIFT_NS, monitor)
            reg.register_exposition(
                "drift", lambda: _monitor.render_prometheus()
                if _monitor is not None else "")
        return monitor


def peek_drift_monitor() -> Optional[DriftMonitor]:
    """The installed monitor, or None — never creates one (a drift
    monitor is meaningless without a reference profile)."""
    return _monitor


def get_drift_monitor() -> Optional[DriftMonitor]:
    return peek_drift_monitor()
