"""Tabular data adapter — the framework's "DataFrame" boundary.

The reference operates on Spark DataFrames (reference layer L1, SURVEY.md §1).
A TPU-native framework has no JVM; its natural data plane is Arrow/pandas/
numpy on the host feeding ``jax.numpy`` arrays on device.  This module defines
a minimal columnar ``DataTable`` plus conversion helpers so that every stage
accepts, interchangeably:

* ``pandas.DataFrame`` (vector columns = object columns of 1-D arrays/lists)
* ``pyarrow.Table``
* ``dict[str, np.ndarray]`` (a 2-D array is a "vector column")
* ``DataTable`` itself

and returns the same flavor it was given, mirroring the reference's
DataFrame-in/DataFrame-out Transformer contract
(core/schema/DatasetExtensions.scala, expected path, UNVERIFIED).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

try:  # pandas is baked into the image, but keep it soft anyway
    import pandas as pd
except ImportError:  # pragma: no cover
    pd = None

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover
    pa = None


ColumnLike = np.ndarray  # rows on axis 0: 1-D scalar, 2-D vector, N-D tensor
TableLike = Union["DataTable", "pd.DataFrame", "pa.Table", Dict[str, Any]]


class DataTable:
    """An ordered, column-oriented table backed by numpy arrays.

    Columns are 1-D numpy arrays (scalar columns), 2-D numpy arrays
    (fixed-width vector columns — the analog of Spark ML vector columns),
    or higher-rank arrays whose leading axis is the row axis (e.g. NHWC
    image batches).  Object-dtype 1-D columns may hold arbitrary python
    payloads (image structs, HTTP responses) just as Spark rows may hold
    structs.
    """

    def __init__(self, columns: Dict[str, Any]):
        self._cols: Dict[str, np.ndarray] = {}
        n = None
        for name, col in columns.items():
            arr = _as_column(col)
            if n is None:
                n = arr.shape[0]
            elif arr.shape[0] != n:
                raise ValueError(
                    f"Column {name!r} has length {arr.shape[0]}, expected {n}")
            self._cols[name] = arr
        self._n = 0 if n is None else int(n)

    # -- basic protocol ------------------------------------------------------

    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return self._n

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(
                f"Column {name!r} not found; available: {self.columns}")
        return self._cols[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._cols)

    def column(self, name: str) -> np.ndarray:
        return self[name]

    # -- functional updates (tables are treated as immutable by stages) -----

    def withColumn(self, name: str, col: Any) -> "DataTable":
        cols = dict(self._cols)
        cols[name] = col
        return DataTable(cols)

    def withColumns(self, new: Dict[str, Any]) -> "DataTable":
        cols = dict(self._cols)
        cols.update(new)
        return DataTable(cols)

    def drop(self, *names: str) -> "DataTable":
        return DataTable({k: v for k, v in self._cols.items() if k not in names})

    def select(self, *names: str) -> "DataTable":
        return DataTable({k: self[k] for k in names})

    def rename(self, mapping: Dict[str, str]) -> "DataTable":
        return DataTable({mapping.get(k, k): v for k, v in self._cols.items()})

    def take(self, idx: np.ndarray) -> "DataTable":
        """Row-select by integer index or boolean mask."""
        idx = np.asarray(idx)
        return DataTable({k: v[idx] for k, v in self._cols.items()})

    def head(self, n: int = 5) -> "DataTable":
        return self.take(np.arange(min(n, self._n)))

    def slice(self, start: int, stop: int) -> "DataTable":
        """Contiguous row range [start, stop) as a new table (views)."""
        return DataTable({k: v[start:stop] for k, v in self._cols.items()})

    def concat(self, other: "DataTable") -> "DataTable":
        if set(self.columns) != set(other.columns):
            raise ValueError("Cannot concat tables with differing columns")
        return DataTable({
            k: np.concatenate([self._cols[k], other._cols[k]], axis=0)
            for k in self._cols})

    # -- conversions ---------------------------------------------------------

    def toPandas(self) -> "pd.DataFrame":
        if pd is None:  # pragma: no cover
            raise ImportError("pandas is not available")
        data = {}
        for k, v in self._cols.items():
            if v.ndim >= 2:
                data[k] = list(v)  # vector/tensor column -> object column
            else:
                data[k] = v
        return pd.DataFrame(data)

    def toArrow(self) -> "pa.Table":
        if pa is None:  # pragma: no cover
            raise ImportError("pyarrow is not available")
        arrays, names = [], []
        for k, v in self._cols.items():
            names.append(k)
            if v.ndim == 2:
                arrays.append(pa.FixedSizeListArray.from_arrays(
                    pa.array(v.reshape(-1)), v.shape[1]))
            elif v.ndim > 2:
                raise ValueError(
                    f"Column {k!r} has shape {v.shape}; tensor columns "
                    "(rank > 2) cannot round-trip Arrow without losing their "
                    "shape — reshape to 2-D or keep the DataTable flavor")
            else:
                arrays.append(pa.array(v))
        return pa.Table.from_arrays(arrays, names=names)

    def toDict(self) -> Dict[str, np.ndarray]:
        return dict(self._cols)

    def __repr__(self) -> str:
        specs = ", ".join(
            f"{k}:{v.dtype}{list(v.shape[1:]) if v.ndim > 1 else ''}"
            for k, v in self._cols.items())
        return f"DataTable[{self._n} rows]({specs})"


def _as_column(col: Any) -> np.ndarray:
    """Normalize a column to a numpy array with rows on axis 0."""
    if isinstance(col, np.ndarray):
        if col.ndim >= 1:
            return col
        raise ValueError("Columns must have at least one axis")
    if pd is not None and isinstance(col, pd.Series):
        return _series_to_column(col)
    if pa is not None and isinstance(col, (pa.Array, pa.ChunkedArray)):
        return _arrow_to_column(col)
    arr = np.asarray(col)
    if arr.dtype == object and arr.ndim == 1 and len(arr) > 0:
        first = arr[0]
        if isinstance(first, (list, tuple, np.ndarray)) and not isinstance(
                first, (str, bytes)):
            try:
                return np.stack([np.asarray(x, dtype=np.float64) for x in arr])
            except (ValueError, TypeError):
                return arr  # ragged or non-numeric payloads stay object
    if arr.ndim >= 1:
        return arr
    raise ValueError("Columns must have at least one axis")


def _series_to_column(s: "pd.Series") -> np.ndarray:
    if s.dtype == object and len(s) > 0:
        first = s.iloc[0]
        if isinstance(first, (list, tuple, np.ndarray)) and not isinstance(
                first, (str, bytes)):
            try:
                return np.stack(
                    [np.asarray(x, dtype=np.float64) for x in s.to_numpy()])
            except (ValueError, TypeError):
                return s.to_numpy()
    if str(s.dtype) == "category":
        return s.astype(object).to_numpy()
    return s.to_numpy()


def _arrow_to_column(a) -> np.ndarray:
    if isinstance(a, pa.ChunkedArray):
        a = a.combine_chunks()
    if pa.types.is_fixed_size_list(a.type):
        width = a.type.list_size
        flat = a.flatten().to_numpy(zero_copy_only=False)
        return flat.reshape(-1, width)
    if pa.types.is_list(a.type) or pa.types.is_large_list(a.type):
        rows = a.to_pylist()
        return np.stack([np.asarray(r, dtype=np.float64) for r in rows])
    return a.to_numpy(zero_copy_only=False)


# -- public entry points -----------------------------------------------------

def to_table(data: TableLike) -> DataTable:
    """Convert any supported tabular input to a :class:`DataTable`."""
    if isinstance(data, DataTable):
        return data
    if pd is not None and isinstance(data, pd.DataFrame):
        return DataTable({c: _series_to_column(data[c]) for c in data.columns})
    if pa is not None and isinstance(data, pa.Table):
        return DataTable(
            {name: _arrow_to_column(data.column(name))
             for name in data.column_names})
    if isinstance(data, dict):
        return DataTable(data)
    raise TypeError(
        f"Unsupported table type {type(data).__name__}; expected DataTable, "
        "pandas.DataFrame, pyarrow.Table, or dict of arrays")


def from_table(table: DataTable, like: TableLike) -> TableLike:
    """Convert a DataTable back to the flavor of ``like``.

    When the row count is unchanged, a pandas input's index is propagated to
    the output so callers can join/assign against their original frame.
    """
    if isinstance(like, DataTable):
        return table
    if pd is not None and isinstance(like, pd.DataFrame):
        out = table.toPandas()
        if len(out) == len(like):
            out.index = like.index
        return out
    if pa is not None and isinstance(like, pa.Table):
        return table.toArrow()
    if isinstance(like, dict):
        return table.toDict()
    return table


def features_matrix(table: DataTable, featuresCol: str) -> np.ndarray:
    """Fetch a 2-D float feature matrix from a vector column."""
    col = table[featuresCol]
    if col.ndim != 2:
        raise ValueError(
            f"Column {featuresCol!r} is not a vector column (shape {col.shape}); "
            "use Featurize/AssembleFeatures to build one, or pass featureCols")
    return np.ascontiguousarray(col, dtype=np.float64)
