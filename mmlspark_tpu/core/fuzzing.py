"""Structural fuzzing harness.

Re-creation of the reference's signature testing idea (SURVEY.md §4;
core/test/fuzzing/Fuzzing.scala, expected path, UNVERIFIED): every public
stage declares *test objects* — an instance plus fitting/transform data —
and from that single declaration the harness derives, automatically:

* **SerializationFuzzing** — save/load round-trip of the stage (and of the
  fitted model for estimators), then re-fit / re-transform and compare.
* **ExperimentFuzzing** — fit→transform smoke execution.

A meta-check (tests/test_fuzzing.py) asserts every class in
``STAGE_REGISTRY`` has a registered test-object provider, so coverage is
enforced structurally exactly as the reference's "FuzzingTest" does by
reflecting over the jar.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .pipeline import Estimator, PipelineStage, Transformer
from .schema import TableLike


@dataclass
class TestObject:
    """One fuzzing scenario: a stage plus the data to exercise it with."""
    stage: PipelineStage
    fitting_data: Optional[TableLike] = None     # estimators
    transform_data: Optional[TableLike] = None   # transformers / fitted models
    #: columns whose values must round-trip exactly through save/load re-runs
    compare_cols: Optional[List[str]] = None
    #: tolerance for numeric comparison
    tol: float = 1e-6
    #: class name the estimator's ``fit`` must produce — lets the meta-test
    #: count Model classes as covered, and the serialization test verify the
    #: declaration (a wrong name fails the assert, so coverage stays honest)
    fitted_model_cls: Optional[str] = None
    #: external-IO stages (live REST endpoints) fuzz persistence only, like
    #: the reference's secret-gated cognitive suites (SURVEY.md §4)
    serialization_only: bool = False
    #: reason a scenario cannot round-trip persistence (must be non-empty
    #: when set); the experiment smoke still runs
    skip_serialization: Optional[str] = None


# class name -> provider returning scenarios
_PROVIDERS: Dict[str, Callable[[], List[TestObject]]] = {}

#: stage class names exempt from fuzzing (abstract shims, external-IO stages
#: that cannot run hermetically).  Every exemption must carry a reason.
EXEMPT: Dict[str, str] = {}


def fuzzing_objects(cls_name: str):
    """Decorator registering a test-object provider for a stage class."""
    def deco(fn: Callable[[], List[TestObject]]):
        _PROVIDERS[cls_name] = fn
        return fn
    return deco


def exempt(cls_name: str, reason: str) -> None:
    EXEMPT[cls_name] = reason


def get_provider(cls_name: str) -> Optional[Callable[[], List[TestObject]]]:
    return _PROVIDERS.get(cls_name)


def all_providers() -> Dict[str, Callable[[], List[TestObject]]]:
    return dict(_PROVIDERS)
