"""Structural fuzzing harness.

Re-creation of the reference's signature testing idea (SURVEY.md §4;
core/test/fuzzing/Fuzzing.scala, expected path, UNVERIFIED): every public
stage declares *test objects* — an instance plus fitting/transform data —
and from that single declaration the harness derives, automatically:

* **SerializationFuzzing** — save/load round-trip of the stage (and of the
  fitted model for estimators), then re-fit / re-transform and compare.
* **ExperimentFuzzing** — fit→transform smoke execution.

A meta-check (tests/test_fuzzing.py) asserts every class in
``STAGE_REGISTRY`` has a registered test-object provider, so coverage is
enforced structurally exactly as the reference's "FuzzingTest" does by
reflecting over the jar.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .pipeline import Estimator, PipelineStage, Transformer
from .schema import TableLike


@dataclass
class TestObject:
    """One fuzzing scenario: a stage plus the data to exercise it with."""
    stage: PipelineStage
    fitting_data: Optional[TableLike] = None     # estimators
    transform_data: Optional[TableLike] = None   # transformers / fitted models
    #: columns whose values must round-trip exactly through save/load re-runs
    compare_cols: Optional[List[str]] = None
    #: tolerance for numeric comparison
    tol: float = 1e-6


# class name -> provider returning scenarios
_PROVIDERS: Dict[str, Callable[[], List[TestObject]]] = {}

#: stage class names exempt from fuzzing (abstract shims, external-IO stages
#: that cannot run hermetically).  Every exemption must carry a reason.
EXEMPT: Dict[str, str] = {}


def fuzzing_objects(cls_name: str):
    """Decorator registering a test-object provider for a stage class."""
    def deco(fn: Callable[[], List[TestObject]]):
        _PROVIDERS[cls_name] = fn
        return fn
    return deco


def exempt(cls_name: str, reason: str) -> None:
    EXEMPT[cls_name] = reason


def get_provider(cls_name: str) -> Optional[Callable[[], List[TestObject]]]:
    return _PROVIDERS.get(cls_name)


def all_providers() -> Dict[str, Callable[[], List[TestObject]]]:
    return dict(_PROVIDERS)
