"""Shared runtime utilities.

Analogs of the reference's ``core/utils`` (ClusterUtil, FaultToleranceUtils,
StreamUtilities — expected paths, UNVERIFIED; SURVEY.md §2.1 "Core").
``ClusterUtil`` counted Spark executors/cores to plan LightGBM's one-task-per-
executor coalescing; here the unit of parallelism is a mesh axis, so the
cluster-topology helpers report JAX device/process topology instead.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional, TypeVar

import jax

log = logging.getLogger("mmlspark_tpu")

T = TypeVar("T")


class ClusterUtil:
    """Device/process topology helpers (executor counting analog)."""

    @staticmethod
    def get_num_devices() -> int:
        return jax.device_count()

    @staticmethod
    def get_num_local_devices() -> int:
        return jax.local_device_count()

    @staticmethod
    def get_num_processes() -> int:
        return jax.process_count()

    @staticmethod
    def get_process_index() -> int:
        return jax.process_index()

    @staticmethod
    def get_default_platform() -> str:
        return jax.default_backend()


class FaultToleranceUtils:
    """Retry helper for flaky IO (model download, HTTP) — reference analog."""

    @staticmethod
    def retry_with_timeout(fn: Callable[[], T], retries: int = 3,
                           backoff_s: float = 0.5,
                           exceptions=(Exception,)) -> T:
        last: Optional[BaseException] = None
        for attempt in range(retries):
            try:
                return fn()
            except exceptions as e:  # noqa: PERF203 - retry loop
                last = e
                if attempt < retries - 1:
                    sleep = backoff_s * (2 ** attempt)
                    log.warning("Attempt %d/%d failed (%s); retrying in %.1fs",
                                attempt + 1, retries, e, sleep)
                    time.sleep(sleep)
        assert last is not None
        raise last


class StopWatch:
    """Minimal wall-clock timer used by the Timer stage and benchmarks."""

    def __init__(self):
        self.start = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self.start

    def restart(self) -> float:
        now = time.perf_counter()
        dt = now - self.start
        self.start = now
        return dt


def block_until_ready(tree: Any) -> Any:
    """jax.block_until_ready that tolerates non-array leaves."""
    return jax.block_until_ready(tree)
