"""Device-mesh bootstrap — the framework's distributed runtime.

This replaces the reference's entire control-plane rendezvous for distributed
training (SURVEY.md §3.1/§5.8): where the reference's driver opens a socket,
collects ``ip:port`` from every executor, broadcasts a machine list, and the
native engine builds a raw TCP mesh (``LightGBMUtils.getNetworkInitNodes`` /
``TrainUtils.networkInit`` / ``LGBM_NetworkInit``, expected paths, UNVERIFIED),
a TPU-native framework simply:

* calls ``jax.distributed.initialize`` once per host (DCN coordination
  service — the moral equivalent of the driver-socket handshake), and
* lays devices out in a ``jax.sharding.Mesh`` whose axes XLA maps onto
  ICI; collectives (``psum`` for histogram allreduce) are compiler-scheduled.

Mesh axes used throughout the framework:

* ``"data"``  — row/data parallelism (LightGBM ``tree_learner=data`` analog;
  also batch parallelism for inference transformers).
* ``"feature"`` — feature-axis sharding of histograms/split-finding
  (LightGBM ``tree_learner=feature`` analog; the GBDT counterpart of
  sequence/context parallelism — it shards the wide axis, SURVEY.md §5.7).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
FEATURE_AXIS = "feature"

_active_mesh: Optional[Mesh] = None


_CLUSTER_ENV_HINTS = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID",
)


def distributed_initialize(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (DCN).

    Replaces the reference's driver-socket rendezvous: the JAX coordination
    service plays the driver role, every host plays an executor.  With
    explicit args it forwards them; with no args it defers to JAX's cluster
    auto-detection whenever the environment looks multi-host, and no-ops on a
    plain single-process machine so local runs need no ceremony.
    """
    explicit = any(a is not None
                   for a in (coordinator_address, num_processes, process_id))
    if explicit:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        return
    if any(os.environ.get(k) for k in _CLUSTER_ENV_HINTS):
        jax.distributed.initialize()


def build_mesh(data: Optional[int] = None, feature: int = 1,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(data, feature)`` mesh over the available devices.

    ``data`` defaults to ``n_devices // feature``.  With a single device this
    yields a degenerate 1x1 mesh, so the same code path runs everywhere.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if data is None:
        if n % feature != 0:
            raise ValueError(f"{n} devices not divisible by feature={feature}")
        data = n // feature
    if data * feature != n:
        raise ValueError(
            f"Mesh {data}x{feature} does not cover {n} devices")
    arr = np.asarray(devs).reshape(data, feature)
    return Mesh(arr, (DATA_AXIS, FEATURE_AXIS))


def get_mesh() -> Mesh:
    """The active mesh (set via :func:`use_mesh`), else a fresh default."""
    if _active_mesh is not None:
        return _active_mesh
    return build_mesh()


@contextmanager
def use_mesh(mesh: Mesh):
    global _active_mesh
    prev = _active_mesh
    _active_mesh = mesh
    try:
        yield mesh
    finally:
        _active_mesh = prev


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable ``shard_map``: newer jax exposes
    ``jax.shard_map`` with a ``check_vma`` kwarg; older releases ship it
    as ``jax.experimental.shard_map.shard_map`` with the same check
    under the ``check_rep`` name.  Every mesh path (gbdt scans, the
    Pallas ring-collective probes) routes through this one shim so a jax
    upgrade/downgrade is a one-line event, not a broken distributed
    subsystem."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded along the data axis, everything else replicated."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def num_workers(mesh: Optional[Mesh] = None) -> int:
    m = mesh or get_mesh()
    return int(m.shape[DATA_AXIS])


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def shard_rows(x: np.ndarray, mesh: Mesh, pad_value=0) -> Tuple[np.ndarray, int]:
    """Pad the leading axis to a multiple of the data-axis size.

    Returns (padded array, original length).  The pad rows carry zero weight
    downstream, mirroring how the reference's ``ClusterUtil`` repartitioning
    gives each executor a (ragged) slice — TPU meshes need equal slices.
    """
    k = num_workers(mesh)
    n = x.shape[0]
    m = pad_to_multiple(max(n, k), k)
    if m == n:
        return x, n
    pad_shape = (m - n,) + x.shape[1:]
    pad = np.full(pad_shape, pad_value, dtype=x.dtype)
    return np.concatenate([x, pad], axis=0), n
