"""Streaming data sketches — the data-quality half of observability
(ISSUE 15).

The systems half of the observability stack (metrics, traces, SLOs,
profiler) watches *how* the served model runs; nothing watched *what*
flows through it.  This module is the measurement substrate for the
drift subsystem (:mod:`mmlspark_tpu.core.drift`): per-feature mergeable
streaming sketches cheap enough for the scoring hot path, plus the
fit-time **reference profile** they are compared against.

Design points:

* **Fixed, fit-time bucket edges.**  A :class:`StreamSketch` counts
  occupancy over a FIXED ascending edge array decided when the profile
  is built — per-feature edges come straight from the
  :class:`~mmlspark_tpu.gbdt.binning.BinMapper`'s quantile bounds
  (downsampled to at most :data:`MAX_PROFILE_EDGES`), the
  prediction-margin edges from training-margin quantiles.  Fixed edges
  are what make sketches MERGEABLE with the same discipline the
  log-bucket latency histograms established (ISSUE 8): bucket counts
  are keyed by stable string indices, key-wise summing K workers'
  snapshots yields exactly the sketch of the concatenated rows, and
  PSI/JS recompute from the summed counts — never an average of
  per-worker divergences.
* **Welford moments + quality counters.**  Next to the bucket counts a
  sketch keeps exact ``count``/``nan``/``posinf``/``neginf`` tallies,
  out-of-training-range counters (``below``/``above`` relative to the
  binning-edge span) and mean/variance via a vectorized Welford/Chan
  update — integer counters merge bit-exactly; moments merge by the
  pairwise (Chan) formula.
* **Vectorized batch updates.**  :meth:`MatrixSketch.update` consumes
  the already-decoded float32 ``(n, f)`` scoring batch: one NaN/Inf
  mask pass plus one ``searchsorted``+``bincount`` per feature — no
  per-row Python.  The duty-cycle gate that keeps this off the latency
  budget lives in the monitor (:mod:`~mmlspark_tpu.core.drift`), not
  here.
* **PSI / Jensen–Shannon.**  :func:`psi` and :func:`js_divergence`
  compare two count vectors (reference vs live) with epsilon
  smoothing; the NaN tally rides as a dedicated trailing slot of the
  distribution vector, so an all-NaN feature is a *distribution* shift
  (huge PSI), not just a null-rate delta.

Everything is numpy + stdlib; importable from the serving hot path and
the training engine alike.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "MAX_PROFILE_EDGES", "MatrixSketch", "ReferenceProfile",
    "StreamSketch", "build_reference_profile", "js_divergence",
    "merge_sketch_snapshots", "psi",
]

#: cap on per-feature bucket-edge count in a reference profile: PSI over
#: a few dozen buckets is the standard discipline (more buckets = more
#: smoothing noise at serving batch sizes, and a fatter profile file)
MAX_PROFILE_EDGES = 31

#: schema stamp for persisted profiles
PROFILE_FORMAT = 1

#: smoothing floor for PSI/JS probabilities — a bucket the reference
#: never saw must not blow the divergence to infinity on one live row
EPS = 1e-4


def downsample_edges(edges: np.ndarray,
                     max_edges: int = MAX_PROFILE_EDGES) -> np.ndarray:
    """At most ``max_edges`` of ``edges``, evenly spaced by INDEX (i.e.
    by training quantile, since the binning bounds are quantile cuts) —
    always a SUBSET, so fine-bin counts regroup exactly onto the coarse
    buckets."""
    edges = np.asarray(edges, np.float64)
    if len(edges) <= max_edges:
        return edges
    idx = np.unique(np.linspace(0, len(edges) - 1, max_edges)
                    .round().astype(np.int64))
    return edges[idx]


class StreamSketch:
    """Streaming occupancy + moments over a fixed edge ladder.

    ``edges`` (ascending, possibly empty) define ``len(edges) + 1``
    value buckets via ``searchsorted(edges, v, side="left")`` — the
    identical bucketing rule :class:`~mmlspark_tpu.gbdt.binning
    .BinMapper.transform` uses, so a live value lands in the same
    bucket its fine training bin rolls up to.  NaNs are tallied
    separately (never bucketed); ±Inf land in the end buckets AND bump
    their own counters.  ``lo``/``hi`` (optional, the training edge
    span) feed the out-of-training-range counters.
    """

    __slots__ = ("edges", "lo", "hi", "counts", "count", "nan",
                 "posinf", "neginf", "below", "above",
                 "_mean", "_m2")

    def __init__(self, edges: Sequence[float] = (),
                 lo: Optional[float] = None,
                 hi: Optional[float] = None):
        self.edges = np.asarray(edges, np.float64)
        self.lo = None if lo is None else float(lo)
        self.hi = None if hi is None else float(hi)
        self.counts = np.zeros(len(self.edges) + 1, np.int64)
        self.count = 0          # finite observations
        self.nan = 0
        self.posinf = 0
        self.neginf = 0
        self.below = 0
        self.above = 0
        self._mean = 0.0
        self._m2 = 0.0

    # -- updates -------------------------------------------------------------

    def update(self, values: np.ndarray) -> None:
        """Vectorized batch update (one pass, no per-row Python)."""
        v = np.asarray(values).ravel()
        if v.size == 0:
            return
        nan_mask = np.isnan(v)
        n_nan = int(nan_mask.sum())
        if n_nan:
            self.nan += n_nan
            v = v[~nan_mask]
            if v.size == 0:
                return
        self.posinf += int(np.count_nonzero(v == np.inf))
        self.neginf += int(np.count_nonzero(v == -np.inf))
        if self.lo is not None:
            self.below += int(np.count_nonzero(v < self.lo))
        if self.hi is not None:
            self.above += int(np.count_nonzero(v > self.hi))
        if len(self.edges):
            idx = np.searchsorted(self.edges, v, side="left")
            self.counts += np.bincount(idx, minlength=len(self.counts)
                                       ).astype(np.int64)
        else:
            self.counts[0] += v.size
        # Chan's batched Welford: merge the batch's exact moments into
        # the running ones (finite values only; an Inf would poison the
        # mean forever)
        fin = v[np.isfinite(v)]
        if fin.size:
            bm = float(fin.mean())
            bm2 = float(((fin - bm) ** 2).sum())
            n0, n1 = self.count, int(fin.size)
            delta = bm - self._mean
            tot = n0 + n1
            self._mean += delta * n1 / tot
            self._m2 += bm2 + delta * delta * n0 * n1 / tot
        self.count += int(v.size)

    def merge(self, other: "StreamSketch") -> "StreamSketch":
        if len(other.counts) != len(self.counts):
            raise ValueError("cannot merge sketches over different "
                             "edge ladders")
        self.counts += other.counts
        self.nan += other.nan
        self.posinf += other.posinf
        self.neginf += other.neginf
        self.below += other.below
        self.above += other.above
        n0, n1 = self.count, other.count
        if n1:
            delta = other._mean - self._mean
            tot = n0 + n1
            self._mean += delta * n1 / tot
            self._m2 += other._m2 + delta * delta * n0 * n1 / tot
        self.count += other.count
        return self

    # -- readings ------------------------------------------------------------

    @property
    def total(self) -> int:
        """All observations, NaNs included — the null-rate denominator."""
        return self.count + self.nan

    @property
    def mean(self) -> float:
        return self._mean if self.count else 0.0

    @property
    def var(self) -> float:
        return self._m2 / self.count if self.count else 0.0

    def null_rate(self) -> float:
        t = self.total
        return self.nan / t if t else 0.0

    def oor_rate(self) -> float:
        """Fraction of finite observations outside the training edge
        span (``None`` bounds contribute nothing)."""
        return (self.below + self.above) / self.count if self.count \
            else 0.0

    def dist_counts(self) -> np.ndarray:
        """The divergence vector: value-bucket counts plus one trailing
        missing slot — a NaN storm shifts the DISTRIBUTION, not just a
        side counter."""
        return np.concatenate([self.counts, [self.nan]])

    def quantile(self, q: float) -> float:
        """q in [0, 1]; piecewise-uniform estimate from the bucket
        counts (end buckets are clamped to their single known edge)."""
        total = int(self.counts.sum())
        if total <= 0 or len(self.edges) == 0:
            return self.mean
        rank = q * total
        cum = 0
        for i, c in enumerate(self.counts):
            nxt = cum + int(c)
            if nxt >= rank and c > 0:
                lo = self.edges[i - 1] if i > 0 else self.edges[0]
                hi = self.edges[i] if i < len(self.edges) \
                    else self.edges[-1]
                frac = (rank - cum) / c
                return float(lo + (hi - lo) * min(max(frac, 0.0), 1.0))
            cum = nxt
        return float(self.edges[-1])

    # -- snapshot / restore --------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able, MERGEABLE state: integer tallies plus a sparse
        ``{bucket-index: count}`` dict whose keys are the bit-stable
        ``str(i)`` indices (the ladder is fixed at profile-build time,
        so the keys mean the same thing in every process — the same
        guarantee ``LE_STRS`` gives the latency histograms)."""
        return {
            "n": self.count,
            "nan": self.nan,
            "posinf": self.posinf,
            "neginf": self.neginf,
            "below": self.below,
            "above": self.above,
            "mean": self._mean,
            "m2": self._m2,
            "buckets": {str(i): int(c)
                        for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any],
                      edges: Sequence[float] = (),
                      lo: Optional[float] = None,
                      hi: Optional[float] = None) -> "StreamSketch":
        sk = cls(edges, lo, hi)
        sk.count = int(snap.get("n", 0) or 0)
        sk.nan = int(snap.get("nan", 0) or 0)
        sk.posinf = int(snap.get("posinf", 0) or 0)
        sk.neginf = int(snap.get("neginf", 0) or 0)
        sk.below = int(snap.get("below", 0) or 0)
        sk.above = int(snap.get("above", 0) or 0)
        sk._mean = float(snap.get("mean", 0.0) or 0.0)
        sk._m2 = float(snap.get("m2", 0.0) or 0.0)
        for k, c in (snap.get("buckets") or {}).items():
            i = int(k)
            if 0 <= i < len(sk.counts):
                sk.counts[i] = int(c)
        return sk


def merge_sketch_snapshots(snaps: Sequence[Dict[str, Any]]
                           ) -> Dict[str, Any]:
    """Key-wise sum of sketch snapshots: integer tallies and bucket
    counts sum EXACTLY (the merged buckets equal one sketch over the
    concatenated rows — the satellite guarantee), moments recombine via
    Chan's formula."""
    out: Dict[str, Any] = {"n": 0, "nan": 0, "posinf": 0, "neginf": 0,
                           "below": 0, "above": 0, "mean": 0.0,
                           "m2": 0.0, "buckets": {}}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for k in ("nan", "posinf", "neginf", "below", "above"):
            out[k] += int(snap.get(k, 0) or 0)
        for b, c in (snap.get("buckets") or {}).items():
            out["buckets"][b] = out["buckets"].get(b, 0) + int(c)
        n0, n1 = out["n"], int(snap.get("n", 0) or 0)
        if n1:
            m1 = float(snap.get("mean", 0.0) or 0.0)
            delta = m1 - out["mean"]
            tot = n0 + n1
            out["mean"] += delta * n1 / tot
            out["m2"] += float(snap.get("m2", 0.0) or 0.0) \
                + delta * delta * n0 * n1 / tot
        out["n"] = n0 + n1
    return out


# -- divergences --------------------------------------------------------------


def _smooth_probs(counts: np.ndarray, eps: float = EPS) -> np.ndarray:
    c = np.asarray(counts, np.float64)
    tot = c.sum()
    if tot <= 0:
        return np.full(c.shape, 1.0 / max(1, c.size))
    p = c / tot
    p = np.maximum(p, eps)
    return p / p.sum()


def psi(ref_counts: np.ndarray, live_counts: np.ndarray,
        eps: float = EPS) -> float:
    """Population Stability Index between two count vectors (same
    ladder): ``Σ (q - p) · ln(q / p)`` with epsilon-smoothed
    probabilities.  Conventional reading: <0.1 stable, 0.1–0.25
    moderate, >0.25 a shift worth paging on."""
    p = _smooth_probs(ref_counts, eps)
    q = _smooth_probs(live_counts, eps)
    return float(np.sum((q - p) * np.log(q / p)))


def js_divergence(ref_counts: np.ndarray, live_counts: np.ndarray,
                  eps: float = EPS) -> float:
    """Jensen–Shannon divergence (base 2 — bounded [0, 1]) between two
    count vectors on the same ladder.  Symmetric and bounded where PSI
    is neither; the report carries both."""
    p = _smooth_probs(ref_counts, eps)
    q = _smooth_probs(live_counts, eps)
    m = 0.5 * (p + q)
    kl_pm = np.sum(p * np.log2(p / m))
    kl_qm = np.sum(q * np.log2(q / m))
    return float(0.5 * kl_pm + 0.5 * kl_qm)


# -- matrix sketch ------------------------------------------------------------


class MatrixSketch:
    """One :class:`StreamSketch` per feature column of an ``(n, f)``
    batch.  ``update`` computes the NaN mask once for the whole matrix
    and does one searchsorted+bincount per feature — the vectorized
    form the scoring hot path pays for (behind the monitor's duty-cycle
    gate)."""

    def __init__(self, edges_list: Sequence[Sequence[float]],
                 los: Optional[Sequence[Optional[float]]] = None,
                 his: Optional[Sequence[Optional[float]]] = None):
        f = len(edges_list)
        los = los if los is not None else [None] * f
        his = his if his is not None else [None] * f
        self.features = [StreamSketch(edges_list[j], los[j], his[j])
                         for j in range(f)]

    @property
    def num_features(self) -> int:
        return len(self.features)

    def update(self, X: np.ndarray) -> int:
        X = np.asarray(X)
        if X.ndim != 2 or X.shape[1] != self.num_features:
            raise ValueError(
                f"MatrixSketch.update expects (n, {self.num_features}) "
                f"matrices, got {X.shape}")
        for j, sk in enumerate(self.features):
            sk.update(X[:, j])
        return int(X.shape[0])

    def merge(self, other: "MatrixSketch") -> "MatrixSketch":
        if other.num_features != self.num_features:
            raise ValueError("feature-count mismatch in MatrixSketch "
                             "merge")
        for sk, osk in zip(self.features, other.features):
            sk.merge(osk)
        return self

    def snapshot(self) -> List[Dict[str, Any]]:
        return [sk.snapshot() for sk in self.features]


# -- reference profile --------------------------------------------------------


class ReferenceProfile:
    """The fit-time "what the training data looked like" artifact:
    per-feature edge ladders + sketch snapshots over the training
    matrix, a prediction-margin ladder + sketch, and feature names —
    persisted beside the model (the registry stores it digest-verified
    like the model file) and loaded by every drift monitor as the
    comparison baseline."""

    def __init__(self, feature_edges: Sequence[Sequence[float]],
                 feature_sketches: Sequence[Dict[str, Any]],
                 margin_edges: Sequence[float],
                 margin_sketch: Dict[str, Any],
                 feature_names: Optional[Sequence[str]] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.feature_edges = [np.asarray(e, np.float64)
                              for e in feature_edges]
        self.feature_sketches = [dict(s) for s in feature_sketches]
        self.margin_edges = np.asarray(margin_edges, np.float64)
        self.margin_sketch = dict(margin_sketch)
        f = len(self.feature_edges)
        self.feature_names = list(feature_names) if feature_names \
            else [f"f{j}" for j in range(f)]
        if len(self.feature_names) != f:
            raise ValueError(
                f"{len(self.feature_names)} names for {f} features")
        self.meta = dict(meta or {})

    @property
    def num_features(self) -> int:
        return len(self.feature_edges)

    def feature_span(self, j: int):
        """(lo, hi) of the binned training support — the
        out-of-training-range bounds live sketches count against."""
        e = self.feature_edges[j]
        if len(e) == 0:
            return None, None
        return float(e[0]), float(e[-1])

    def live_matrix_sketch(self) -> MatrixSketch:
        """A fresh, empty live sketch on this profile's ladders."""
        spans = [self.feature_span(j)
                 for j in range(self.num_features)]
        return MatrixSketch(self.feature_edges,
                            [s[0] for s in spans],
                            [s[1] for s in spans])

    def live_margin_sketch(self) -> StreamSketch:
        return StreamSketch(self.margin_edges)

    def ref_feature(self, j: int) -> StreamSketch:
        lo, hi = self.feature_span(j)
        return StreamSketch.from_snapshot(
            self.feature_sketches[j], self.feature_edges[j], lo, hi)

    def ref_margin(self) -> StreamSketch:
        return StreamSketch.from_snapshot(self.margin_sketch,
                                          self.margin_edges)

    # -- persistence ---------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "format": PROFILE_FORMAT,
            "feature_names": self.feature_names,
            "feature_edges": [e.tolist() for e in self.feature_edges],
            "feature_sketches": self.feature_sketches,
            "margin_edges": self.margin_edges.tolist(),
            "margin_sketch": self.margin_sketch,
            "meta": self.meta,
        }, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ReferenceProfile":
        d = json.loads(text)
        if d.get("format") != PROFILE_FORMAT:
            raise ValueError(
                f"reference-profile format {d.get('format')!r} not "
                f"supported (want {PROFILE_FORMAT})")
        return cls(d["feature_edges"], d["feature_sketches"],
                   d["margin_edges"], d["margin_sketch"],
                   feature_names=d.get("feature_names"),
                   meta=d.get("meta"))


def build_reference_profile(bins: np.ndarray, mapper,
                            margins: Optional[np.ndarray] = None,
                            feature_names: Optional[Sequence[str]]
                            = None,
                            max_edges: int = MAX_PROFILE_EDGES,
                            margin_buckets: int = 32,
                            meta: Optional[Dict[str, Any]] = None
                            ) -> ReferenceProfile:
    """Build the fit-time profile from the BINNED training matrix — no
    raw-feature pass needed.

    The bin ladder IS the bucketing rule: ``transform`` assigned fine
    bin ``b`` via ``searchsorted(upper_bounds, v, side="left")``, so
    the count of training values in a coarse bucket (coarse edges a
    SUBSET of the fine bounds) is exactly the sum of its fine-bin
    counts — per-feature ``bincount`` over the uint8 column plus an
    index regroup, and the missing bin maps to the NaN tally.
    Categorical features get an empty ladder (drift for them reads
    through the null-rate/mean channel only).

    ``margins``: the training-set prediction margins (any shape;
    raveled) — the prediction-distribution baseline.  Edges are the
    interior ``margin_buckets``-quantiles of the margins.
    """
    bins = np.asarray(bins)
    n, f = bins.shape
    edges_list: List[np.ndarray] = []
    sketches: List[Dict[str, Any]] = []
    for j in range(f):
        ub = mapper.upper_bounds[j]
        if mapper.is_categorical(j) or len(ub) == 0:
            edges = np.empty(0, np.float64)
        else:
            edges = downsample_edges(ub, max_edges)
        lo, hi = ((float(edges[0]), float(edges[-1]))
                  if len(edges) else (None, None))
        sk = StreamSketch(edges, lo, hi)
        col = np.ascontiguousarray(bins[:, j])
        fine = np.bincount(col, minlength=mapper.num_total_bins
                           ).astype(np.int64)
        sk.nan = int(fine[mapper.missing_bin])
        if mapper.is_categorical(j):
            # category identity occupies the fine bins; the coarse
            # ladder is empty → everything finite in bucket 0
            finite = int(fine[:mapper.missing_bin].sum())
            sk.counts[0] = finite
            sk.count = finite
        else:
            value_bins = fine[:len(ub) + 1]
            if len(edges):
                # fine bin b (first bound >= v is ub[b]) rolls up to
                # the first coarse edge position >= b
                idx = np.searchsorted(ub, edges, side="left")
                coarse_of_fine = np.searchsorted(
                    idx, np.arange(len(ub) + 1), side="left")
                sk.counts += np.bincount(
                    coarse_of_fine, weights=value_bins,
                    minlength=len(sk.counts)).astype(np.int64)
            else:
                sk.counts[0] = int(value_bins.sum())
            sk.count = int(value_bins.sum())
        edges_list.append(edges)
        sketches.append(sk.snapshot())
    if margins is not None and np.asarray(margins).size:
        mg = np.asarray(margins, np.float64).ravel()
        mg = mg[np.isfinite(mg)]
        qs = np.linspace(0.0, 1.0, margin_buckets + 1)[1:-1]
        medges = np.unique(np.quantile(mg, qs)) if mg.size \
            else np.empty(0, np.float64)
        msk = StreamSketch(medges)
        msk.update(mg)
    else:
        medges = np.empty(0, np.float64)
        msk = StreamSketch(medges)
    return ReferenceProfile(
        edges_list, sketches, medges, msk.snapshot(),
        feature_names=feature_names,
        meta={"n_rows": int(n), "created": round(time.time(), 3),
              **(meta or {})})
