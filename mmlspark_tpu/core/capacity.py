"""Saturation & capacity observability (ISSUE 20).

The stack so far can say *that* the SLO is burning (core/slo.py) and
*where* the time goes (core/profiler.py) but not *how much more load
the fleet can take* or *which resource saturates first*.  This module
is the USE-method layer (utilization / saturation / errors — errors
already live in the resilience counters) plus an online capacity-knee
estimator:

* **Utilization** — :meth:`CapacityMonitor.sample` derives per-stage
  busy fractions (Δ ``total_s`` / Δ wall-clock) from the profiler's
  existing phase timers — the scoring engine, transport and fleet
  already alias their hot-path histograms into the profiler, so
  utilization costs ZERO extra hot-path records.  The instantaneous
  saturation gauges (scoring ``queue_depth`` / ``batch_occupancy`` /
  ``worker_busy``, transport ``credit_occupancy``, fleet
  ``fanout_inflight``) are set by the components themselves on their
  own :class:`~mmlspark_tpu.core.profiling.StageStats`, so the
  existing beacon + :func:`~mmlspark_tpu.core.telemetry.
  merge_snapshots` machinery federates them cross-process with no new
  transport (see the gauge merge policy in core/telemetry.py — depth-
  style gauges SUM to a total backlog, level-style gauges take the
  worst value).

* **Saturation / knee** — per resource (``scoring``, ``transport``),
  the monitor windows the rotating-epoch latency histograms: each tick
  diffs the cumulative log-bucket counts against a reading ~
  ``window_s`` old, so the percentile is of the LAST WINDOW's
  population exactly (the same delta-histogram discipline the SLO
  monitor uses for counters).  The (throughput, latency) pairs feed a
  :class:`KneeEstimator` — a hinge (flat-then-rising) regressor whose
  breakpoint is the load where latency departs its flat baseline, i.e.
  the goodput knee.  The published knee moves only after the raw
  estimate has left a relative dead-band for several consecutive
  ticks (hysteresis), so bursts wiggle the raw fit without flapping
  the headroom surface.

* **Headroom** — ``mmlspark_tpu_capacity_headroom_ratio{resource=}``
  = current load / published knee load.  Two gauge-form SLO
  objectives (``scoring_headroom``, ``transport_headroom``, declared
  in core/slo.py) feed the existing multiwindow burn machinery, so
  "approaching saturation" pages BEFORE "SLO violated" does.
  Saturation onset/clear transitions (with per-verdict hysteresis)
  journal ``saturation_onset`` / ``saturation_cleared`` and dump a
  flight record at onset — the post-mortem for "why did we start
  shedding" is self-contained.

Overhead contract: with capacity observability DISABLED
(``MMLSPARK_TPU_CAPACITY=0`` or :func:`configure`) the component taps
are one cached-bool check and the sampler never runs; ENABLED, the
taps are a few gauge stores per BATCH (not per row) and the sampler is
one registry snapshot per second.  The perf sentinel pins the
enabled-vs-disabled p50 delta of a closed-loop scoring burst under 3%
(tools/perf_sentinel.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .profiling import StageStats, percentile_from_buckets
from .telemetry import (PREFIX, _fmt, _labels, get_journal, get_registry,
                        record_flight)

__all__ = ["CapacityMonitor", "KneeEstimator", "ResourceSpec",
           "default_resources", "capacity_enabled", "configure",
           "get_capacity_monitor", "set_capacity_monitor",
           "peek_capacity_monitor", "ensure_capacity_sampler",
           "render_statusz", "CAPACITY_ENV",
           "SATURATION_ONSET_RATIO", "SATURATION_CLEAR_RATIO"]

#: set to ``"0"`` to disable capacity observability process-wide; the
#: sentinel overhead A/B and tests flip :func:`configure` instead
#: (same switch, no env round-trip)
CAPACITY_ENV = "MMLSPARK_TPU_CAPACITY"

#: headroom (load / knee) at which a resource is "approaching
#: saturation".  The ``*_headroom`` SLO objectives in core/slo.py use
#: the SAME constant as their gauge threshold — the burn gate and the
#: journal verdict must agree on what "saturating" means.
SATURATION_ONSET_RATIO = 0.9

#: headroom below which a saturated resource is considered recovered;
#: the gap to the onset ratio is the anti-flap hysteresis band
SATURATION_CLEAR_RATIO = 0.75

_enabled = {"on": os.environ.get(CAPACITY_ENV, "1") != "0"}


def capacity_enabled() -> bool:
    """Process-wide capacity-observability switch.  Components CACHE
    this at construction time (one attribute check on their hot paths);
    the sampler re-reads it every tick so :func:`configure` pauses a
    running monitor immediately."""
    return _enabled["on"]


def configure(enabled: Optional[bool] = None) -> bool:
    """Flip the process-wide switch (None = leave unchanged); returns
    the resulting state.  Components constructed AFTER the flip pick it
    up — the sentinel A/B constructs a fresh engine per arm."""
    if enabled is not None:
        _enabled["on"] = bool(enabled)
    return _enabled["on"]


# -- knee estimation ---------------------------------------------------------


class KneeEstimator:
    """Online goodput-knee estimator over (load, latency) observations.

    Model: a hinge — latency is FLAT at a baseline ``a`` up to the knee
    load ``k``, then rises linearly with slope ``c``.  :meth:`
    raw_estimate` grid-searches the breakpoint over the observed loads,
    fitting ``a`` as the mean of the left segment and ``c`` by least
    squares on the right, and returns the SSE-minimizing ``k`` — but
    only when the curve actually shows a knee: enough points, enough
    load dynamic range, a positive right-segment slope, and a modeled
    rise of at least ``rise_factor`` over the baseline at the max
    observed load.  An open-loop sweep past saturation (throughput
    plateaus, latency explodes) and a closed-loop concurrency curve
    (latency rises smoothly) both fit this shape.  When overload
    instead REDUCES delivered load (congestion collapse: latency-vs-
    load folds back and no hinge fits), a fallback splits the points
    on latency and estimates the knee as the max load sustained below
    ``rise_factor`` times the low-latency baseline.

    Hysteresis: the PUBLISHED knee (:attr:`knee`) moves only after the
    raw estimate has been outside a ``band`` relative dead-band around
    it for ``confirm`` consecutive :meth:`update` calls — a burst that
    wiggles the raw fit for a tick or two cannot flap the headroom
    surface the autoscaler will act on."""

    def __init__(self, window: int = 240, min_points: int = 10,
                 min_load_span: float = 1.5, rise_factor: float = 1.3,
                 band: float = 0.15, confirm: int = 3,
                 min_left: int = 3, min_right: int = 3):
        self.window = int(window)
        self.min_points = int(min_points)
        self.min_load_span = float(min_load_span)
        self.rise_factor = float(rise_factor)
        self.band = float(band)
        self.confirm = int(confirm)
        self.min_left = int(min_left)
        self.min_right = int(min_right)
        self._pts: "deque[Tuple[float, float]]" = deque(maxlen=self.window)
        self._published: Optional[float] = None
        self._pending: Optional[float] = None
        self._pending_n = 0

    def observe(self, load: float, latency_ms: float) -> None:
        """Add one (throughput, latency) observation; non-positive
        readings carry no information and are dropped."""
        if load > 0 and latency_ms > 0:
            self._pts.append((float(load), float(latency_ms)))

    def raw_estimate(self) -> Optional[float]:
        """The hinge-fit knee of the current window, or ``None`` while
        the curve shows no credible knee (too few points, too little
        load range, or latency still flat)."""
        pts = sorted(self._pts)
        n = len(pts)
        if n < self.min_points:
            return None
        loads = [p[0] for p in pts]
        lats = [p[1] for p in pts]
        if loads[0] <= 0 or loads[-1] / loads[0] < self.min_load_span:
            return None
        mean_all = sum(lats) / n
        sse_flat = sum((y - mean_all) ** 2 for y in lats)
        best: Optional[Tuple[float, float, float, float]] = None
        # candidate breakpoints: every observed load that leaves both
        # segments enough points to fit
        for i in range(self.min_left - 1, n - self.min_right):
            k = loads[i]
            left = lats[: i + 1]
            a = sum(left) / len(left)
            xs = [x - k for x in loads[i + 1:]]
            ys = [y - a for y in lats[i + 1:]]
            sxx = sum(x * x for x in xs)
            if sxx <= 0:
                continue
            c = max(0.0, sum(x * y for x, y in zip(xs, ys)) / sxx)
            sse = sum((y - a) ** 2 for y in left) \
                + sum((y - c * x) ** 2 for x, y in zip(xs, ys))
            if best is None or sse < best[0]:
                best = (sse, k, a, c)
        if best is not None:
            sse, k, a, c = best
            modeled_max = a + c * (loads[-1] - k)
            if c > 0 and sse < sse_flat and (
                    a <= 0 or modeled_max >= self.rise_factor * a):
                return k
        # Fold-back fallback: past saturation an open-loop system can
        # deliver LESS than at the knee (congestion collapse — the
        # sender, shedder, and scorer fight for the same cores), so
        # latency-vs-load is multivalued and no hinge explains it: the
        # highest-load points are the healthy ones.  Split on latency
        # instead — congested points sit >= rise_factor over the
        # low-latency baseline — and take the knee as the best load the
        # system ever sustained while healthy.
        base = sorted(lats)[: max(self.min_left, n // 4)]
        a = sum(base) / len(base)
        if a <= 0:
            return None
        healthy = [x for x, y in pts if y < self.rise_factor * a]
        congested = n - len(healthy)
        if congested >= self.min_right and len(healthy) >= self.min_left:
            return max(healthy)
        return None               # flat explains the data just as well

    def update(self) -> Optional[float]:
        """Re-fit and (maybe) move the published knee; returns it."""
        raw = self.raw_estimate()
        if raw is None:
            return self._published
        if self._published is None:
            self._published = raw
            self._pending, self._pending_n = None, 0
            return self._published
        if abs(raw - self._published) <= self.band * self._published:
            self._pending, self._pending_n = None, 0   # inside dead-band
            return self._published
        if self._pending is not None and \
                abs(raw - self._pending) <= self.band * self._pending:
            self._pending_n += 1
        else:
            self._pending, self._pending_n = raw, 1
        if self._pending_n >= self.confirm:
            self._published = self._pending
            self._pending, self._pending_n = None, 0
        return self._published

    @property
    def knee(self) -> Optional[float]:
        return self._published


# -- resource tracking -------------------------------------------------------


class ResourceSpec:
    """One saturable resource: where its load counter and latency
    histograms live in the metrics registry.

    ``load`` is ``"rows"`` (the StageStats row counter) or a named
    event counter; ``stages`` are the latency stages whose windowed
    p50s SUM into the resource's latency reading (scoring sums queue
    age + e2e, so queueing delay — where saturation actually shows —
    counts even though the engine clocks it separately)."""

    def __init__(self, name: str, ns: str, stages: Sequence[str],
                 load: str = "rows"):
        self.name = str(name)
        self.ns = str(ns)
        self.stages = tuple(stages)
        self.load = str(load)


def default_resources() -> Tuple[ResourceSpec, ...]:
    """The resources the serving substrate saturates first."""
    return (
        ResourceSpec("scoring", "scoring", ("queue_age", "e2e"),
                     load="rows"),
        ResourceSpec("transport", "transport", ("wire_write",),
                     load="frames_sent"),
    )


class _ResourceTracker:
    """Windowed (throughput, latency) reader for one resource: keeps a
    short ring of cumulative readings and diffs the newest against one
    ~``window_s`` older, so both the rate and the percentile describe
    the SAME trailing window."""

    def __init__(self, spec: ResourceSpec, window_s: float,
                 estimator: Optional[KneeEstimator] = None,
                 min_dt_s: float = 0.5):
        self.spec = spec
        self.window_s = float(window_s)
        self.min_dt_s = float(min_dt_s)
        self.est = estimator if estimator is not None else KneeEstimator()
        #: ring of (t, cum_load, {stage: cum_buckets})
        self._ring: "deque[Tuple[float, float, Dict[str, Dict[str, int]]]]" \
            = deque(maxlen=4096)

    def tick(self, reg_snap: Dict[str, dict], t: float
             ) -> Tuple[Optional[float], Optional[float]]:
        """Record one reading; returns ``(load_per_s, latency_ms)`` over
        the trailing window (either may be ``None`` when the window is
        still filling or saw no traffic)."""
        src = reg_snap.get(self.spec.ns)
        if not isinstance(src, dict):
            return None, None
        if self.spec.load == "rows":
            cum = float(src.get("rows", 0) or 0)
        else:
            cum = float((src.get("counters") or {})
                        .get(self.spec.load, 0) or 0)
        buckets: Dict[str, Dict[str, int]] = {}
        for st in self.spec.stages:
            s = (src.get("stages") or {}).get(st)
            if isinstance(s, dict) and isinstance(s.get("buckets"), dict):
                buckets[st] = dict(s["buckets"])
        # base = newest reading at least window_s old (else the oldest
        # kept); drop anything older than 2x the window
        while self._ring and t - self._ring[0][0] > 2 * self.window_s \
                and len(self._ring) > 1 \
                and t - self._ring[1][0] >= self.window_s:
            self._ring.popleft()
        base = None
        for rec in reversed(self._ring):
            if t - rec[0] >= self.window_s:
                base = rec
                break
        if base is None and self._ring:
            base = self._ring[0]
        self._ring.append((t, cum, buckets))
        if base is None:
            return None, None
        t0, cum0, buckets0 = base
        dt = t - t0
        if dt < self.min_dt_s:
            return None, None
        d_load = cum - cum0
        load = d_load / dt if d_load > 0 else 0.0
        lat_ms = 0.0
        saw = False
        for st, nb in buckets.items():
            ob = buckets0.get(st, {})
            delta = {le: int(c) - int(ob.get(le, 0))
                     for le, c in nb.items()
                     if int(c) - int(ob.get(le, 0)) > 0}
            if delta:
                lat_ms += percentile_from_buckets(delta, 50) * 1e3
                saw = True
        return load, (lat_ms if saw else None)


# -- the monitor -------------------------------------------------------------


class CapacityMonitor:
    """Per-process saturation/capacity sampler.

    ``sample()`` takes one reading: busy fractions from the profiler's
    phase timers, windowed (load, latency) per declared resource into
    its knee estimator, then the derived headroom / knee / saturation
    gauges — all onto one :class:`StageStats` (``self.stats``), so the
    block is beacon-able and ``merge_snapshots``-able like every other
    telemetry source.  Deterministic given its inputs: tests drive
    ``sample(now=...)`` manually; ``start()`` runs a 1 Hz daemon
    ticker for live serving."""

    def __init__(self, registry=None, *, window_s: float = 30.0,
                 onset_ratio: float = SATURATION_ONSET_RATIO,
                 clear_ratio: float = SATURATION_CLEAR_RATIO,
                 onset_ticks: int = 3, clear_ticks: int = 3,
                 resources: Optional[Sequence[ResourceSpec]] = None,
                 estimators: Optional[Dict[str, KneeEstimator]] = None,
                 min_dt_s: float = 0.5):
        self._registry = registry
        self.window_s = float(window_s)
        self.onset_ratio = float(onset_ratio)
        self.clear_ratio = float(clear_ratio)
        self.onset_ticks = int(onset_ticks)
        self.clear_ticks = int(clear_ticks)
        self.stats = StageStats()
        self.stats.incr("saturation_onsets", 0)
        self.stats.incr("saturation_cleared", 0)
        specs = tuple(resources if resources is not None
                      else default_resources())
        self._trackers: Dict[str, _ResourceTracker] = {
            s.name: _ResourceTracker(
                s, self.window_s,
                (estimators or {}).get(s.name), min_dt_s=min_dt_s)
            for s in specs}
        #: saturation verdict state per resource
        self._sat: Dict[str, Dict[str, Any]] = {
            s.name: {"saturated": False, "onset_n": 0, "clear_n": 0}
            for s in specs}
        self._prev_phases: Dict[str, float] = {}
        self._prev_t: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    def resource_names(self) -> List[str]:
        return sorted(self._trackers)

    def estimator(self, resource: str) -> KneeEstimator:
        return self._trackers[resource].est

    # ---- sampling ----

    def sample(self, now: Optional[float] = None) -> None:
        """One reading of every utilization and saturation surface.
        No-ops while capacity observability is disabled, so
        :func:`configure` pauses a running ticker immediately."""
        if not capacity_enabled():
            return
        t = time.monotonic() if now is None else float(now)
        snap = self._reg().snapshot()
        with self._lock:
            self._sample_busy_locked(t)
            for name, tracker in self._trackers.items():
                load, lat = tracker.tick(snap, t)
                if load is not None:
                    self.stats.set_gauge(f"load_{name}", round(load, 3))
                    if lat is not None:
                        tracker.est.observe(load, lat)
                        self.stats.set_gauge(f"latency_ms_{name}",
                                             round(lat, 3))
                knee = tracker.est.update()
                self.stats.set_gauge(
                    f"knee_{name}",
                    round(knee, 3) if knee else 0.0)
                headroom = (load / knee) if (knee and load) else 0.0
                self.stats.set_gauge(f"headroom_{name}",
                                     round(headroom, 4))
                self._verdict_locked(name, headroom, knee, load)

    def _sample_busy_locked(self, t: float) -> None:
        """Busy fractions from the profiler's phase timers: Δtotal_s
        over Δwall per phase.  The hot paths alias their stage
        histograms into the profiler, so this reads utilization they
        already paid to measure; a fraction can exceed 1.0 when several
        workers run the phase concurrently (it is per-process, not
        per-core)."""
        from .profiler import get_profiler
        try:
            phases = (get_profiler().stats.snapshot().get("stages")
                      or {})
        except Exception:  # noqa: BLE001 - observer must not raise
            phases = {}
        dt = (t - self._prev_t) if self._prev_t is not None else None
        for phase, s in phases.items():
            if not isinstance(s, dict):
                continue
            tot = float(s.get("total_s", 0.0) or 0.0)
            prev = self._prev_phases.get(phase)
            if dt is not None and dt > 0 and prev is not None:
                busy = max(0.0, (tot - prev) / dt)
                self.stats.set_gauge(f"busy_{phase}", round(busy, 4))
            self._prev_phases[phase] = tot
        self._prev_t = t

    def _verdict_locked(self, name: str, headroom: float,
                        knee: Optional[float],
                        load: Optional[float]) -> None:
        """Saturation onset/clear with consecutive-tick hysteresis;
        journals the transitions and flight-records the onset."""
        st = self._sat[name]
        if headroom >= self.onset_ratio:
            st["onset_n"] += 1
            st["clear_n"] = 0
        elif headroom <= self.clear_ratio:
            st["clear_n"] += 1
            st["onset_n"] = 0
        else:
            st["onset_n"] = 0
            st["clear_n"] = 0
        if not st["saturated"] and st["onset_n"] >= self.onset_ticks:
            st["saturated"] = True
            self.stats.incr("saturation_onsets")
            get_journal().emit("saturation_onset", resource=name,
                               headroom=round(headroom, 4),
                               knee=round(knee or 0.0, 3),
                               load=round(load or 0.0, 3))
            record_flight("saturation_onset",
                          {"resource": name,
                           "headroom": round(headroom, 4),
                           "knee": round(knee or 0.0, 3),
                           "load": round(load or 0.0, 3)})
        elif st["saturated"] and st["clear_n"] >= self.clear_ticks:
            st["saturated"] = False
            self.stats.incr("saturation_cleared")
            get_journal().emit("saturation_cleared", resource=name,
                               headroom=round(headroom, 4))
        self.stats.set_gauge(f"saturated_{name}",
                             1.0 if st["saturated"] else 0.0)

    def snapshot(self) -> dict:
        """The StageStats-shaped saturation block (gauges ``headroom_*``
        / ``knee_*`` / ``load_*`` / ``busy_*`` / ``saturated_*``,
        transition counters) — what the worker stats beacon carries and
        the driver merges."""
        return self.stats.snapshot()

    # ---- exposition ----

    def render_prometheus(self, prefix: str = PREFIX) -> str:
        """The ``mmlspark_tpu_capacity_*`` families (joined to every
        scrape through the registry's exposition-provider hook)."""
        snap = self.stats.snapshot()
        gauges: Dict[str, float] = snap.get("gauges") or {}
        lines: List[str] = []

        def fam(suffix: str, help_: str) -> str:
            name = f"{prefix}_capacity_{suffix}"
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            return name

        n = fam("enabled",
                "1 while capacity observability is sampling.")
        lines.append(f"{n} {1 if capacity_enabled() else 0}")

        def by_prefix(p: str) -> List[Tuple[str, float]]:
            return sorted((k[len(p):], v) for k, v in gauges.items()
                          if k.startswith(p))

        fams = (
            ("headroom_ratio", "headroom_", "resource",
             "Current load / estimated knee load (0 while the knee is "
             "unknown; >= ~0.9 is approaching saturation)."),
            ("knee_load", "knee_", "resource",
             "Estimated goodput-knee load (rows/s or frames/s; 0 = "
             "not yet estimable)."),
            ("load", "load_", "resource",
             "Current windowed load (rows/s or frames/s)."),
            ("saturated", "saturated_", "resource",
             "1 while the resource is past saturation onset "
             "(hysteresis-debounced)."),
            ("busy_fraction", "busy_", "phase",
             "Fraction of wall-clock the phase was executing over the "
             "last sampling interval (per-process; can exceed 1 with "
             "concurrent workers)."),
        )
        for suffix, gpfx, label, help_ in fams:
            vals = by_prefix(gpfx)
            if not vals:
                continue
            n = fam(suffix, help_)
            for key, v in vals:
                lines.append(f"{n}{_labels({label: key})} {_fmt(v)}")
        return "\n".join(lines) + "\n"

    # ---- background ticker ----

    def start(self, interval_s: float = 1.0) -> "CapacityMonitor":
        """Start the 1 Hz (default) sampling ticker; idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.sample()
                except Exception:  # noqa: BLE001 - the observer must
                    pass           # outlive a transient registry error

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="capacity-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- process-global install --------------------------------------------------


_cap_lock = threading.Lock()
_cap_monitor: Optional[CapacityMonitor] = None


def peek_capacity_monitor() -> Optional[CapacityMonitor]:
    """The installed monitor, or ``None`` — never creates one (the
    stats beacon peeks so a worker without a monitor sends no block)."""
    return _cap_monitor


def get_capacity_monitor() -> CapacityMonitor:
    """The process-global monitor (created and registered on first
    use; replace with :func:`set_capacity_monitor`)."""
    global _cap_monitor
    with _cap_lock:
        if _cap_monitor is None:
            _set_locked(CapacityMonitor())
        return _cap_monitor


def set_capacity_monitor(monitor: CapacityMonitor) -> CapacityMonitor:
    """Install ``monitor`` as the process-global one, registering its
    stats under ns ``capacity`` (that is where the ``*_headroom`` SLO
    objectives read the headroom gauges) and its ``capacity_*``
    exposition into the global registry."""
    with _cap_lock:
        return _set_locked(monitor)


def _set_locked(monitor: CapacityMonitor) -> CapacityMonitor:
    global _cap_monitor
    old, _cap_monitor = _cap_monitor, monitor
    if old is not None:
        old.stop()
    get_registry().register("capacity", monitor.stats)
    get_registry().register_exposition(
        "capacity", lambda: _cap_monitor.render_prometheus()
        if _cap_monitor is not None else "")
    return monitor


def ensure_capacity_sampler(interval_s: float = 1.0
                            ) -> Optional[CapacityMonitor]:
    """Idempotent engine-startup hook: install the global monitor and
    start its ticker — unless capacity observability is disabled, in
    which case nothing is created and ``None`` returns (the sentinel's
    disabled arm must cost zero)."""
    if not capacity_enabled():
        return None
    m = get_capacity_monitor()
    m.start(interval_s)
    return m


# -- /statusz ----------------------------------------------------------------


def render_statusz(model_info: Optional[dict] = None,
                   workers: Optional[Dict[str, dict]] = None) -> str:
    """One human-readable operational summary (the ``/statusz`` route
    body): active model version, SLO burn states, headroom ratios,
    top-3 busiest phases, worker liveness — ALL assembled from the
    registries that already exist; no new state, and any piece that
    fails to render degrades to a line saying so (a status page must
    not 500 because one subsystem is sick)."""
    from .profiler import get_profiler
    from .slo import get_monitor
    lines: List[str] = [f"{PREFIX} statusz",
                        time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime()), ""]
    # model
    lines.append("== model ==")
    if model_info:
        for k in sorted(model_info):
            lines.append(f"  {k}: {model_info[k]}")
    else:
        lines.append("  (no model info provider)")
    # slo
    lines.append("")
    lines.append("== slo burn ==")
    try:
        rep = get_monitor().report()
        breaching = rep.get("breaching") or []
        lines.append(f"  healthy: {rep.get('healthy')}"
                     f"  breaching: {breaching or 'none'}")
        for name in sorted(rep.get("objectives") or {}):
            v = rep["objectives"][name]
            lines.append(
                f"  {name}: burn_fast={v.get('burn_rate_fast')} "
                f"burn_slow={v.get('burn_rate_slow')} "
                f"{'BREACH' if v.get('breach') else 'ok'}")
    except Exception as e:  # noqa: BLE001 - status must render anyway
        lines.append(f"  (slo monitor unavailable: {e!r})")
    # capacity / headroom
    lines.append("")
    lines.append("== capacity headroom ==")
    cm = peek_capacity_monitor()
    if cm is None:
        lines.append("  (no capacity monitor installed)")
    else:
        try:
            gauges = cm.snapshot().get("gauges") or {}
            names = cm.resource_names()
            for r in names:
                lines.append(
                    f"  {r}: headroom={gauges.get(f'headroom_{r}', 0)} "
                    f"knee={gauges.get(f'knee_{r}', 0)} "
                    f"load={gauges.get(f'load_{r}', 0)} "
                    f"saturated="
                    f"{int(gauges.get(f'saturated_{r}', 0) or 0)}")
            if not names:
                lines.append("  (no resources tracked)")
        except Exception as e:  # noqa: BLE001
            lines.append(f"  (capacity monitor unavailable: {e!r})")
    # top phases
    lines.append("")
    lines.append("== top phases (by total_s) ==")
    try:
        stages = (get_profiler().stats.snapshot().get("stages") or {})
        top = sorted(stages.items(),
                     key=lambda kv: -float(
                         kv[1].get("total_s", 0.0) or 0.0))[:3]
        for phase, s in top:
            lines.append(
                f"  {phase}: total_s={s.get('total_s')} "
                f"count={s.get('count')} p50_ms={s.get('p50_ms')}")
        if not top:
            lines.append("  (no phases recorded)")
    except Exception as e:  # noqa: BLE001
        lines.append(f"  (profiler unavailable: {e!r})")
    # workers
    lines.append("")
    lines.append("== workers ==")
    if workers:
        for w in sorted(workers):
            info = workers[w] or {}
            up = info.get("up")
            age = info.get("beacon_age_s")
            lines.append(
                f"  {w}: {'up' if up else 'DOWN'}"
                + (f" beacon_age_s={round(age, 2)}"
                   if age is not None else ""))
    else:
        lines.append("  (single-process: no worker fleet)")
    return "\n".join(lines) + "\n"
