"""Debug / sanitizer mode — the SURVEY §5.2 subsystem.

The reference has no sanitizers (JVM memory safety plus prebuilt native
libs; SWIG handle misuse surfaces as CI segfaults).  The TPU-native
equivalent is ``jax.experimental.checkify`` compiled INTO the training
program:

* ``user_checks`` — ``checkify.debug_check`` invariants placed in the
  engine: finite gradients/hessians after the objective, and bin indices
  inside the histogram range (XLA clamps/drops OOB indices *silently* —
  the memory-corruption analog a sanitizer exists to make loud).
  ``debug_check`` is a no-op unless the program is checkified, so the
  hot path pays nothing when debug mode is off.

Blanket ``nan_checks`` is deliberately NOT enabled: split finding masks
empty-bin gain arithmetic with ``-inf``/``where``, so transient NaNs
before the mask are expected and would false-positive.  Automatic
``index_checks`` is also off: checkify's scatter rewrite crashes on the
vmapped ``segment_sum`` histogram (jax bug — "tuple index out of range"
inside the scatter error rule), so the OOB class is covered by the
explicit bins-range invariant instead.

Enable with ``MMLSPARK_TPU_DEBUG=1`` or :func:`debug_mode`.  Serial
training paths only (checkify does not discharge through ``shard_map``);
distributed fits ignore the flag.
"""

from __future__ import annotations

import functools
import os
from typing import Callable

_STATE = {"enabled": None}


def debug_enabled() -> bool:
    if _STATE["enabled"] is None:
        _STATE["enabled"] = os.environ.get(
            "MMLSPARK_TPU_DEBUG", "") not in ("", "0")
    return bool(_STATE["enabled"])


def debug_mode(on: bool) -> None:
    """Programmatic override of the MMLSPARK_TPU_DEBUG env switch."""
    _STATE["enabled"] = bool(on)


def checked(fn: Callable) -> Callable:
    """Wrap a jitted callable with checkify when debug mode is on.

    Raises ``jax.experimental.checkify.JaxRuntimeError`` (via
    ``err.throw()``) on the first failed check; returns ``fn`` untouched
    when debug mode is off, so call sites can wrap unconditionally.
    """
    if not debug_enabled():
        return fn
    from jax.experimental import checkify

    checked_fn = checkify.checkify(fn, errors=checkify.user_checks)

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        err, out = checked_fn(*args, **kwargs)
        err.throw()
        return out

    return wrapped


def check_finite(name: str, *arrays) -> None:
    """``debug_check`` that every array is finite (no-op outside
    checkify)."""
    import jax.numpy as jnp
    from jax.experimental import checkify
    for a in arrays:
        checkify.debug_check(
            jnp.all(jnp.isfinite(a)), "non-finite values in " + name)


def check_bins_in_range(bins, num_bins: int) -> None:
    """``debug_check`` that bin indices fit the histogram range — XLA
    would silently clamp/drop OOB indices and train on garbage.  Both
    ends: the int32 bin dtype (>256 total bins) can hold negative
    indices, which scatter ops drop just as silently."""
    import jax.numpy as jnp
    from jax.experimental import checkify
    b = bins.astype(jnp.int32)
    checkify.debug_check(
        (jnp.max(b) < num_bins) & (jnp.min(b) >= 0),
        "bin index out of range (negative or >= num_bins): corrupt "
        "binned matrix")
