"""Parameter system for pipeline stages.

TPU-native re-design of the reference's Spark ML ``Params`` layer
(reference: src/main/scala/com/microsoft/ml/spark/core/contracts/Params.scala,
expected path, UNVERIFIED — see SURVEY.md provenance warning).  The reference
attaches typed ``Param`` objects to every Estimator/Transformer so that every
knob has a name, a doc string, a default, validation, and automatic surfacing
into the Python/R APIs via codegen.  Here there is no JVM to bridge, so the
same contract is met with plain Python descriptors: declaring a ``Param`` on a
class body auto-generates ``getX``/``setX`` methods (mirroring the mmlspark
public API so existing notebooks port over), participates in persistence, and
is introspectable for the fuzzing test harness (SURVEY.md §4).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Iterator, Optional, Tuple


class _NoDefault:
    """Sentinel: param has no default; getting it while unset raises."""
    def __repr__(self):
        return "<undefined>"


NO_DEFAULT = _NoDefault()


class Param:
    """A typed, documented parameter attached to a :class:`Params` subclass.

    Unlike the JVM original there is no separate ``ParamMap``; values live in
    ``instance._paramMap`` and defaults in the class-level descriptor.
    A param declared without a default is *required*: reading it while unset
    raises (mirroring Spark ML's ``NoSuchElementException``).  Optional params
    declare ``default=None`` explicitly.
    """

    __slots__ = ("name", "doc", "default", "typeConverter", "validator")

    def __init__(
        self,
        name: str,
        doc: str = "",
        default: Any = NO_DEFAULT,
        typeConverter: Optional[Callable[[Any], Any]] = None,
        validator: Optional[Callable[[Any], bool]] = None,
    ):
        self.name = name
        self.doc = doc
        self.default = default
        self.typeConverter = typeConverter
        self.validator = validator

    @property
    def hasDefault(self) -> bool:
        return not isinstance(self.default, _NoDefault)

    def convert(self, value: Any) -> Any:
        if self.typeConverter is not None and value is not None:
            value = self.typeConverter(value)
        if self.validator is not None and value is not None:
            if not self.validator(value):
                raise ValueError(
                    f"Invalid value {value!r} for param {self.name!r}"
                )
        return value

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Param({self.name!r}, default={self.default!r})"


# -- common type converters (analog of Spark's TypeConverters) ---------------

class TypeConverters:
    @staticmethod
    def toInt(v: Any) -> int:
        if isinstance(v, bool):
            raise TypeError(f"Expected int, got bool {v!r}")
        return int(v)

    @staticmethod
    def toFloat(v: Any) -> float:
        return float(v)

    @staticmethod
    def toBool(v: Any) -> bool:
        if isinstance(v, bool):
            return v
        raise TypeError(f"Expected bool, got {v!r}")

    @staticmethod
    def toString(v: Any) -> str:
        return str(v)

    @staticmethod
    def toList(v: Any) -> list:
        return list(v)

    @staticmethod
    def toListString(v: Any) -> list:
        return [str(x) for x in v]

    @staticmethod
    def toListInt(v: Any) -> list:
        return [int(x) for x in v]

    @staticmethod
    def toListFloat(v: Any) -> list:
        return [float(x) for x in v]


def _capitalize(name: str) -> str:
    return name[0].upper() + name[1:] if name else name


class Params:
    """Base class providing param declaration, get/set, copy and explain.

    Subclasses declare params as class attributes::

        class MyStage(Params):
            inputCol = Param("inputCol", "The input column", default="input")

    which auto-generates ``self.getInputCol()`` / ``self.setInputCol(v)``
    (matching the reference's public stage API) and records the param for
    persistence and the structural fuzzing tests.
    """

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        # Merge the param registry once at class-definition time (bases are
        # already built, so their caches are complete).
        merged: Dict[str, Param] = {}
        for base in reversed(cls.__mro__[1:]):
            merged.update(getattr(base, "_params_cache", {}))
        # Collect params declared directly on this class and generate
        # accessor methods once, at class-definition time.
        for attr, p in list(vars(cls).items()):
            if not isinstance(p, Param):
                continue
            if p.name != attr:
                raise ValueError(
                    f"Param attribute {attr!r} must match Param.name {p.name!r}"
                )
            merged[attr] = p
            cap = _capitalize(attr)
            getter_name, setter_name = f"get{cap}", f"set{cap}"
            if getter_name not in vars(cls):
                def getter(self, _name=attr):
                    return self.getOrDefault(_name)
                getter.__name__ = getter_name
                getter.__doc__ = f"Gets the value of {attr}: {p.doc}"
                setattr(cls, getter_name, getter)
            if setter_name not in vars(cls):
                def setter(self, value, _name=attr):
                    return self.set(_name, value)
                setter.__name__ = setter_name
                setter.__doc__ = f"Sets the value of {attr}: {p.doc}"
                setattr(cls, setter_name, setter)
        cls._params_cache = merged

    def __init__(self, **kwargs):
        self._paramMap: Dict[str, Any] = {}
        self.setParams(**kwargs)

    # -- param registry ------------------------------------------------------

    _params_cache: Dict[str, Param] = {}

    @classmethod
    def params(cls) -> Dict[str, Param]:
        """All params declared on this class and its bases."""
        return dict(cls._params_cache)

    def hasParam(self, name: str) -> bool:
        return name in type(self)._params_cache

    def _param(self, name: str) -> Param:
        try:
            return type(self)._params_cache[name]
        except KeyError:
            raise AttributeError(
                f"{type(self).__name__} has no param {name!r}"
            ) from None

    # -- get/set -------------------------------------------------------------

    def set(self, name: str, value: Any) -> "Params":
        p = self._param(name)
        self._paramMap[name] = p.convert(value)
        return self

    def setParams(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def isSet(self, name: str) -> bool:
        self._param(name)
        return name in self._paramMap

    def getOrDefault(self, name: str) -> Any:
        p = self._param(name)
        if name in self._paramMap:
            return self._paramMap[name]
        if not p.hasDefault:
            raise KeyError(
                f"Param {name!r} is not set on {type(self).__name__} and has "
                f"no default; call set{_capitalize(name)}(...) first")
        return p.default

    def _peek(self, name: str, fallback: Any = None) -> Any:
        """Non-raising read: set value, else default, else ``fallback``."""
        p = self._param(name)
        if name in self._paramMap:
            return self._paramMap[name]
        return p.default if p.hasDefault else fallback

    def clear(self, name: str) -> "Params":
        self._paramMap.pop(name, None)
        return self

    def extractParamMap(self) -> Dict[str, Any]:
        """Effective values of every defined param (set values over defaults)."""
        out = {}
        for name, p in type(self)._params_cache.items():
            if name in self._paramMap:
                out[name] = self._paramMap[name]
            elif p.hasDefault:
                out[name] = p.default
        return out

    def explainParams(self) -> str:
        lines = []
        for name, p in sorted(type(self)._params_cache.items()):
            cur = self._peek(name, fallback="<unset>")
            lines.append(f"{name}: {p.doc} (default: {p.default!r}, current: {cur!r})")
        return "\n".join(lines)

    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        new = copy.copy(self)
        new._paramMap = dict(self._paramMap)
        if extra:
            for k, v in extra.items():
                new.set(k, v)
        return new

    def _iterSetParams(self) -> Iterator[Tuple[str, Any]]:
        for k in type(self).params():
            if k in self._paramMap:
                yield k, self._paramMap[k]

    def __repr__(self) -> str:
        set_params = ", ".join(f"{k}={v!r}" for k, v in self._iterSetParams())
        return f"{type(self).__name__}({set_params})"


# -- shared param mix-ins (HasInputCol-style traits of the reference) --------

class HasInputCol(Params):
    inputCol = Param("inputCol", "The name of the input column",
                     typeConverter=TypeConverters.toString)


class HasOutputCol(Params):
    outputCol = Param("outputCol", "The name of the output column",
                      typeConverter=TypeConverters.toString)


class HasInputCols(Params):
    inputCols = Param("inputCols", "The names of the input columns",
                      typeConverter=TypeConverters.toListString)


class HasOutputCols(Params):
    outputCols = Param("outputCols", "The names of the output columns",
                       typeConverter=TypeConverters.toListString)


class HasFeaturesCol(Params):
    featuresCol = Param("featuresCol", "The name of the features column",
                        default="features", typeConverter=TypeConverters.toString)


class HasLabelCol(Params):
    labelCol = Param("labelCol", "The name of the label column",
                     default="label", typeConverter=TypeConverters.toString)


class HasPredictionCol(Params):
    predictionCol = Param("predictionCol", "The name of the prediction column",
                          default="prediction", typeConverter=TypeConverters.toString)


class HasProbabilityCol(Params):
    probabilityCol = Param("probabilityCol",
                           "The name of the predicted probability column",
                           default="probability",
                           typeConverter=TypeConverters.toString)


class HasRawPredictionCol(Params):
    rawPredictionCol = Param("rawPredictionCol",
                             "The name of the raw prediction (margin) column",
                             default="rawPrediction",
                             typeConverter=TypeConverters.toString)


class HasWeightCol(Params):
    weightCol = Param("weightCol",
                      "The name of the sample weight column (optional)",
                      default=None, typeConverter=TypeConverters.toString)


class HasValidationIndicatorCol(Params):
    validationIndicatorCol = Param(
        "validationIndicatorCol",
        "Column with a boolean marking rows used for validation/early stopping "
        "(optional)",
        default=None, typeConverter=TypeConverters.toString)


class HasSeed(Params):
    seed = Param("seed", "Random seed", default=42,
                 typeConverter=TypeConverters.toInt)
