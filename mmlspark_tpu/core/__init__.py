from .params import (Param, Params, TypeConverters, HasInputCol, HasOutputCol,
                     HasInputCols, HasOutputCols, HasFeaturesCol, HasLabelCol,
                     HasPredictionCol, HasProbabilityCol, HasRawPredictionCol,
                     HasWeightCol, HasValidationIndicatorCol, HasSeed)
from .schema import DataTable, to_table, from_table, features_matrix
from .pipeline import (PipelineStage, Transformer, Estimator, Model, Pipeline,
                       PipelineModel, STAGE_REGISTRY)
from .mesh import (build_mesh, get_mesh, use_mesh, distributed_initialize,
                   DATA_AXIS, FEATURE_AXIS)
from .utils import ClusterUtil, FaultToleranceUtils, StopWatch
from .telemetry import (MetricsRegistry, EventJournal, get_registry,
                        get_journal, new_trace_id, render_prometheus,
                        merge_snapshots, read_journal)
from .sketch import (StreamSketch, MatrixSketch, ReferenceProfile,
                     build_reference_profile, merge_sketch_snapshots,
                     psi, js_divergence)
from .drift import (DriftConfig, DriftMonitor, set_drift_monitor,
                    peek_drift_monitor, drift_report_from_counters)

__all__ = [
    "Param", "Params", "TypeConverters", "HasInputCol", "HasOutputCol",
    "HasInputCols", "HasOutputCols", "HasFeaturesCol", "HasLabelCol",
    "HasPredictionCol", "HasProbabilityCol", "HasRawPredictionCol",
    "HasWeightCol", "HasValidationIndicatorCol", "HasSeed",
    "DataTable", "to_table", "from_table", "features_matrix",
    "PipelineStage", "Transformer", "Estimator", "Model", "Pipeline",
    "PipelineModel", "STAGE_REGISTRY",
    "build_mesh", "get_mesh", "use_mesh", "distributed_initialize",
    "DATA_AXIS", "FEATURE_AXIS",
    "ClusterUtil", "FaultToleranceUtils", "StopWatch",
    "MetricsRegistry", "EventJournal", "get_registry", "get_journal",
    "new_trace_id", "render_prometheus", "merge_snapshots",
    "read_journal",
    "StreamSketch", "MatrixSketch", "ReferenceProfile",
    "build_reference_profile", "merge_sketch_snapshots",
    "psi", "js_divergence",
    "DriftConfig", "DriftMonitor", "set_drift_monitor",
    "peek_drift_monitor", "drift_report_from_counters",
]
