"""Stage persistence.

TPU-native analog of the reference's ML persistence layer
(core/serialize/ConstructorWritable.scala, expected path, UNVERIFIED).  The
reference serializes stage params as Spark ML metadata plus constructor args
for complex state; here every stage saves to a directory::

    <path>/metadata.json     {"class": ..., "params": {...}, "version": ...}
    <path>/arrays.npz        numpy arrays registered via _save_extra helpers
    <path>/...               arbitrary extra files a stage chooses to write

Stages holding non-Param state override ``_save_extra``/``_load_extra``
(the moral equivalent of ``ConstructorWritable``'s extra constructor args).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, List

import numpy as np

FORMAT_VERSION = 1


def _json_default(obj: Any):
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"Param value {obj!r} is not JSON-serializable")


def save_stage(stage, path: str, overwrite: bool = False) -> None:
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"Path {path!r} exists; pass overwrite=True to replace")
    # Write into a sibling temp dir and swap at the end, so a failed save
    # never destroys an existing good artifact.
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".tmp_save_", dir=parent)
    try:
        meta = {
            "class": type(stage).__name__,
            "module": type(stage).__module__,
            "format_version": FORMAT_VERSION,
            "params": {k: v for k, v in stage._iterSetParams()},
        }
        with open(os.path.join(tmp, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2, default=_json_default)
        stage._save_extra(tmp)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.exists(path):
        shutil.rmtree(path)
    os.rename(tmp, path)


def load_stage(path: str):
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"No stage metadata at {meta_path}")
    with open(meta_path) as f:
        meta = json.load(f)
    cls = _resolve_class(meta["class"], meta.get("module"))
    stage = cls.__new__(cls)
    # Re-run minimal init: Params.__init__ without subclass positional args.
    stage._paramMap = {}
    for k, v in meta.get("params", {}).items():
        stage.set(k, v)
    stage._load_extra(path)
    return stage


def _resolve_class(name: str, module: str):
    from .pipeline import _ALL_STAGES
    # Prefer an exact (module, name) match; bare-name fallback covers classes
    # that moved modules between versions.
    def lookup():
        cls = _ALL_STAGES.get((module, name))
        if cls is None:
            cls = _ALL_STAGES.get(name)
        return cls

    cls = lookup()
    if cls is None and module:
        import importlib
        importlib.import_module(module)  # registers the class on import
        cls = lookup()
    if cls is None:
        raise KeyError(f"Unknown stage class {name!r} (module {module!r})")
    return cls


def save_stage_list(stages: List[Any], path: str) -> None:
    os.makedirs(path, exist_ok=True)
    order = []
    for i, stage in enumerate(stages):
        name = f"{i}_{type(stage).__name__}"
        order.append(name)
        save_stage(stage, os.path.join(path, name), overwrite=True)
    with open(os.path.join(path, "order.json"), "w") as f:
        json.dump(order, f)


def load_stage_list(path: str) -> List[Any]:
    with open(os.path.join(path, "order.json")) as f:
        order = json.load(f)
    return [load_stage(os.path.join(path, name)) for name in order]


def save_arrays(path: str, name: str = "arrays", **arrays: np.ndarray) -> None:
    np.savez_compressed(os.path.join(path, f"{name}.npz"), **arrays)


def load_arrays(path: str, name: str = "arrays") -> Dict[str, np.ndarray]:
    with np.load(os.path.join(path, f"{name}.npz"), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def save_optional_stage(path: str, name: str, stage: Any) -> None:
    """Persist a possibly-None nested stage under ``path/name``."""
    if stage is not None:
        save_stage(stage, os.path.join(path, name), overwrite=True)


def load_optional_stage(path: str, name: str) -> Any:
    p = os.path.join(path, name)
    return load_stage(p) if os.path.exists(p) else None


def save_callable(path: str, name: str, fn: Any) -> None:
    """Persist a python callable with cloudpickle.

    Same contract as Spark's pickled Python UDFs: the load environment must
    provide the same modules the function closes over.
    """
    import cloudpickle
    with open(os.path.join(path, f"{name}.pkl"), "wb") as f:
        cloudpickle.dump(fn, f)


def load_callable(path: str, name: str) -> Any:
    import cloudpickle
    p = os.path.join(path, f"{name}.pkl")
    if not os.path.exists(p):
        return None
    with open(p, "rb") as f:
        return cloudpickle.load(f)


def save_json(path: str, name: str, obj: Any) -> None:
    with open(os.path.join(path, f"{name}.json"), "w") as f:
        json.dump(obj, f, default=_json_default)


def load_json(path: str, name: str) -> Any:
    with open(os.path.join(path, f"{name}.json")) as f:
        return json.load(f)


class StageWriter:
    """Spark-style ``stage.write().overwrite().save(path)`` shim."""

    def __init__(self, stage):
        self._stage = stage
        self._overwrite = False

    def overwrite(self) -> "StageWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        save_stage(self._stage, path, overwrite=self._overwrite)


class StageReader:
    """Spark-style ``Cls.read().load(path)`` shim."""

    def __init__(self, cls):
        self._cls = cls

    def load(self, path: str):
        stage = load_stage(path)
        if not isinstance(stage, self._cls):
            raise TypeError(
                f"Loaded {type(stage).__name__}, expected {self._cls.__name__}")
        return stage
