"""Tabular + image LIME.

Reference: lime/LIME.scala (expected path, UNVERIFIED — SURVEY.md §2.1).
Perturb → predict → weighted local linear fit, per row.  TPU-first shape:
all perturbed samples for a row form one batch through the underlying
model (one jit'd forward), and the local surrogate solve is a batched
weighted least-squares (``vmap`` over rows on device) instead of the
reference's per-row JVM regression.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import (HasInputCol, HasOutputCol, HasPredictionCol,
                           Param, TypeConverters, HasSeed)
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import DataTable, features_matrix
from ..core import serialize
from .superpixel import Superpixel


@jax.jit
def _weighted_lstsq(Xs, ys, ws, reg):
    """Batched ridge-stabilized weighted least squares.

    Xs: (R, S, F) samples per row, ys: (R, S), ws: (R, S) kernel weights,
    reg: ridge strength (the stage's ``regularization`` param).
    Returns (R, F) local coefficients (intercept excluded).
    """
    def solve(X, y, w):
        Xa = jnp.concatenate([X, jnp.ones((X.shape[0], 1))], axis=1)
        Xw = Xa * w[:, None]
        A = Xw.T @ Xa + reg * jnp.eye(Xa.shape[1])
        b = Xw.T @ y
        coef = jnp.linalg.solve(A, b)
        return coef[:-1]
    return jax.vmap(solve)(Xs, ys, ws)


class _LIMEParams(HasPredictionCol, HasSeed):
    nSamples = Param("nSamples", "Perturbed samples per row", default=512,
                     typeConverter=TypeConverters.toInt)
    samplingFraction = Param("samplingFraction",
                             "Probability a feature/superpixel stays ON",
                             default=0.7,
                             typeConverter=TypeConverters.toFloat)
    regularization = Param("regularization", "Surrogate ridge term",
                           default=0.001,
                           typeConverter=TypeConverters.toFloat)
    kernelWidth = Param("kernelWidth", "Exponential kernel width",
                        default=0.75, typeConverter=TypeConverters.toFloat)


class TabularLIME(_LIMEParams, HasInputCol, HasOutputCol, Estimator):
    """Fits feature statistics; the model explains rows of a predictor
    (lime/LIME.scala tabular path)."""

    def __init__(self, model: Optional[Transformer] = None, **kwargs):
        super().__init__(**kwargs)
        self._model = model

    def setModel(self, model: Transformer) -> "TabularLIME":
        self._model = model
        return self

    def _save_extra(self, path: str) -> None:
        serialize.save_optional_stage(path, "model", self._model)

    def _load_extra(self, path: str) -> None:
        self._model = serialize.load_optional_stage(path, "model")

    def _fit(self, table: DataTable) -> "TabularLIMEModel":
        X = features_matrix(table, self.getInputCol())
        out = TabularLIMEModel(
            model=self._model,
            means=X.mean(axis=0), stds=X.std(axis=0) + 1e-12)
        out.setParams(**{k: v for k, v in self._iterSetParams()
                         if out.hasParam(k)})
        return out


class TabularLIMEModel(_LIMEParams, HasInputCol, HasOutputCol, Model):
    def __init__(self, model: Optional[Transformer] = None,
                 means: Optional[np.ndarray] = None,
                 stds: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._model = model
        self._means = means
        self._stds = stds

    def _predict_fn(self) -> Callable[[np.ndarray], np.ndarray]:
        model = self._model
        in_col = self.getInputCol()
        pred_col = self.getPredictionCol()

        def predict(X: np.ndarray) -> np.ndarray:
            scored = model._transform(DataTable({in_col: X}))
            out = np.asarray(scored[pred_col], dtype=np.float64)
            return out if out.ndim == 1 else out[:, -1]
        return predict

    def _transform(self, table: DataTable) -> DataTable:
        X = features_matrix(table, self.getInputCol())
        R, F = X.shape
        S = self.getNSamples()
        rng = np.random.default_rng(self.getSeed())
        predict = self._predict_fn()

        # perturb in standardized space around each row
        noise = rng.normal(size=(R, S, F))
        Xs = X[:, None, :] + noise * self._stds[None, None, :]
        flat = Xs.reshape(R * S, F)
        ys = predict(flat).reshape(R, S)
        # exponential kernel over standardized distance
        d2 = ((noise) ** 2).mean(axis=2)
        ws = np.exp(-d2 / (self.getKernelWidth() ** 2))
        coefs = np.asarray(_weighted_lstsq(
            jnp.asarray((Xs - self._means) / self._stds, jnp.float32),
            jnp.asarray(ys, jnp.float32), jnp.asarray(ws, jnp.float32),
            jnp.asarray(self.getRegularization(), jnp.float32)))
        return table.withColumn(self.getOutputCol(),
                                coefs.astype(np.float64))

    def _save_extra(self, path: str) -> None:
        import os
        serialize.save_arrays(path, means=self._means, stds=self._stds)
        if self._model is not None:
            serialize.save_stage(self._model, os.path.join(path, "model"),
                                 overwrite=True)

    def _load_extra(self, path: str) -> None:
        import os
        arrays = serialize.load_arrays(path)
        self._means, self._stds = arrays["means"], arrays["stds"]
        p = os.path.join(path, "model")
        self._model = serialize.load_stage(p) if os.path.exists(p) else None


class ImageLIME(_LIMEParams, HasInputCol, HasOutputCol, Transformer):
    """Superpixel-mask LIME for NHWC image columns (lime/LIME.scala image
    path).  For each image: cluster superpixels, sample binary masks,
    batch-predict masked images, fit the local surrogate over mask bits."""

    cellSize = Param("cellSize", "Superpixel diameter", default=16.0,
                     typeConverter=TypeConverters.toFloat)
    modifier = Param("modifier", "Superpixel compactness", default=130.0,
                     typeConverter=TypeConverters.toFloat)
    superpixelCol = Param("superpixelCol", "Output superpixel-label column",
                          default="superpixels",
                          typeConverter=TypeConverters.toString)

    def __init__(self, model: Optional[Transformer] = None,
                 predictionFn: Optional[Callable] = None, **kwargs):
        super().__init__(**kwargs)
        self._model = model
        self._predict_fn = predictionFn

    def setModel(self, model: Transformer) -> "ImageLIME":
        self._model = model
        return self

    def _save_extra(self, path: str) -> None:
        serialize.save_optional_stage(path, "model", self._model)
        if self._predict_fn is not None:
            serialize.save_callable(path, "predict_fn", self._predict_fn)

    def _load_extra(self, path: str) -> None:
        self._model = serialize.load_optional_stage(path, "model")
        self._predict_fn = serialize.load_callable(path, "predict_fn")

    def _predict(self, imgs: np.ndarray) -> np.ndarray:
        if self._predict_fn is not None:
            return np.asarray(self._predict_fn(imgs), dtype=np.float64)
        in_col = self.getInputCol()
        scored = self._model._transform(DataTable({in_col: imgs}))
        out = np.asarray(scored[self.getPredictionCol()], dtype=np.float64)
        return out if out.ndim == 1 else out[:, -1]

    def _transform(self, table: DataTable) -> DataTable:
        imgs = np.asarray(table[self.getInputCol()], dtype=np.float32)
        N, H, W, C = imgs.shape
        n_segments = max(4, int((H / self.getCellSize())
                                * (W / self.getCellSize())))
        S = self.getNSamples()
        keep_p = self.getSamplingFraction()
        rng = np.random.default_rng(self.getSeed())

        # SLIC's label space is the static grid², independent of image
        # content — one shape for every image means one XLA compile and one
        # batched surrogate solve for the whole table
        K = int(np.ceil(np.sqrt(n_segments))) ** 2
        all_masks = np.empty((N, S, K), dtype=np.float32)
        all_ys = np.empty((N, S), dtype=np.float32)
        all_ws = np.empty((N, S), dtype=np.float32)
        labels_out = np.empty(N, dtype=object)
        for i in range(N):
            labels = Superpixel.cluster(imgs[i], n_segments=n_segments,
                                        compactness=self.getModifier() / 13.0)
            masks = (rng.random(size=(S, K)) < keep_p)   # (S, K) bool
            masks[0] = True                              # all-on reference
            pixel_masks = masks[:, labels]               # (S, H, W)
            masked = imgs[i][None] * pixel_masks[..., None]
            all_ys[i] = self._predict(masked)            # (S,)
            d = 1.0 - masks.mean(axis=1)                 # fraction off
            all_ws[i] = np.exp(-(d ** 2) / (self.getKernelWidth() ** 2))
            all_masks[i] = masks
            labels_out[i] = labels
        coefs = np.asarray(_weighted_lstsq(
            jnp.asarray(all_masks), jnp.asarray(all_ys),
            jnp.asarray(all_ws),
            jnp.asarray(self.getRegularization(), jnp.float32)))
        weights_out = np.empty(N, dtype=object)
        for i in range(N):
            weights_out[i] = coefs[i].astype(np.float64)
        return table.withColumns({
            self.getOutputCol(): weights_out,
            self.getSuperpixelCol(): labels_out,
        })
