"""SLIC-style superpixel clustering, jit'd.

Reference: lime/Superpixel.scala, lime/SuperpixelTransformer.scala (expected
paths, UNVERIFIED — SURVEY.md §2.1).  The reference clusters pixels on the
JVM per image; here SLIC's k-means-style iteration is a fixed-count
``lax.fori_loop`` over one (H·W, K) distance computation per step —
batched over images with ``vmap``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import HasInputCol, HasOutputCol, Param, TypeConverters
from ..core.pipeline import Transformer
from ..core.schema import DataTable


@partial(jax.jit, static_argnames=("n_segments", "n_iter", "H", "W"))
def _slic(img, n_segments: int, compactness, n_iter: int, H: int, W: int):
    """img: (H, W, C) float. Returns (H, W) int32 superpixel labels."""
    C = img.shape[-1]
    grid = int(np.ceil(np.sqrt(n_segments)))
    step_y, step_x = H / grid, W / grid
    # initial cluster centers on a regular grid: (K, 2 + C)
    cy = (jnp.arange(grid) + 0.5) * step_y
    cx = (jnp.arange(grid) + 0.5) * step_x
    centers_yx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"),
                           axis=-1).reshape(-1, 2)
    K = centers_yx.shape[0]
    yy, xx = jnp.meshgrid(jnp.arange(H, dtype=jnp.float32),
                          jnp.arange(W, dtype=jnp.float32), indexing="ij")
    pix_yx = jnp.stack([yy, xx], axis=-1).reshape(-1, 2)     # (P, 2)
    pix_feat = img.reshape(-1, C)                             # (P, C)
    init_color = pix_feat[
        (centers_yx[:, 0].astype(jnp.int32) * W
         + centers_yx[:, 1].astype(jnp.int32))]

    S = jnp.sqrt((H * W) / K)
    ratio = compactness / S

    def step(_, carry):
        c_yx, c_col = carry
        d_space = jnp.sum((pix_yx[:, None, :] - c_yx[None, :, :]) ** 2, -1)
        d_color = jnp.sum((pix_feat[:, None, :] - c_col[None, :, :]) ** 2, -1)
        dist = d_color + (ratio ** 2) * d_space
        assign = jnp.argmin(dist, axis=1)                     # (P,)
        onehot = jax.nn.one_hot(assign, K, dtype=jnp.float32)  # (P, K)
        counts = onehot.sum(0) + 1e-6
        new_yx = (onehot.T @ pix_yx) / counts[:, None]
        new_col = (onehot.T @ pix_feat) / counts[:, None]
        return (new_yx, new_col)

    c_yx, c_col = jax.lax.fori_loop(0, n_iter, step,
                                    (centers_yx, init_color))
    d_space = jnp.sum((pix_yx[:, None, :] - c_yx[None, :, :]) ** 2, -1)
    d_color = jnp.sum((pix_feat[:, None, :] - c_col[None, :, :]) ** 2, -1)
    assign = jnp.argmin(d_color + (ratio ** 2) * d_space, axis=1)
    return assign.reshape(H, W).astype(jnp.int32)


class Superpixel:
    """Functional interface used by ImageLIME (lime/Superpixel.scala)."""

    @staticmethod
    def cluster(img: np.ndarray, n_segments: int = 40,
                compactness: float = 10.0, n_iter: int = 10) -> np.ndarray:
        img = np.asarray(img, dtype=np.float32)
        H, W = img.shape[:2]
        if img.ndim == 2:
            img = img[:, :, None]
        return np.asarray(_slic(jnp.asarray(img), n_segments,
                                jnp.asarray(compactness, jnp.float32),
                                n_iter, H, W))


class SuperpixelTransformer(HasInputCol, HasOutputCol, Transformer):
    """Adds a superpixel-label column for an NHWC image column
    (lime/SuperpixelTransformer.scala)."""

    cellSize = Param("cellSize", "Approximate superpixel diameter in pixels",
                     default=16.0, typeConverter=TypeConverters.toFloat)
    modifier = Param("modifier", "Compactness modifier", default=130.0,
                     typeConverter=TypeConverters.toFloat)

    def _transform(self, table: DataTable) -> DataTable:
        imgs = np.asarray(table[self.getInputCol()], dtype=np.float32)
        if imgs.ndim != 4:
            raise ValueError(
                f"Expected NHWC image column, got shape {imgs.shape}")
        N, H, W, C = imgs.shape
        n_segments = max(4, int((H / self.getCellSize())
                                * (W / self.getCellSize())))
        batched = jax.vmap(
            lambda im: _slic(im, n_segments,
                             jnp.asarray(self.getModifier() / 13.0,
                                         jnp.float32), 10, H, W))
        labels = np.asarray(batched(jnp.asarray(imgs)))
        return table.withColumn(self.getOutputCol(), labels)
