"""Model explainability — LIME (reference ``lime/`` package).

Reference: src/main/scala/com/microsoft/ml/spark/lime/ (expected paths,
UNVERIFIED — SURVEY.md §2.1): tabular + image LIME, SLIC superpixels.
"""

from .lime import ImageLIME, TabularLIME, TabularLIMEModel
from .superpixel import Superpixel, SuperpixelTransformer

__all__ = ["ImageLIME", "TabularLIME", "TabularLIMEModel",
           "Superpixel", "SuperpixelTransformer"]
