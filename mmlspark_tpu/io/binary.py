"""Binary file datasource — batch and streaming.

Reference: io/binary/BinaryFileFormat.scala, BinaryFileReader.scala
(expected paths, UNVERIFIED — SURVEY.md §2.1): (path, bytes) rows from a
directory tree, with subsampling, usable in batch and streaming queries.
The native engine (``mmlspark_tpu.native``, C++) provides the directory
scan and a thread-pool bulk read with the GIL released; pure-Python
fallbacks keep behavior identical when the extension isn't built.
"""

from __future__ import annotations

import time
from typing import Iterator, List, Optional

import numpy as np

from .. import native
from ..core.schema import DataTable


def _scan(path: str, pattern: Optional[str],
          recursive: bool) -> List[tuple]:
    import os
    if os.path.isfile(path):
        st = os.stat(path)
        return [(path, int(st.st_size), float(st.st_mtime))]
    return native.scan_dir(path, pattern, recursive)


def _subsample(entries: List[tuple], sample_ratio: float,
               seed: int) -> List[tuple]:
    """Per-file Bernoulli subsample (BinaryFileFormat's subsample option).

    The keep/drop decision is a pure function of (path, seed) — NOT a
    positional draw — so a file's sampling fate is stable as new files
    appear in a streaming listing."""
    if sample_ratio >= 1.0:
        return entries
    from ..featurize.hashing import murmur3_32
    thresh = sample_ratio * 2147483648.0
    return [e for e in entries
            if (murmur3_32(e[0].encode("utf-8"), seed) & 0x7FFFFFFF)
            < thresh]


def _table(entries: List[tuple], with_stats: bool = True) -> DataTable:
    paths = [e[0] for e in entries]
    blobs_list = native.read_files(paths)
    blobs = np.empty(len(paths), dtype=object)
    lengths = np.zeros(len(paths), dtype=np.int64)
    for i, b in enumerate(blobs_list):
        blobs[i] = b
        lengths[i] = len(b)
    cols = {
        "path": np.asarray(paths, dtype=object),
        "length": lengths,
        "bytes": blobs,
    }
    if with_stats:
        cols["modificationTime"] = np.asarray(
            [e[2] for e in entries], np.float64)
    return DataTable(cols)


def read_binary_files(path: str, pattern: Optional[str] = None,
                      recursive: bool = True, with_stats: bool = True,
                      *, sample_ratio: float = 1.0,
                      seed: int = 0) -> DataTable:
    """Directory tree → (path, length[, modificationTime], bytes) table.

    New options are keyword-only so pre-existing positional callers of
    ``(path, pattern, recursive, with_stats)`` keep their meaning."""
    entries = _subsample(_scan(path, pattern, recursive), sample_ratio, seed)
    return _table(entries, with_stats)


class BinaryFileReader:
    """Streaming binary datasource: iterate micro-batches of binary rows.

    Batch mode (``follow=False``) yields the directory's current contents
    in ``batch_size`` chunks.  Streaming mode (``follow=True``) keeps
    polling for NEW files (by path + mtime) every ``poll_interval``
    seconds and yields them as they appear — the reference's streaming
    ``readStream.format("binaryFile")`` behavior — until ``stop()`` is
    called or ``max_batches`` is reached.
    """

    def __init__(self, path: str, pattern: Optional[str] = None,
                 recursive: bool = True, batch_size: int = 64,
                 sample_ratio: float = 1.0, seed: int = 0,
                 follow: bool = False, poll_interval: float = 0.25,
                 max_batches: Optional[int] = None):
        self.path = path
        self.pattern = pattern
        self.recursive = recursive
        self.batch_size = batch_size
        self.sample_ratio = sample_ratio
        self.seed = seed
        self.follow = follow
        self.poll_interval = poll_interval
        self.max_batches = max_batches
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def __iter__(self) -> Iterator[DataTable]:
        seen: dict = {}
        emitted = 0
        while not self._stopped:
            entries = _subsample(
                _scan(self.path, self.pattern, self.recursive),
                self.sample_ratio, self.seed)
            fresh = [e for e in entries
                     if seen.get(e[0]) != e[2]]
            for e in fresh:
                seen[e[0]] = e[2]
            for i in range(0, len(fresh), self.batch_size):
                yield _table(fresh[i:i + self.batch_size])
                emitted += 1
                if self.max_batches and emitted >= self.max_batches:
                    return
                if self._stopped:
                    return
            if not self.follow:
                return
            time.sleep(self.poll_interval)
