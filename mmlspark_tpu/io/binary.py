"""Binary file datasource.

Reference: io/binary/BinaryFileFormat.scala, BinaryFileReader.scala
(expected paths, UNVERIFIED — SURVEY.md §2.1): (path, bytes) rows from a
directory tree, streaming-capable.  A C++ fast path
(``mmlspark_tpu.native``) mmaps and bulk-reads when built; the Python
fallback keeps behavior identical.
"""

from __future__ import annotations

import fnmatch
import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..core.schema import DataTable


def _iter_files(path: str, pattern: Optional[str],
                recursive: bool) -> Iterator[str]:
    if os.path.isfile(path):
        yield path
        return
    if recursive:
        for root, _, files in os.walk(path):
            for f in sorted(files):
                if pattern is None or fnmatch.fnmatch(f, pattern):
                    yield os.path.join(root, f)
    else:
        for f in sorted(os.listdir(path)):
            full = os.path.join(path, f)
            if os.path.isfile(full) and (pattern is None
                                         or fnmatch.fnmatch(f, pattern)):
                yield full


def _read_bytes(path: str) -> bytes:
    try:
        from mmlspark_tpu import native
        if native.available():
            return native.read_file(path)
    except ImportError:
        pass
    with open(path, "rb") as f:
        return f.read()


def read_binary_files(path: str, pattern: Optional[str] = None,
                      recursive: bool = True,
                      with_stats: bool = True) -> DataTable:
    """Directory tree → (path, length, modificationTime, bytes) table."""
    paths: List[str] = list(_iter_files(path, pattern, recursive))
    blobs = np.empty(len(paths), dtype=object)
    lengths = np.zeros(len(paths), dtype=np.int64)
    mtimes = np.zeros(len(paths), dtype=np.float64)
    for i, p in enumerate(paths):
        blobs[i] = _read_bytes(p)
        lengths[i] = len(blobs[i])
        if with_stats:
            mtimes[i] = os.path.getmtime(p)
    return DataTable({
        "path": np.asarray(paths, dtype=object),
        "length": lengths,
        "modificationTime": mtimes,
        "bytes": blobs,
    })


class BinaryFileReader:
    """Streaming-capable reader: iterate micro-batches of binary rows
    (analog of the datasource's streaming mode)."""

    def __init__(self, path: str, pattern: Optional[str] = None,
                 recursive: bool = True, batch_size: int = 64):
        self.path = path
        self.pattern = pattern
        self.recursive = recursive
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[DataTable]:
        batch_paths: List[str] = []
        for p in _iter_files(self.path, self.pattern, self.recursive):
            batch_paths.append(p)
            if len(batch_paths) >= self.batch_size:
                yield self._make(batch_paths)
                batch_paths = []
        if batch_paths:
            yield self._make(batch_paths)

    def _make(self, paths: List[str]) -> DataTable:
        blobs = np.empty(len(paths), dtype=object)
        lengths = np.zeros(len(paths), dtype=np.int64)
        for i, p in enumerate(paths):
            blobs[i] = _read_bytes(p)
            lengths[i] = len(blobs[i])
        return DataTable({
            "path": np.asarray(paths, dtype=object),
            "length": lengths,
            "bytes": blobs,
        })
