"""SLO-gated zero-downtime model rollout (ISSUE 14 tentpole).

The repo could only swap a serving model by restarting the server; this
module closes ROADMAP item 2(c): a :class:`RolloutController` that runs
blue/green predictor arms over the versioned
:class:`~mmlspark_tpu.io.registry.ModelRegistry` and lets the SLO
burn-rate machinery (:mod:`mmlspark_tpu.core.slo`, PR 7) make the
promote/rollback decision — a canary that trips a fast-window burn gets
yanked without a human.

How it composes with the serving stack:

* **Arms** — each arm is a ``Booster.predictor()``
  (:class:`~mmlspark_tpu.gbdt.booster.CompiledPredictor`): baseline
  serves, a canary (when a rollout is in flight) takes a configurable
  traffic fraction.  Arms live in an immutable :class:`_Arms` snapshot;
  a batch pins the snapshot for its whole scoring call, so a promote
  or rollback mid-batch NEVER mixes tree versions inside one batch —
  in-flight batches finish on the arms they started with.
* **Routing** — deterministic per-request-id hashing
  (:meth:`RolloutController.arm_for`): sha256 of ``rid`` + the canary
  version as salt, so (a) a retry/salvage of the same rid lands on the
  same arm, and (b) each new canary samples an independent traffic
  slice.  The :class:`~mmlspark_tpu.io.scoring.ScoringEngine` detects
  the controller's ``routes_by_rid`` attribute and hands it the batch's
  rids alongside the feature matrix.
* **The gate** — per-arm counters feed dedicated
  :class:`~mmlspark_tpu.core.slo.SLObjective` s
  (``canary_error_ratio``, ``canary_deadline_miss``, plus an optional
  holdout-margin drift gauge) evaluated by a private
  :class:`~mmlspark_tpu.core.slo.SLOMonitor` on every :meth:`tick`:

  - **breach** (both burn windows over threshold) → immediate
    :meth:`rollback`: the canary slot is cleared atomically, the
    registry entry is marked ``rolled_back``, a ``rollout_rolled_back``
    journal event + crash-flight record capture the scene;
  - **SLO-clean for the soak window** (and at least
    ``min_canary_rows`` scored) → :meth:`promote`: the registry entry
    activates, the canary becomes the baseline in one atomic snapshot
    swap, and the superseded booster's ``invalidate_cache()`` is
    called once the last pinned batch drains — any predictor still
    bound to the old forest raises instead of silently serving it.
* **Zero wrong answers under canary faults** — a canary batch that
  raises is transparently rescored on the baseline (counted as
  ``canary_errors`` + ``canary_fallback_rows``); the client sees a
  correct baseline answer, the gate sees the burn.

``tools/chaos_rollout.py`` drills the whole loop (healthy promote,
faulty canary auto-rollback, driver SIGKILL mid-cutover, corrupted
registry entry) and commits the verdicts as
``artifacts/chaos_rollout_r14.json``.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.profiling import StageStats
from ..core.slo import SLObjective, SLOMonitor
from ..core.telemetry import (PREFIX, get_journal, get_registry,
                              record_flight)
from .registry import ModelCorruption, ModelRegistry, RegistryError
from .scoring import next_pow2

log = logging.getLogger(__name__)

__all__ = ["RolloutConfig", "RolloutController",
           "render_model_info", "rollout_objectives"]


@dataclass
class RolloutConfig:
    """Gate knobs (docs/rollout.md §Knobs)."""
    #: fraction of requests the canary arm takes, by rid hash
    canary_fraction: float = 0.05
    #: SLO-clean seconds before a canary is promoted
    soak_s: float = 60.0
    #: minimum rows the canary must have scored before promotion (a
    #: canary that saw no traffic proved nothing)
    min_canary_rows: int = 200
    #: per-batch canary scoring deadline; batches slower than this
    #: count every row as a deadline miss (None disables the objective)
    canary_deadline_ms: Optional[float] = 250.0
    #: success targets for the canary objectives
    error_target: float = 0.999
    deadline_target: float = 0.99
    #: burn windows/thresholds for the PRIVATE gate monitor (chaos
    #: drills shrink these; production keeps SRE-ish defaults)
    fast_window_s: float = 15.0
    slow_window_s: float = 60.0
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 6.0
    #: holdout drift gauge threshold (mean |canary − baseline| margin
    #: on the registered holdout); None disables the objective
    holdout_drift_threshold: Optional[float] = None
    holdout_target: float = 0.99
    #: LIVE-traffic drift gate (ISSUE 15): worst per-feature /
    #: prediction PSI from the attached
    #: :class:`~mmlspark_tpu.core.drift.DriftMonitor` staying under
    #: this while the canary soaks; None disables (and without
    #: :meth:`RolloutController.attach_drift` the objectives are never
    #: declared).  Unlike the holdout gauge this watches the traffic
    #: actually hitting the rollout, so a canary promoted INTO a
    #: drifting feed is caught even when the model itself is healthy.
    live_drift_threshold: Optional[float] = 0.25
    live_drift_target: float = 0.99
    #: background gate cadence (:meth:`RolloutController.start`)
    tick_s: float = 0.5
    #: how long promote/rollback waits for in-flight pinned batches
    #: before invalidating the superseded booster's cache
    retire_grace_s: float = 5.0


def rollout_objectives(cfg: RolloutConfig,
                       holdout: bool = False,
                       live_drift: bool = False) -> List[SLObjective]:
    """The canary gate's objectives, reading the ``rollout``
    namespace's counters (plus, with ``live_drift``, the attached
    drift monitor's ``ns="drift"`` gauges)."""
    objs = [
        SLObjective(
            "canary_error_ratio", cfg.error_target,
            "canary scoring errors (rescued on the baseline) per "
            "canary row",
            bad=(("rollout", "canary_errors"),),
            total=(("rollout", "canary_rows"),
                   ("rollout", "canary_errors"))),
    ]
    if cfg.canary_deadline_ms is not None:
        objs.append(SLObjective(
            "canary_deadline_miss", cfg.deadline_target,
            "canary rows scored past the canary deadline",
            bad=(("rollout", "canary_deadline_miss"),),
            total=(("rollout", "canary_rows"),
                   ("rollout", "canary_errors"))))
    if holdout and cfg.holdout_drift_threshold is not None:
        objs.append(SLObjective(
            "canary_holdout_drift", cfg.holdout_target,
            "mean |canary - baseline| margin on the holdout staying "
            "under the drift threshold",
            gauge=("rollout", "canary_holdout_drift"),
            threshold=float(cfg.holdout_drift_threshold)))
    if live_drift and cfg.live_drift_threshold is not None:
        objs.append(SLObjective(
            "canary_live_drift", cfg.live_drift_target,
            "worst per-feature PSI on LIVE traffic (attached drift "
            "monitor vs the fit-time reference profile) staying under "
            "the rollout drift threshold",
            gauge=("drift", "psi_worst"),
            threshold=float(cfg.live_drift_threshold)))
        objs.append(SLObjective(
            "canary_prediction_drift", cfg.live_drift_target,
            "prediction-margin PSI on live traffic staying under the "
            "rollout drift threshold",
            gauge=("drift", "psi_prediction"),
            threshold=float(cfg.live_drift_threshold)))
    return objs


class _Arms:
    """One immutable blue/green snapshot.  Batches pin it (refcount)
    for their whole scoring call: swaps replace the controller's
    POINTER, never the snapshot a batch is using, so no batch ever
    sees two generations of arms."""

    __slots__ = ("baseline", "canary", "fraction", "baseline_info",
                 "canary_info", "refs", "lock", "drained")

    def __init__(self, baseline, canary, fraction: float,
                 baseline_info: Dict[str, Any],
                 canary_info: Optional[Dict[str, Any]]):
        self.baseline = baseline
        self.canary = canary
        self.fraction = float(fraction) if canary is not None else 0.0
        self.baseline_info = baseline_info
        self.canary_info = canary_info
        self.refs = 0
        self.lock = threading.Lock()
        self.drained = threading.Event()
        self.drained.set()

    def pin(self) -> "_Arms":
        with self.lock:
            self.refs += 1
            self.drained.clear()
        return self

    def unpin(self) -> None:
        with self.lock:
            self.refs -= 1
            if self.refs <= 0:
                self.drained.set()


def render_model_info(arm_infos: List[Dict[str, Any]],
                      prefix: str = PREFIX) -> str:
    """The ``mmlspark_tpu_serving_model_info`` info-style family: one
    always-1 gauge per serving arm, labelled with the arm name, the
    registry version and the content digest — joinable against any
    other family the scrape carries (the Prometheus *_info idiom)."""
    name = f"{prefix}_serving_model_info"
    lines = [
        f"# HELP {name} Active model per serving arm (info-style: "
        "value is always 1; labels carry version/digest/arm).",
        f"# TYPE {name} gauge",
    ]
    for info in arm_infos:
        arm = info.get("arm", "baseline")
        version = info.get("version", "")
        digest = str(info.get("digest", ""))
        lines.append(
            f'{name}{{arm="{arm}",digest="{digest}",'
            f'version="{version}"}} 1')
    return "\n".join(lines) + "\n"


class RolloutController:
    """Blue/green rollout over a :class:`ModelRegistry`, gated by SLO
    burn rates.  Plugs into :class:`~mmlspark_tpu.io.scoring
    .ScoringEngine` as an ordinary predictor (``engine =
    ScoringEngine(server, predictor=controller)``); the engine detects
    ``routes_by_rid`` and calls :meth:`score_routed` with the batch's
    request ids so the canary split is per-request and retry-stable.

    Lifecycle::

        ctl = RolloutController(registry, backend="auto").install(server)
        engine = ScoringEngine(server, predictor=ctl).start()
        ctl.start()                      # background gate ticks
        ...
        v = registry.publish(new_booster)     # candidate
        ctl.start_canary(v)                   # canary takes traffic
        # the gate promotes or rolls back on its own
    """

    #: the ScoringEngine hook: batches arrive with their rids
    routes_by_rid = True

    def __init__(self, registry: ModelRegistry, *,
                 backend: str = "auto",
                 config: Optional[RolloutConfig] = None,
                 stats: Optional[StageStats] = None):
        self.registry = registry
        self.cfg = config or RolloutConfig()
        self._backend = backend
        self.stats = stats or StageStats()
        for k in ("baseline_rows", "canary_rows", "canary_errors",
                  "canary_deadline_miss", "canary_fallback_rows",
                  "promotions", "rollbacks", "canaries_started"):
            self.stats.incr(k, 0)
        self._pt_baseline = self.stats.timer("arm_baseline")
        self._pt_canary = self.stats.timer("arm_canary")
        self._journal = get_journal()
        self._lock = threading.Lock()
        self._boosters: Dict[str, Any] = {}   # arm -> live Booster
        self._soak_started: Optional[float] = None
        # counters are process-cumulative; the gate and the journal
        # must report THIS rollout's traffic, so start_canary snapshots
        # a zero-point and everything gates on the delta — otherwise a
        # canary that saw no traffic inherits the previous rollout's
        # rows and sails through min_canary_rows
        self._canary_rows0 = 0
        self._canary_errors0 = 0
        self._monitor: Optional[SLOMonitor] = None
        self._holdout: Optional[np.ndarray] = None
        self._holdout_ref: Optional[np.ndarray] = None
        #: live-traffic drift gate (ISSUE 15): attach_drift() installs
        #: a DriftMonitor; canaries then gate on its PSI gauges too
        self._drift = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: chaos/test seam: wraps the canary predictor at
        #: :meth:`start_canary` (the drill injects ChaosPredictor here)
        self.canary_wrap: Optional[Callable[[Any], Any]] = None
        active = registry.active_version()
        if active is None:
            raise RegistryError(
                "registry has no active version to serve as baseline; "
                "publish(model, activate=True) one first")
        booster = registry.load(active)
        self._boosters["baseline"] = booster
        self._arms = _Arms(
            booster.predictor(backend=backend), None, 0.0,
            self._info_for(active), None)
        self.num_features = self._arms.baseline.num_features
        get_registry().register("rollout", self.stats)
        get_registry().register_exposition(
            "serving_model_info",
            lambda: render_model_info(self.model_info()["arms"]))

    # -- wiring --------------------------------------------------------------

    @property
    def mode(self) -> str:
        return "rollout"

    def _info_for(self, version: int) -> Dict[str, Any]:
        e = self.registry.entry(version)
        return {"version": int(version), "digest": e["digest"],
                "state": e["promoted_state"]}

    def install(self, server) -> "RolloutController":
        """Hook the server's ``/readyz`` model block (and any
        fan-out the server does to worker processes)."""
        if hasattr(server, "model_info_provider"):
            server.model_info_provider = self.model_info
        return self

    def model_info(self) -> Dict[str, Any]:
        """The active arms — the ``/readyz`` model block and the
        ``serving_model_info`` labels."""
        arms = self._arms
        out = [{"arm": "baseline", **arms.baseline_info}]
        if arms.canary is not None and arms.canary_info is not None:
            out.append({"arm": "canary", **arms.canary_info,
                        "fraction": arms.fraction})
        return {"arms": out,
                "active_version": arms.baseline_info.get("version"),
                "canary_version":
                    (arms.canary_info or {}).get("version"),
                "state": self.state()}

    def state(self) -> str:
        return "canarying" if self._arms.canary is not None else "steady"

    def set_holdout(self, X) -> None:
        """Register a holdout matrix for the drift gauge: each tick
        with a live canary scores it on both arms and gauges the mean
        absolute margin difference."""
        X = np.ascontiguousarray(np.asarray(X, np.float32))
        self._holdout = X
        self._holdout_ref = None      # recomputed against current arms

    def attach_drift(self, monitor) -> "RolloutController":
        """Attach a :class:`~mmlspark_tpu.core.drift.DriftMonitor`
        (ISSUE 15): it is installed process-wide (``ns="drift"`` +
        exposition) and every canary's private gate gains the
        live-traffic drift objectives (``canary_live_drift`` /
        ``canary_prediction_drift``) next to the holdout gauge — a
        canary soaking while the input or prediction distribution
        shifts past ``cfg.live_drift_threshold`` is auto-rolled-back
        by the same burn machinery as an erroring one."""
        from ..core.drift import set_drift_monitor
        self._drift = monitor
        set_drift_monitor(monitor)
        return self

    # -- routing -------------------------------------------------------------

    def arm_for(self, rid: str, fraction: Optional[float] = None,
                salt: Optional[str] = None) -> str:
        """Deterministic per-rid arm choice: the first 8 hex digits of
        ``sha256(rid:salt)`` as a uniform draw in [0, 1).  Same rid →
        same arm, always — retries and per-row salvage land where the
        original did.  The salt is the canary version, so each rollout
        samples an independent slice of the id space."""
        arms = self._arms
        if fraction is None:
            fraction = arms.fraction
        if fraction <= 0.0:
            return "baseline"
        if salt is None:
            salt = str((arms.canary_info or {}).get("version", ""))
        h = hashlib.sha256(f"{rid}:{salt}".encode("utf-8")).hexdigest()
        draw = int(h[:8], 16) / float(0x100000000)
        return "canary" if draw < fraction else "baseline"

    def __call__(self, X):
        """Plain predictor contract (no rids — e.g. a transform-mode
        caller): everything scores on the baseline arm."""
        arms = self._arms.pin()
        try:
            return self._score_arm(arms, "baseline", np.asarray(X))
        finally:
            arms.unpin()

    def _score_arm(self, arms: _Arms, arm: str, X: np.ndarray):
        """Score one arm with pow2 padding (the engine skips its own
        padding for routed predictors — sub-batches pad here so the
        jit walk keeps its bounded compile cache)."""
        pred = arms.baseline if arm == "baseline" else arms.canary
        n = X.shape[0]
        pad = getattr(pred, "mode", "jit") != "native"
        if pad:
            b = next_pow2(n)
            if b > n:
                Xp = np.zeros((b, X.shape[1]), np.float32)
                Xp[:n] = X
                X = Xp
        timer = self._pt_baseline if arm == "baseline" \
            else self._pt_canary
        t0 = time.perf_counter()
        out = np.asarray(pred(X))[:n]
        dur = time.perf_counter() - t0
        timer.record(dur)
        if arm == "canary":
            self.stats.incr("canary_rows", n)
            dl = self.cfg.canary_deadline_ms
            if dl is not None and dur * 1e3 > dl:
                self.stats.incr("canary_deadline_miss", n)
        else:
            self.stats.incr("baseline_rows", n)
        return out

    def score_routed(self, X, rids) -> np.ndarray:
        """The engine's routed entrypoint: split the batch's rows by
        arm, score each sub-batch on its pinned arm, scatter the
        margins back into input order.  A canary failure is rescored
        on the baseline (zero wrong answers; the gate counts the
        burn).  The arms snapshot is pinned for the whole call, so a
        concurrent promote/rollback cannot mix versions inside this
        batch."""
        X = np.asarray(X)
        arms = self._arms.pin()
        try:
            if arms.canary is None:
                return self._score_arm(arms, "baseline", X)
            salt = str((arms.canary_info or {}).get("version", ""))
            canary_idx = [i for i, rid in enumerate(rids)
                          if self.arm_for(str(rid), arms.fraction,
                                          salt) == "canary"]
            if not canary_idx:
                return self._score_arm(arms, "baseline", X)
            cset = set(canary_idx)
            base_idx = [i for i in range(X.shape[0])
                        if i not in cset]
            parts: List[tuple] = []
            if base_idx:
                parts.append((base_idx, self._score_arm(
                    arms, "baseline", X[base_idx])))
            try:
                cm = self._score_arm(arms, "canary", X[canary_idx])
            except Exception:  # noqa: BLE001 - canary fault: the
                # client still gets a CORRECT answer (baseline), the
                # gate gets the error signal
                log.exception("canary scoring failed; rescoring %d "
                              "rows on the baseline", len(canary_idx))
                self.stats.incr("canary_errors", len(canary_idx))
                self.stats.incr("canary_fallback_rows",
                                len(canary_idx))
                cm = self._score_arm(arms, "baseline", X[canary_idx])
            parts.append((canary_idx, cm))
            first = parts[0][1]
            out_shape = (X.shape[0],) + first.shape[1:]
            out = np.empty(out_shape, first.dtype)
            for idx, vals in parts:
                out[idx] = vals
            return out
        finally:
            arms.unpin()

    # -- the gate ------------------------------------------------------------

    def start_canary(self, version: Optional[int] = None) -> int:
        """Load ``version`` (default: the newest candidate) from the
        registry (digest-verified) and put it in the canary slot.  The
        soak clock and a FRESH gate monitor start now."""
        with self._lock:
            if self._arms.canary is not None:
                raise RegistryError(
                    "a canary rollout is already in flight "
                    f"(version {self._arms.canary_info['version']})")
            if version is None:
                cands = self.registry.candidates()
                if not cands:
                    raise RegistryError(
                        "registry has no candidate version to canary")
                version = cands[-1]
            booster = self.registry.load(version)   # digest-verified
            pred = booster.predictor(backend=self._backend)
            if self.canary_wrap is not None:
                pred = self.canary_wrap(pred)
            old = self._arms
            self._boosters["canary"] = booster
            self._arms = _Arms(old.baseline, pred,
                               self.cfg.canary_fraction,
                               old.baseline_info,
                               self._info_for(version))
            self._soak_started = time.monotonic()
            self._canary_rows0 = self.stats.counter("canary_rows")
            self._canary_errors0 = self.stats.counter("canary_errors")
            # fresh per-rollout gate: burn windows must not inherit a
            # previous canary's errors
            self._monitor = SLOMonitor(
                rollout_objectives(
                    self.cfg, holdout=self._holdout is not None,
                    live_drift=self._drift is not None),
                fast_window_s=self.cfg.fast_window_s,
                slow_window_s=self.cfg.slow_window_s,
                fast_burn_threshold=self.cfg.fast_burn_threshold,
                slow_burn_threshold=self.cfg.slow_burn_threshold)
            # the zero-point reading: windowed deltas count from the
            # canary's first moment, so the FIRST tick after traffic
            # already sees the burn instead of needing two post-fault
            # samples
            self._monitor.sample()
            self._holdout_ref = None
            self.stats.incr("canaries_started")
        self._journal.emit("rollout_started", version=int(version),
                           fraction=self.cfg.canary_fraction,
                           soak_s=self.cfg.soak_s)
        return int(version)

    def _retire(self, arms: _Arms, booster) -> None:
        """Wait (bounded) for the superseded snapshot's pinned batches
        to drain, then invalidate the retired booster's prediction
        cache so any predictor still bound to it RAISES instead of
        silently scoring the old forest."""
        if booster is None:
            return
        # still serving under another arm (promote moves the canary
        # booster into the baseline slot) → must NOT be invalidated
        with self._lock:
            if any(b is booster for b in self._boosters.values()):
                return
        if not arms.drained.wait(self.cfg.retire_grace_s):
            log.warning("rollout: %d batch(es) still pinned to the "
                        "retired arms after %.1fs; invalidating anyway",
                        arms.refs, self.cfg.retire_grace_s)
        booster.invalidate_cache()

    def promote(self) -> int:
        """Atomic cutover: the canary's registry entry activates, the
        canary predictor becomes the baseline, and the superseded
        baseline booster is invalidated after its in-flight batches
        drain.  Returns the promoted version."""
        with self._lock:
            old = self._arms
            if old.canary is None or old.canary_info is None:
                raise RegistryError("no canary in flight to promote")
            version = int(old.canary_info["version"])
            self.registry.activate(version)
            info = self._info_for(version)
            # the promoted predictor may be chaos-wrapped (canary_wrap
            # is a drill seam); the baseline must serve the REAL one
            booster = self._boosters.pop("canary")
            retired_booster = self._boosters.get("baseline")
            self._boosters["baseline"] = booster
            self._arms = _Arms(booster.predictor(
                backend=self._backend), None, 0.0, info, None)
            self._soak_started = None
            self._monitor = None
            self._holdout_ref = None
            self.stats.incr("promotions")
            rows = (self.stats.counter("canary_rows")
                    - self._canary_rows0)
        self._journal.emit("rollout_promoted", version=version,
                           canary_rows=rows)
        self._retire(old, retired_booster)   # the superseded baseline
        return version

    def rollback(self, reason: str = "slo_burn",
                 detail: Optional[dict] = None) -> int:
        """Yank the canary: clear the slot atomically, mark the
        registry entry ``rolled_back``, journal + flight-record the
        scene.  Returns the version rolled back."""
        with self._lock:
            old = self._arms
            if old.canary is None or old.canary_info is None:
                raise RegistryError("no canary in flight to roll back")
            version = int(old.canary_info["version"])
            try:
                self.registry.mark(version, "rolled_back")
            except RegistryError:
                pass   # already quarantined by a failed load elsewhere
            retired_booster = self._boosters.pop("canary", None)
            self._arms = _Arms(old.baseline, None, 0.0,
                               old.baseline_info, None)
            self._soak_started = None
            self._monitor = None
            self._holdout_ref = None
            self.stats.incr("rollbacks")
            rows = (self.stats.counter("canary_rows")
                    - self._canary_rows0)
            errors = (self.stats.counter("canary_errors")
                      - self._canary_errors0)
        ev = {"version": version, "reason": reason,
              "canary_rows": rows, "canary_errors": errors}
        if detail:
            ev["slo"] = detail
        self._journal.emit("rollout_rolled_back", **ev)
        record_flight("rollout_rolled_back", ev)
        self._retire(old, retired_booster)
        return version

    def _gauge_holdout_drift(self, arms: _Arms) -> None:
        if self._holdout is None or arms.canary is None:
            return
        try:
            if self._holdout_ref is None:
                self._holdout_ref = np.asarray(
                    arms.baseline(self._holdout), np.float32)
            cm = np.asarray(arms.canary(self._holdout), np.float32)
            drift = float(np.mean(np.abs(cm - self._holdout_ref)))
            self.stats.set_gauge("canary_holdout_drift", drift)
        except Exception:  # noqa: BLE001 - the drift gauge is advisory;
            # a canary fault here shows up through the error objective
            # on live traffic instead
            log.exception("rollout: holdout drift probe failed")

    def tick(self) -> str:
        """One gate evaluation.  Returns the resulting state:
        ``steady`` (no canary), ``soaking``, ``promoted`` or
        ``rolled_back``.  Deterministic given the counters — the chaos
        drill pumps it manually; :meth:`start` runs it on a cadence."""
        with self._lock:
            arms = self._arms
            monitor = self._monitor
            soak_started = self._soak_started
            rows0 = self._canary_rows0
        if arms.canary is None or monitor is None:
            return "steady"
        self._gauge_holdout_drift(arms)
        if self._drift is not None:
            # refresh the live PSI gauges before the gate samples them
            # (rate-limited inside by DriftConfig.eval_interval_s)
            self._drift.evaluate()
        monitor.sample()
        verdicts = monitor.evaluate()
        breaching = sorted(n for n, v in verdicts.items()
                           if v["breach"])
        if breaching:
            self.rollback(reason=f"slo_burn:{','.join(breaching)}",
                          detail={n: verdicts[n] for n in breaching})
            return "rolled_back"
        soaked = (soak_started is not None
                  and time.monotonic() - soak_started
                  >= self.cfg.soak_s)
        if soaked and (self.stats.counter("canary_rows") - rows0
                       >= self.cfg.min_canary_rows):
            self.promote()
            return "promoted"
        return "soaking"

    def slo_report(self) -> Optional[dict]:
        """The gate monitor's current report (None outside a rollout)
        — the chaos drill embeds it next to each verdict."""
        monitor = self._monitor
        if monitor is None:
            return None
        return monitor.report()

    # -- background gate -----------------------------------------------------

    def start(self) -> "RolloutController":
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.cfg.tick_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - the gate must
                    # outlive a transient registry/monitor error
                    log.exception("rollout gate tick failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rollout-gate")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
