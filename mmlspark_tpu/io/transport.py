"""Unified resilient exchange transport (ISSUE 6).

One framed, flow-controlled, resumable byte transport for every socket
protocol in the package.  PRs 1-5 grew four bespoke newline-JSON
protocols over raw sockets — scoring request routing, elastic
heartbeats, worker stats beacons, and the ``/metrics`` scrape fan-in —
each with its own framing, auth and reconnect, and none with
backpressure, integrity checking or half-open-link detection.  This
module replaces all four framings (the reference surface is mmlspark's
socket ``Network``/``DistributedHTTPSource`` executor links, where the
transport IS the fault boundary — SURVEY.md §3.4):

* **Framing** — length-prefixed binary frames with a fixed 28-byte
  header and a CRC32C over the payload; a corrupt or oversized frame is
  a typed error (:class:`ChecksumError` / :class:`FrameTooLarge`),
  never unbounded buffering or a stray ``UnicodeDecodeError``.
* **Handshake** — a 5-byte magic+version preamble followed by a tokened
  HELLO; non-protocol peers are dropped before they touch any state,
  wrong tokens get an ERROR frame and a close.  The token
  authenticates joiners — it does not encrypt the line (see
  docs/transport.md §Security for the canonical caveat).
* **Channels** — one TCP connection multiplexes logical channels
  (:data:`CH_SCORING`, :data:`CH_ELASTIC`, :data:`CH_STATS`,
  :data:`CH_METRICS`, :data:`CH_CONTROL`); each frame names its
  channel, so a slow metrics scrape shares the link with scoring
  traffic without a second protocol.
* **Flow control** — credit-based: a receiver grants an initial window
  and replenishes in batches as it *delivers* frames; a sender that
  exhausts credits blocks (counted as a backpressure stall) and raises
  :class:`Backpressure` past ``send_timeout_s`` — bounded queues on
  both sides, never an unbounded ``sendall`` pile-up.
* **Keepalive** — transport-level PING/PONG with an idle-receive
  deadline detects half-open TCP links (peer died without a FIN) and
  tears them down so the resume machinery can take over.
* **Deadline propagation** — each DATA frame carries the remaining
  milliseconds its sender gave it; receivers get it alongside the
  payload and can drop already-dead work instead of scoring it.
* **Resumable sessions** — every DATA frame is sequence-numbered per
  direction and cumulatively acked; senders keep unacked frames and a
  reconnect (bounded exponential backoff, jittered) replays exactly the
  suffix the peer has not seen — the receiver drops duplicates by
  sequence number, so a link blip loses nothing and duplicates nothing.
* **Binary payloads** (ISSUE 11) — the frame header's ``flags`` field
  gained :data:`FLAG_BINARY`: a frame so marked carries raw bytes that
  are handed to the app ``on_message`` verbatim — no JSON encode on
  the sender, no ``json.loads`` on the receiver, zero per-value Python
  objects on the wire path.  The capability is NEGOTIATED at handshake
  (``bin: 1`` in HELLO/HELLO_ACK, see :attr:`Session.peer_binary`);
  ``send_bytes`` refuses when the peer did not negotiate it, so a
  version-skewed peer degrades to the JSON wire instead of receiving
  frames it would misparse.  The scoring hot path rides this as the
  raw-float32 wire (:mod:`mmlspark_tpu.io.wire`).
* **Trace context** (ISSUE 8) — ``send(..., tc={"tid": ...})`` attaches
  a reserved ``_tc`` payload key carrying the trace id and the sender's
  wall clock; both endpoints journal per-hop transport spans
  (``hop_enqueue`` / ``hop_send`` / ``hop_ack`` on the sender,
  ``hop_deliver`` with the send→recv clock offset on the receiver, a
  ``retrans`` flag on replayed sends), so ``tools/trace_report.py`` can
  stitch a scoring request's driver-side and worker-side spans into ONE
  cross-process timeline.  The ``_tc`` key is stripped before the app's
  ``on_message`` sees the payload.

Telemetry: all endpoints share :data:`transport_stats` (registered
under the ``transport`` namespace): ``frames_sent`` / ``frames_recvd``
/ ``bytes_sent`` / ``bytes_recvd`` / ``retransmits`` / ``crc_drops`` /
``dup_drops`` / ``backpressure_stalls`` / ``reconnects`` / ``resumes``
/ ``session_resets`` / ``keepalive_drops`` / ``oversize_rejected`` /
``handshake_rejects`` / ``bin_frames_sent`` / ``bin_frames_recvd``,
plus per-channel DATA payload byte counters
(``payload_bytes_sent_ch<N>`` / ``payload_bytes_recvd_ch<N>``) and the
wire codec timers (``encode_json`` / ``decode_json`` here;
``encode_binary`` / ``decode_binary`` recorded by
:mod:`mmlspark_tpu.io.wire`) — the encode/decode cost of the two wires
is readable off one scrape, and ``tools/bench_serving.py --wire``
commits the A/B from exactly these numbers.

Chaos: :class:`~mmlspark_tpu.io.chaos.ChaosTransport` wraps either
end's socket via ``TransportConfig.socket_wrap`` (frame bitflips, ack
loss, half-open stalls, mid-frame resets) so the drills exercise the
transport itself.  See docs/transport.md for the frame layout, channel
ids, resume semantics and tuning knobs.
"""

from __future__ import annotations

import hmac
import json
import logging
import random
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

from ..core.capacity import capacity_enabled
from ..core.profiler import get_profiler
from ..core.profiling import StageStats
from ..core.telemetry import get_journal, get_registry

log = logging.getLogger(__name__)

__all__ = [
    "Backpressure", "CH_CONTROL", "CH_ELASTIC", "CH_METRICS",
    "CH_SCORING", "CH_STATS", "ChecksumError", "FLAG_BINARY",
    "FrameTooLarge", "HandshakeError", "Session", "TransportClient",
    "TransportConfig", "TransportError", "TransportServer", "crc32c",
    "parse_address", "transport_stats",
]

# -- protocol constants ------------------------------------------------------

#: connection preamble: 4 magic bytes + 1 version byte, sent by the
#: dialing side before any frame — a peer that does not lead with this
#: is not speaking the protocol and is dropped without touching state
MAGIC = b"MTPX"
VERSION = 1

# frame types (transport-internal; apps only ever see DATA payloads)
T_DATA = 1        # app payload on a channel; sequenced + acked
T_HELLO = 2       # client handshake: token, session id, last_recv
T_HELLO_ACK = 3   # server handshake answer: resumed?, last_recv, credits
T_ACK = 4         # bare cumulative ack (ack rides every header too)
T_CREDIT = 5      # flow-control grant (count in the seq field)
T_PING = 6        # keepalive probe
T_PONG = 7        # keepalive answer
T_ERROR = 8       # typed refusal: {code, detail}; sender closes after
T_CLOSE = 9       # orderly end of session: no resume expected

#: frame-header flag: the payload is raw bytes, NOT JSON — delivered
#: to the app ``on_message`` verbatim.  Only valid on T_DATA frames and
#: only after both peers negotiated ``bin`` at handshake; the scoring
#: hot path's raw-float32 wire (io/wire.py) rides this flag.
FLAG_BINARY = 0x0001

#: logical channels — one connection carries all of them
CH_CONTROL = 0    # session control: app hello, ready beacons, stop
CH_SCORING = 1    # scoring request routing: park / reply / expire / ack
CH_ELASTIC = 2    # elastic training: lease beacons, rendezvous control
CH_STATS = 3      # periodic worker stats beacons
CH_METRICS = 4    # /metrics scrape round-trips

#: header after the u32 length prefix:
#: type(u8) channel(u8) flags(u16) seq(u64) ack(u64) deadline_ms(u32)
#: then crc32c(u32) — 28 bytes total, then the payload.  The CRC
#: covers the 24 header bytes BEFORE it plus the payload, so a flipped
#: bit anywhere past the length prefix is caught (a corrupt ack or seq
#: would silently poison session state, worse than corrupt payload)
_HPREFIX = struct.Struct("<BBHQQI")
_CRC = struct.Struct("<I")
HEADER_BYTES = _HPREFIX.size + _CRC.size
_LEN = struct.Struct("<I")


# -- CRC32C (Castagnoli) -----------------------------------------------------

def _make_crc32c_table() -> Tuple[int, ...]:
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_CRC_TABLE = _make_crc32c_table()


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Table-driven pure-Python CRC32C — the always-available fallback
    (~200 ns/byte; exchange frames are small, so still off every
    per-row hot path)."""
    c = crc ^ 0xFFFFFFFF
    tab = _CRC_TABLE
    for b in data:
        c = tab[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


try:                                    # C extension when the image has
    import google_crc32c as _gcrc32c    # it; no new dependency is added

    def crc32c(data: bytes, crc: int = 0) -> int:
        """CRC32C (Castagnoli) of ``data`` — the per-frame integrity
        check (native extension fast path; chaining via ``crc`` matches
        concatenation, same as the pure-Python fallback)."""
        return _gcrc32c.extend(crc, data)

    # the wire format is pinned by the RFC 3720 vector: refuse a fast
    # path that would frame with a DIFFERENT polynomial
    if crc32c(b"123456789") != 0xE3069283:   # pragma: no cover
        raise ImportError("google_crc32c produced a non-Castagnoli CRC")
except (ImportError, AttributeError):        # pragma: no cover
    crc32c = _crc32c_py


# -- typed errors ------------------------------------------------------------


class TransportError(OSError):
    """Base transport failure.  Subclasses ``OSError`` on purpose: every
    pre-transport call site guarded its bespoke socket writes with
    ``except OSError`` — those guards keep working unchanged."""


class FrameTooLarge(TransportError):
    """A frame exceeded ``max_frame_bytes`` (refused on send; on
    receive the link is closed instead of buffering without bound)."""


class ChecksumError(TransportError):
    """Payload CRC32C mismatch — the stream is poisoned; the link is
    closed and session resume replays the suffix."""


class HandshakeError(TransportError):
    """Magic/version/token refused during connection setup."""


class Backpressure(TransportError):
    """Send credits exhausted beyond ``send_timeout_s`` — the peer is
    not draining; the caller must shed or retry, not queue more."""


class _ProtocolError(TransportError):
    """Framing/sequencing violation (gap, unknown type) — link closed."""


# -- address parsing ---------------------------------------------------------


def parse_address(address: str) -> Tuple[str, int]:
    """Parse ``host:port`` (including bracketed IPv6 ``[::1]:9000``)
    with validation — malformed addresses raise a clear ``ValueError``
    here instead of failing deep inside ``create_connection``."""
    if not isinstance(address, str) or not address.strip():
        raise ValueError(f"malformed exchange address {address!r}: "
                         "expected 'host:port'")
    addr = address.strip()
    if addr.startswith("["):                   # bracketed IPv6
        end = addr.find("]")
        if end < 0:
            raise ValueError(f"malformed IPv6 address {address!r}: "
                             "missing closing ']'")
        host, rest = addr[1:end], addr[end + 1:]
        if not rest.startswith(":"):
            raise ValueError(f"malformed address {address!r}: expected "
                             "':port' after the bracketed IPv6 host")
        port_s = rest[1:]
    else:
        host, sep, port_s = addr.rpartition(":")
        if not sep or not host:
            raise ValueError(f"malformed exchange address {address!r}: "
                             "expected 'host:port'")
        if ":" in host and not host.startswith("["):
            raise ValueError(
                f"ambiguous IPv6 address {address!r}: bracket the host "
                f"as '[{host}]:{port_s}'")
    if not host:
        raise ValueError(f"malformed address {address!r}: empty host")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(f"malformed address {address!r}: port "
                         f"{port_s!r} is not an integer") from None
    if not 0 < port < 65536:
        raise ValueError(f"malformed address {address!r}: port {port} "
                         "outside 1..65535")
    return host, port


# -- config + shared telemetry -----------------------------------------------


@dataclass
class TransportConfig:
    """Tuning knobs for one endpoint (documented in docs/transport.md)."""
    #: hard per-frame ceiling, enforced on send AND receive
    max_frame_bytes: int = 8 << 20
    #: flow-control window granted to the peer at handshake
    initial_credits: int = 256
    #: receiver re-grants after delivering this many frames
    credit_batch: int = 32
    #: receiver sends a bare ACK after this many unacked deliveries
    ack_every: int = 16
    #: send a PING when nothing was sent for this long
    keepalive_interval_s: float = 2.0
    #: declare the link half-open when nothing was RECEIVED for this
    #: long (must comfortably exceed the interval)
    keepalive_timeout_s: float = 10.0
    #: how long a blocked (credit-starved) send waits before raising
    #: :class:`Backpressure`
    send_timeout_s: float = 30.0
    #: handshake must complete within this long (silent peers dropped)
    preauth_timeout_s: float = 30.0
    #: how long the server keeps a disconnected session's state alive
    #: for resume before declaring it lost
    resume_grace_s: float = 30.0
    #: client reconnect budget: attempts, (base, cap) seconds; delays
    #: are exponential and jittered
    reconnect_tries: int = 5
    reconnect_backoff: Tuple[float, float] = (0.1, 2.0)
    connect_timeout_s: float = 10.0
    #: offer the FLAG_BINARY payload capability in the client HELLO.
    #: Production leaves this on; the wire-format A/B bench
    #: (``tools/bench_serving.py --wire json``) pins it off so BOTH
    #: directions measurably ride the JSON fallback
    offer_binary: bool = True
    #: chaos hook: wraps every raw socket right after connect/accept
    #: (:class:`~mmlspark_tpu.io.chaos.ChaosTransport` plugs in here)
    socket_wrap: Optional[Callable[[socket.socket], Any]] = None


def _new_stats() -> StageStats:
    s = StageStats()
    for k in ("frames_sent", "frames_recvd", "bytes_sent", "bytes_recvd",
              "retransmits", "crc_drops", "dup_drops",
              "backpressure_stalls", "reconnects", "resumes",
              "session_resets", "keepalive_drops", "oversize_rejected",
              "handshake_rejects", "bin_frames_sent", "bin_frames_recvd"):
        s.incr(k, 0)
    # per-channel DATA payload bytes: the wire-format A/B
    # (tools/bench_serving.py --wire) reads payload volume per channel
    # straight off a scrape instead of instrumenting call sites
    for ch in (CH_CONTROL, CH_SCORING, CH_ELASTIC, CH_STATS, CH_METRICS):
        s.incr(f"payload_bytes_sent_ch{ch}", 0)
        s.incr(f"payload_bytes_recvd_ch{ch}", 0)
    return s


#: process-wide transport counters, shared by every endpoint in the
#: process and federated under the ``transport`` namespace so every
#: ``/metrics`` scrape carries them
transport_stats = _new_stats()
# JSON wire codec timers, resolved once (timer() locks per call — a
# measurable tax at per-frame rates; the binary codec in io/wire.py
# caches its timers the same way, so the A/B stays apples-to-apples)
_ENC_JSON = transport_stats.timer("encode_json")
_DEC_JSON = transport_stats.timer("decode_json")
# the continuous profiler's unified phase view (ISSUE 12): the codec
# timers are ALIASED (shared histogram objects — zero extra work per
# frame); only the wire-write phase records explicitly, on a timer
# resolved once
_PROF = get_profiler()
_PROF.alias("transport.encode_json", _ENC_JSON)
_PROF.alias("transport.decode_json", _DEC_JSON)
_PT_WIRE = _PROF.timer("transport.wire_write")
# the wire-write histogram is SHARED back into the transport namespace
# (same zero-copy adopt the profiler aliases use) so the capacity
# monitor's transport resource can window it from the registry — the
# knee estimator reads throughput (frames_sent) against wire-write
# latency, both under ns="transport" (ISSUE 20)
transport_stats.adopt("wire_write", _PT_WIRE)
# per-channel payload-byte counter KEYS, precomputed for the same
# reason (no per-frame f-string build; channels above the table fall
# back to on-the-fly names)
_PB_SENT = tuple(f"payload_bytes_sent_ch{c}" for c in range(8))
_PB_RECVD = tuple(f"payload_bytes_recvd_ch{c}" for c in range(8))
_stats_registered = threading.Event()


def _ensure_registered() -> None:
    if not _stats_registered.is_set():
        get_registry().register("transport", transport_stats)
        _stats_registered.set()


# -- frame codec -------------------------------------------------------------


def encode_frame(ftype: int, channel: int, payload: bytes, *,
                 seq: int = 0, ack: int = 0, deadline_ms: int = 0,
                 flags: int = 0,
                 max_frame_bytes: int = 8 << 20) -> bytes:
    """One wire frame: u32 length, 28-byte header, payload."""
    size = HEADER_BYTES + len(payload)
    if size > max_frame_bytes:
        raise FrameTooLarge(
            f"frame of {size} bytes exceeds max_frame_bytes="
            f"{max_frame_bytes}")
    prefix = _HPREFIX.pack(ftype, channel, flags, seq, ack,
                           min(int(deadline_ms), 0xFFFFFFFF))
    crc = crc32c(payload, crc32c(prefix))
    return _LEN.pack(size) + prefix + _CRC.pack(crc) + payload


def _kill_socket(sock) -> None:
    """Tear a socket down so that a recv() blocked on it in ANOTHER
    thread wakes up: plain ``close()`` only drops the fd — the blocked
    reader can stay parked forever; ``shutdown`` delivers the EOF."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ``ConnectionError`` on EOF."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(65536, n - got))
        if not chunk:
            raise ConnectionError("transport: peer closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def read_frame(sock, max_frame_bytes: int
               ) -> Tuple[int, int, int, int, int, int, bytes]:
    """Read one frame: ``(type, channel, flags, seq, ack, deadline_ms,
    payload)``.  Oversized frames raise :class:`FrameTooLarge` (the
    link must be closed — the stream cannot be re-synced); CRC
    mismatches raise :class:`ChecksumError`."""
    size = _LEN.unpack(_recv_exact(sock, 4))[0]
    if size > max_frame_bytes:
        transport_stats.incr("oversize_rejected")
        raise FrameTooLarge(
            f"incoming frame of {size} bytes exceeds max_frame_bytes="
            f"{max_frame_bytes}")
    if size < HEADER_BYTES:
        raise _ProtocolError(f"frame shorter than header ({size} bytes)")
    buf = _recv_exact(sock, size)
    ftype, channel, flags, seq, ack, deadline_ms = \
        _HPREFIX.unpack_from(buf)
    crc = _CRC.unpack_from(buf, _HPREFIX.size)[0]
    payload = buf[HEADER_BYTES:]
    if crc32c(payload, crc32c(buf[:_HPREFIX.size])) != crc:
        transport_stats.incr("crc_drops")
        raise ChecksumError(
            f"frame CRC32C mismatch on channel {channel} (seq {seq})")
    transport_stats.incr("frames_recvd")
    transport_stats.incr("bytes_recvd", 4 + size)
    return ftype, channel, flags, seq, ack, deadline_ms, payload


# -- session -----------------------------------------------------------------


class Session:
    """One resumable, flow-controlled, sequenced message stream.

    Both endpoints hold one ``Session`` per logical peer; the TCP
    connection underneath may come and go — ``attach``/``detach`` swap
    it while sequence numbers, the unacked replay buffer and the
    receive cursor persist, which is what makes a reconnect lossless
    and duplicate-free.

    ``send`` is safe from any thread.  Delivery callbacks run on the
    endpoint's read pump thread (same threading contract as the old
    line-protocol readers).
    """

    def __init__(self, sid: str, cfg: TransportConfig, *,
                 on_message: Optional[Callable] = None,
                 name: str = "session"):
        self.sid = sid
        self.cfg = cfg
        self.name = name
        self.on_message = on_message
        #: app scratch (the serving driver stores the worker slot here)
        self.meta: Dict[str, Any] = {}
        #: the peer negotiated :data:`FLAG_BINARY` payloads at handshake
        #: (``bin: 1`` in HELLO/HELLO_ACK); gates :meth:`send_bytes` so
        #: a version-skewed peer keeps getting the JSON wire
        self.peer_binary = False
        self._sock: Any = None
        self._slock = threading.Lock()      # wire write serialization
        self._cv = threading.Condition()    # credits + connect state
        self._credits = 0
        #: the credit window the peer last granted whole — the
        #: denominator for the ``credit_occupancy`` saturation gauge
        #: (1 - credits/window; ISSUE 20).  Taps are gated on the flag
        #: cached at session construction — one bool check per send
        #: when capacity observability is off.
        self._credit_window = max(1, int(cfg.initial_credits))
        self._cap_taps = capacity_enabled()
        self._next_seq = 0                  # last DATA seq assigned
        self._peer_ack = 0                  # highest seq peer confirmed
        #: seq -> (channel, payload, abs_deadline_monotonic|None, flags)
        self._unacked: "OrderedDict[int, Tuple[int, bytes, Optional[float], int]]" = OrderedDict()
        self._recv_seq = 0                  # highest contiguous seq seen
        self._since_ack = 0
        self._since_credit = 0
        #: seq -> trace id for in-flight TRACED frames (bounded by the
        #: replay buffer: entries drop when their seq is acked) and the
        #: subset already wired once (a second wire write is a
        #: retransmission, flagged on its hop_send span)
        self._traced: Dict[int, str] = {}
        self._traced_sent: set = set()
        #: highest seq actually written to the CURRENT link; the wire
        #: writer (``flush``) only ever writes ``_wired + 1`` next, so
        #: DATA frames hit the wire in strict sequence order no matter
        #: how sends and resumes interleave — a receiver can never see
        #: a gap that wasn't real loss
        self._wired = 0
        self.connected = False
        self.closed = False
        self.last_recv = time.monotonic()
        self.last_send = time.monotonic()

    # ---- connection lifecycle ----

    def attach(self, sock, ready: bool = True) -> None:
        """Install a live socket.  ``ready=False`` installs it for
        handshake writes only (``mark_connected`` later opens the DATA
        path) — the server must not let queued DATA race ahead of its
        HELLO_ACK."""
        with self._cv:
            self._sock = sock
            self.last_recv = time.monotonic()
            if ready:
                self.connected = True
            self._cv.notify_all()

    def mark_connected(self) -> None:
        with self._cv:
            self.connected = True
            self._cv.notify_all()

    def detach(self, sock=None) -> None:
        """Drop the current link.  With ``sock`` given, detach only if
        that exact socket is still the attached one — a finished pump
        must not tear down the replacement link a takeover or resume
        already attached."""
        with self._cv:
            if sock is not None and self._sock is not sock:
                old = sock          # close the caller's dead socket
            else:
                old, self._sock = self._sock, None
                self.connected = False
            self._cv.notify_all()
        if old is not None:
            _kill_socket(old)

    def close(self) -> None:
        """Orderly end: best-effort CLOSE frame, then drop the link and
        refuse further sends."""
        with self._cv:
            if self.closed:
                return
            self.closed = True
            self._cv.notify_all()
        try:
            self._wire_send(T_CLOSE, CH_CONTROL, b"")
        except OSError:
            pass
        self.detach()

    # ---- sending ----

    def _wire_send(self, ftype: int, channel: int, payload: bytes, *,
                   seq: int = 0, deadline_ms: int = 0) -> None:
        frame = encode_frame(ftype, channel, payload, seq=seq,
                             ack=self._recv_seq, deadline_ms=deadline_ms,
                             max_frame_bytes=self.cfg.max_frame_bytes)
        with self._slock:
            sock = self._sock
            if sock is None:
                raise TransportError("transport: link down")
            sock.sendall(frame)
            self.last_send = time.monotonic()
        transport_stats.incr("frames_sent")
        transport_stats.incr("bytes_sent", len(frame))

    def send(self, channel: int, obj: Any, *,
             deadline_ms: Optional[float] = None,
             timeout: Optional[float] = None,
             tc: Optional[Dict[str, Any]] = None) -> int:
        """Send one JSON message on ``channel``; returns its sequence
        number.  Blocks while credits are exhausted (a backpressure
        stall), raising :class:`Backpressure` past ``timeout``
        (default ``cfg.send_timeout_s``).  While the link is down the
        frame is queued in the replay buffer and goes out on resume;
        a CLOSEd session refuses with :class:`TransportError`.

        ``tc={"tid": trace_id}`` attaches the trace context as the
        reserved ``_tc`` payload key (requires a dict ``obj``), stamps
        the sender's wall clock into it, and journals ``hop_enqueue`` /
        ``hop_send`` / ``hop_ack`` spans for this frame's life so the
        trace reader can reconstruct the transport hop."""
        tid = None
        if tc is not None and isinstance(obj, dict):
            tid = str(tc.get("tid") or "") or None
        if tid:
            obj = dict(obj)
            obj["_tc"] = {"tid": tid, "sts": round(time.time(), 6)}
        t0 = time.perf_counter()
        payload = json.dumps(obj).encode("utf-8")
        _ENC_JSON.record(time.perf_counter() - t0)
        return self._enqueue(channel, payload, 0, deadline_ms,
                             timeout, tid)

    def send_bytes(self, channel: int, data, *,
                   deadline_ms: Optional[float] = None,
                   timeout: Optional[float] = None) -> int:
        """Send one RAW binary message on ``channel`` — the payload
        bytes reach the peer's ``on_message`` verbatim (no JSON on
        either side; :data:`FLAG_BINARY` rides the frame header).
        Requires the peer to have negotiated binary payloads at
        handshake (:attr:`peer_binary`) — callers gate on that flag and
        fall back to :meth:`send`; calling without it is a programming
        error and raises :class:`TransportError` rather than feeding a
        peer frames it would misparse.  Same credit/backpressure/replay
        semantics as :meth:`send`."""
        if not self.peer_binary:
            raise TransportError(
                f"{self.name}: peer did not negotiate binary payloads "
                "(send_bytes requires the handshake 'bin' capability)")
        payload = bytes(data)
        transport_stats.incr("bin_frames_sent")
        return self._enqueue(channel, payload, FLAG_BINARY, deadline_ms,
                             timeout, None)

    def _enqueue(self, channel: int, payload: bytes, flags: int,
                 deadline_ms: Optional[float],
                 timeout: Optional[float],
                 tid: Optional[str]) -> int:
        if HEADER_BYTES + len(payload) > self.cfg.max_frame_bytes:
            raise FrameTooLarge(
                f"message of {len(payload)} bytes exceeds "
                f"max_frame_bytes={self.cfg.max_frame_bytes}")
        budget = self.cfg.send_timeout_s if timeout is None else timeout
        deadline = time.monotonic() + budget
        with self._cv:
            if self.closed:
                raise TransportError("transport: session closed")
            stalled = False
            while self._credits <= 0 and not self.closed:
                stalled = True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    transport_stats.incr("backpressure_stalls")
                    raise Backpressure(
                        f"{self.name}: no send credits for {budget:.1f}s "
                        f"on channel {channel} (peer not draining)")
                self._cv.wait(min(remaining, 0.5))
            if self.closed:
                raise TransportError("transport: session closed")
            if stalled:
                transport_stats.incr("backpressure_stalls")
            self._credits -= 1
            if self._cap_taps:
                self._note_occupancy_locked()
            self._next_seq += 1
            seq = self._next_seq
            abs_deadline = (time.monotonic() + deadline_ms / 1e3
                            if deadline_ms else None)
            self._unacked[seq] = (channel, payload, abs_deadline, flags)
            if tid:
                self._traced[seq] = tid
        transport_stats.incr(
            _PB_SENT[channel] if channel < len(_PB_SENT)
            else f"payload_bytes_sent_ch{channel}", len(payload))
        if tid:
            get_journal().emit("hop_enqueue", tid=tid, channel=channel,
                               seq=seq, session=self.name)
        self.flush()
        return seq

    def flush(self) -> int:
        """Write every queued-but-unwired DATA frame, in strict
        sequence order, to the current link.  THE single wire writer
        for DATA frames: concurrent senders and the resume path all
        funnel through here under one lock, so the peer can never
        observe a sequence gap.  A dead link simply stops the flush —
        the frames stay queued for the next resume."""
        n = 0
        with self._slock:
            while True:
                with self._cv:
                    if not self.connected or self.closed:
                        return n
                    sock = self._sock
                    nxt = self._wired + 1
                    entry = self._unacked.get(nxt)
                if sock is None or entry is None:
                    return n
                channel, payload, abs_deadline, flags = entry
                remaining = 0
                if abs_deadline is not None:
                    remaining = max(
                        1, int((abs_deadline - time.monotonic()) * 1e3))
                frame = encode_frame(
                    T_DATA, channel, payload, seq=nxt,
                    ack=self._recv_seq, deadline_ms=remaining,
                    flags=flags,
                    max_frame_bytes=self.cfg.max_frame_bytes)
                t_w = time.perf_counter()
                try:
                    sock.sendall(frame)
                except OSError:
                    return n   # link died; resume re-flushes the rest
                if _PROF.enabled:
                    _PT_WIRE.record(time.perf_counter() - t_w)
                with self._cv:
                    self._wired = nxt
                    tid = self._traced.get(nxt)
                    retrans = tid is not None \
                        and nxt in self._traced_sent
                    if tid is not None:
                        self._traced_sent.add(nxt)
                self.last_send = time.monotonic()
                transport_stats.incr("frames_sent")
                transport_stats.incr("bytes_sent", len(frame))
                if tid is not None:
                    ev = {"tid": tid, "channel": channel, "seq": nxt,
                          "session": self.name}
                    if retrans:
                        ev["retrans"] = 1
                    get_journal().emit("hop_send", **ev)
                n += 1

    def prepare_resume(self, peer_last: int) -> int:
        """A (re)connect handshake told us the peer has everything up
        to ``peer_last``: drop the acked prefix and REWIND the wire
        cursor so the next ``flush`` retransmits exactly the unseen
        suffix.  Must run BEFORE the new link opens for DATA (attach /
        mark_connected), so no concurrent send can flush from the old
        cursor.  Returns the number of frames that will be
        retransmitted (were wired on a previous link)."""
        self.acknowledge(peer_last)
        with self._cv:
            redo = max(0, min(self._wired, self._next_seq) - peer_last)
            self._wired = peer_last
        if redo:
            transport_stats.incr("retransmits", redo)
        return redo

    def acknowledge(self, upto: int) -> None:
        """Peer confirmed everything ``<= upto``: drop it from the
        replay buffer (and close any traced frames' hop spans)."""
        acked_traced = []
        with self._cv:
            if upto <= self._peer_ack:
                return
            self._peer_ack = upto
            while self._unacked and next(iter(self._unacked)) <= upto:
                self._unacked.popitem(last=False)
            for seq in [s for s in self._traced if s <= upto]:
                acked_traced.append((seq, self._traced.pop(seq)))
                self._traced_sent.discard(seq)
        for seq, tid in acked_traced:
            get_journal().emit("hop_ack", tid=tid, seq=seq,
                               session=self.name)

    def _note_occupancy_locked(self) -> None:
        """Refresh the ``credit_occupancy`` gauge (fraction of the
        granted window currently consumed — 1.0 means the next send
        blocks on backpressure).  Called under ``self._cv``."""
        transport_stats.set_gauge(
            "credit_occupancy",
            round(1.0 - self._credits / self._credit_window, 4))

    def grant(self, n: int) -> None:
        """Receive an incremental flow-control grant of ``n`` frames."""
        with self._cv:
            self._credits += n
            if self._credits > self._credit_window:
                # the peer widened the window (credits above the last
                # whole grant): track it so occupancy stays in [0, 1]
                self._credit_window = self._credits
            if self._cap_taps:
                self._note_occupancy_locked()
            self._cv.notify_all()

    def set_credits(self, n: int) -> None:
        """(Re)connect: the peer granted a fresh window — REPLACE the
        balance (a stale pre-blip balance must not compound)."""
        with self._cv:
            self._credits = n
            self._credit_window = max(1, int(n))
            if self._cap_taps:
                self._note_occupancy_locked()
            self._cv.notify_all()

    def send_credit(self, n: int) -> None:
        """Grant the PEER ``n`` more frames (the count rides the seq
        field; CREDIT frames carry no payload)."""
        self._wire_send(T_CREDIT, CH_CONTROL, b"", seq=n)

    # ---- receiving ----

    def on_data_frame(self, channel: int, flags: int, seq: int,
                      deadline_ms: int, payload: bytes) -> None:
        """Sequence-check one inbound DATA frame and deliver it.
        Duplicates (replay overlap after a resume) are dropped by seq;
        a sequence GAP means the stream lost frames the resume protocol
        should have replayed — that is a protocol violation and the
        link is torn down rather than delivering out of order."""
        if seq <= self._recv_seq:
            transport_stats.incr("dup_drops")
            # refresh the peer's ack cursor so it stops replaying
            try:
                self._wire_send(T_ACK, CH_CONTROL, b"")
            except OSError:
                pass
            return
        if seq != self._recv_seq + 1:
            raise _ProtocolError(
                f"{self.name}: sequence gap (have {self._recv_seq}, "
                f"got {seq})")
        self._recv_seq = seq
        self._since_ack += 1
        self._since_credit += 1
        if self._since_ack >= self.cfg.ack_every:
            self._since_ack = 0
            try:
                self._wire_send(T_ACK, CH_CONTROL, b"")
            except OSError:
                pass
        transport_stats.incr(
            _PB_RECVD[channel] if channel < len(_PB_RECVD)
            else f"payload_bytes_recvd_ch{channel}", len(payload))
        if flags & FLAG_BINARY:
            # raw payload: hand the bytes to the app verbatim — the
            # scoring wire's whole point is that NOTHING decodes here
            transport_stats.incr("bin_frames_recvd")
            obj: Any = payload
        else:
            t0 = time.perf_counter()
            obj = json.loads(payload.decode("utf-8"))
            _DEC_JSON.record(time.perf_counter() - t0)
        if isinstance(obj, dict) and "_tc" in obj:
            # reserved trace-context key: strip it before the app sees
            # the payload, journal the delivery hop with the send→recv
            # wall-clock offset (network + skew — on one host, network)
            tc = obj.pop("_tc")
            if isinstance(tc, dict) and tc.get("tid"):
                try:
                    offset_ms = round(
                        (time.time() - float(tc["sts"])) * 1e3, 3)
                except (KeyError, TypeError, ValueError):
                    offset_ms = None
                get_journal().emit(
                    "hop_deliver", tid=str(tc["tid"]), channel=channel,
                    seq=seq, offset_ms=offset_ms, session=self.name)
        try:
            if self.on_message is not None:
                try:
                    self.on_message(self, channel, obj,
                                    deadline_ms if deadline_ms else None)
                except Exception:  # noqa: BLE001 - a malformed message
                    # (version-skewed peer, app bug) must cost exactly
                    # ONE message, never the connection thread — the
                    # guarantee the old line-protocol reader gave for
                    # its stray KeyErrors
                    log.exception(
                        "%s: message handler failed on channel %d; "
                        "dropping that message", self.name, channel)
        finally:
            if self._since_credit >= self.cfg.credit_batch:
                batch, self._since_credit = self._since_credit, 0
                try:
                    self.send_credit(batch)
                except OSError:
                    pass   # link died; resume re-grants a full window

    def pump(self, sock) -> None:
        """Read frames off ``sock`` until it dies or the session ends.
        Raises nothing: all link failures end the pump after counting;
        the caller decides whether to resume."""
        try:
            while not self.closed:
                (ftype, channel, flags, seq, ack, deadline_ms,
                 payload) = read_frame(sock, self.cfg.max_frame_bytes)
                self.last_recv = time.monotonic()
                if ack:
                    self.acknowledge(ack)
                if ftype == T_DATA:
                    self.on_data_frame(channel, flags, seq, deadline_ms,
                                       payload)
                elif ftype == T_CREDIT:
                    self.grant(seq)
                elif ftype == T_PING:
                    try:
                        self._wire_send(T_PONG, CH_CONTROL, b"")
                    except OSError:
                        pass
                elif ftype in (T_PONG, T_ACK):
                    pass                     # header bookkeeping only
                elif ftype == T_CLOSE:
                    with self._cv:
                        self.closed = True
                        self._cv.notify_all()
                elif ftype == T_ERROR:
                    log.warning("%s: peer error frame: %s", self.name,
                                payload[:200].decode("utf-8", "replace"))
                    with self._cv:
                        self.closed = True
                        self._cv.notify_all()
                else:
                    raise _ProtocolError(
                        f"{self.name}: unknown frame type {ftype}")
        except (ChecksumError, FrameTooLarge, _ProtocolError) as e:
            # poisoned / hostile stream: kill the link; session resume
            # replays whatever the teardown lost
            log.warning("%s: closing link: %s", self.name, e)
        except (OSError, ValueError):
            pass                             # link died / torn JSON tail

    def keepalive_tick(self) -> bool:
        """One keepalive step; returns False when the link is half-open
        (nothing received for ``keepalive_timeout_s``) — the caller
        must tear the connection down."""
        now = time.monotonic()
        if not self.connected:
            return True
        if now - self.last_recv > self.cfg.keepalive_timeout_s:
            transport_stats.incr("keepalive_drops")
            log.warning("%s: half-open link (nothing received for "
                        "%.1fs); dropping", self.name,
                        now - self.last_recv)
            return False
        if now - self.last_send >= self.cfg.keepalive_interval_s:
            try:
                self._wire_send(T_PING, CH_CONTROL, b"")
            except OSError:
                pass
        return True

    # ---- introspection ----

    @property
    def unacked_frames(self) -> int:
        with self._cv:
            return len(self._unacked)

    def reset_stream(self, credits: int) -> None:
        """Forget all stream state (the server lost our session): seqs
        restart, the replay buffer is dropped, a fresh window applies.
        The app layer is responsible for re-establishing its state
        (re-hello, re-park)."""
        with self._cv:
            self._next_seq = 0
            self._peer_ack = 0
            self._recv_seq = 0
            self._since_ack = 0
            self._since_credit = 0
            self._wired = 0
            self._unacked.clear()
            self._traced.clear()
            self._traced_sent.clear()
            self._credits = credits
            self._credit_window = max(1, int(credits))
            if self._cap_taps:
                self._note_occupancy_locked()
            self._cv.notify_all()
        transport_stats.incr("session_resets")


# -- server ------------------------------------------------------------------


class TransportServer:
    """Accepts transport connections, authenticates, and keeps sessions
    resumable across link drops.

    ``on_message(session, channel, obj, deadline_ms)`` runs on the
    connection's read pump; ``on_session(session)`` fires once per NEW
    session (not on resume); ``on_session_lost(session)`` fires when a
    disconnected session's ``resume_grace_s`` expires, when the peer
    sends CLOSE, or when :meth:`drop_session` is called — exactly once
    per session.

    The listener binds in the constructor (so the address is known and
    early dialers queue in the backlog) and accepting starts at
    :meth:`start` — the pre-start dial pattern the serving exchange
    relies on.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 token: str = "", cfg: Optional[TransportConfig] = None,
                 on_message: Optional[Callable] = None,
                 on_session: Optional[Callable] = None,
                 on_session_lost: Optional[Callable] = None,
                 name: str = "transport-server"):
        self.cfg = cfg or TransportConfig()
        self.token = token
        self.name = name
        self.on_message = on_message
        self.on_session = on_session
        self.on_session_lost = on_session_lost
        self.sessions: Dict[str, Session] = {}
        self._dc_since: Dict[str, float] = {}   # sid -> detach time
        self._lock = threading.Lock()
        self._closing = threading.Event()
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self._listener.settimeout(0.2)
        self._accept_thread: Optional[threading.Thread] = None
        self._reaper_thread: Optional[threading.Thread] = None
        _ensure_registered()

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()[:2]

    def start(self) -> "TransportServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self.name}-accept",
            daemon=True)
        self._accept_thread.start()
        self._reaper_thread = threading.Thread(
            target=self._reaper_loop, name=f"{self.name}-reaper",
            daemon=True)
        self._reaper_thread.start()
        return self

    def stop(self) -> None:
        self._closing.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            sessions = list(self.sessions.values())
            self.sessions.clear()
            self._dc_since.clear()
        for s in sessions:
            s.close()
        for t in (self._accept_thread, self._reaper_thread):
            if t is not None:
                t.join(timeout=5)

    def drop_session(self, sid: str, *, notify: bool = True) -> None:
        """Forget a session now (no resume).  ``notify`` fires
        ``on_session_lost`` — the takeover path passes False because
        the slot moved, it was not lost."""
        with self._lock:
            session = self.sessions.pop(sid, None)
            self._dc_since.pop(sid, None)
        if session is None:
            return
        session.close()
        if notify and self.on_session_lost is not None:
            try:
                self.on_session_lost(session)
            except Exception:  # noqa: BLE001
                log.exception("%s: on_session_lost failed", self.name)

    # ---- internals ----

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _addr = self._listener.accept()
            except (TimeoutError, OSError):
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"{self.name}-conn").start()

    def _reaper_loop(self) -> None:
        while not self._closing.wait(0.5):
            horizon = time.monotonic() - self.cfg.resume_grace_s
            with self._lock:
                expired = [sid for sid, t in self._dc_since.items()
                           if t < horizon]
            for sid in expired:
                with self._lock:
                    s = self.sessions.get(sid)
                    if s is not None and s.connected:
                        # resumed while the entry aged (park/attach
                        # race): live sessions are never reaped
                        self._dc_since.pop(sid, None)
                        continue
                log.warning("%s: session %s resume grace expired; "
                            "declaring it lost", self.name, sid[:8])
                self.drop_session(sid)

    def _handshake(self, conn
                   ) -> Optional[Tuple[Session, bool, int, int]]:
        """Run the server half of the handshake.  Returns ``(session,
        resumed, peer_last_recv, peer_granted_credits)`` or ``None``
        when the peer was refused (already closed)."""
        preamble = _recv_exact(conn, len(MAGIC) + 1)
        if preamble[:len(MAGIC)] != MAGIC:
            transport_stats.incr("handshake_rejects")
            log.warning("%s: dropping non-protocol peer (bad magic)",
                        self.name)
            return None
        if preamble[len(MAGIC)] != VERSION:
            transport_stats.incr("handshake_rejects")
            self._refuse(conn, "bad_version",
                         f"server speaks v{VERSION}, "
                         f"peer sent v{preamble[len(MAGIC)]}")
            return None
        ftype, _ch, _fl, _seq, _ack, _dl, payload = read_frame(
            conn, self.cfg.max_frame_bytes)
        if ftype != T_HELLO:
            transport_stats.incr("handshake_rejects")
            self._refuse(conn, "bad_handshake",
                         "first frame must be HELLO")
            return None
        hello = json.loads(payload.decode("utf-8"))
        if not hmac.compare_digest(
                str(hello.get("token", "")).encode("utf-8"),
                self.token.encode("utf-8")):
            transport_stats.incr("handshake_rejects")
            log.warning("%s: dropping peer with bad or missing token",
                        self.name)
            self._refuse(conn, "bad_token", "token mismatch")
            return None
        sid = str(hello.get("session") or "") or uuid.uuid4().hex
        peer_last = int(hello.get("last_recv", 0))
        peer_credits = int(hello.get("credits",
                                     self.cfg.initial_credits))
        with self._lock:
            session = self.sessions.get(sid)
            resumed = session is not None
            if session is None:
                session = Session(sid, self.cfg,
                                  on_message=self._dispatch,
                                  name=f"{self.name}:{sid[:8]}")
                self.sessions[sid] = session
            self._dc_since.pop(sid, None)
        # binary-payload capability: negotiated per HANDSHAKE (a resume
        # from an upgraded or downgraded peer re-evaluates it)
        session.peer_binary = bool(hello.get("bin"))
        if resumed:
            session.detach()   # a takeover replaces any stale link
        return session, resumed, peer_last, peer_credits

    def _refuse(self, conn, code: str, detail: str) -> None:
        try:
            payload = json.dumps({"code": code,
                                  "detail": detail}).encode("utf-8")
            conn.sendall(encode_frame(T_ERROR, CH_CONTROL, payload))
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def _dispatch(self, session: Session, channel: int, obj: Any,
                  deadline_ms: Optional[float]) -> None:
        if self.on_message is not None:
            self.on_message(session, channel, obj, deadline_ms)

    def _serve_conn(self, conn) -> None:
        session = None
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(self.cfg.preauth_timeout_s)
            if self.cfg.socket_wrap is not None:
                conn = self.cfg.socket_wrap(conn)
            shake = self._handshake(conn)
            if shake is None:
                return
            session, resumed, peer_last, peer_credits = shake
            if resumed:
                # rewind the wire cursor BEFORE the link opens for
                # DATA: a concurrent send must replay the unseen
                # suffix, not continue from the dead link's cursor
                session.prepare_resume(peer_last)
            # ready=False: the socket serves the HELLO_ACK only —
            # queued DATA must not race ahead of it
            session.attach(conn, ready=False)
            ack_payload = json.dumps({
                "session": session.sid, "resumed": resumed,
                "last_recv": session._recv_seq,
                "credits": self.cfg.initial_credits,
                "bin": 1 if session.peer_binary else 0}).encode("utf-8")
            session._wire_send(T_HELLO_ACK, CH_CONTROL, ack_payload)
        except (OSError, ValueError, KeyError):
            # pre-auth timeout, torn handshake, garbage peer — nothing
            # registered (or the session stays parked for resume)
            try:
                conn.close()
            except OSError:
                pass
            if session is not None:
                self._park(session)
            return
        self._run_session(session, conn, resumed, peer_credits)

    def _run_session(self, session: Session, conn, resumed: bool,
                     peer_credits: int) -> None:
        try:
            conn.settimeout(None)
            # the peer's HELLO granted our send window; a resume
            # REPLACES any stale pre-blip balance
            session.set_credits(peer_credits)
            session.mark_connected()
            # clear any disconnect stamp the OLD link's teardown raced
            # in between attach and mark_connected — a stale stamp
            # would silently shorten the next blip's resume grace
            with self._lock:
                self._dc_since.pop(session.sid, None)
            if resumed:
                transport_stats.incr("resumes")
                get_journal().emit("transport_resume",
                                   session=session.name,
                                   unacked=session.unacked_frames)
                session.flush()   # retransmit the unseen suffix
            elif self.on_session is not None:
                try:
                    self.on_session(session)
                except Exception:  # noqa: BLE001
                    log.exception("%s: on_session failed", self.name)
            ka = threading.Thread(target=self._keepalive,
                                  args=(session, conn), daemon=True,
                                  name=f"{self.name}-keepalive")
            ka.start()
            session.pump(conn)
        finally:
            self._park(session, conn)

    def _park(self, session: Session, conn=None) -> None:
        """The link died: keep the session for resume (or finish it if
        the peer CLOSEd)."""
        session.detach(conn)
        if session.closed:
            self.drop_session(session.sid)
            return
        with self._lock:
            if session.sid in self.sessions and not session.connected:
                self._dc_since.setdefault(session.sid, time.monotonic())

    def _keepalive(self, session: Session, conn) -> None:
        step = max(0.2, self.cfg.keepalive_interval_s / 2)
        while (session.connected and session._sock is conn
               and not self._closing.is_set() and not session.closed):
            if not session.keepalive_tick():
                _kill_socket(conn)   # wake the pump; resume takes over
                return
            time.sleep(step)


# -- client ------------------------------------------------------------------


class TransportClient:
    """Dials a :class:`TransportServer`, keeps ONE resumable session
    across reconnects (bounded exponential backoff with jitter), and
    replays unacked frames on resume.

    Callbacks (all optional):

    * ``on_message(session, channel, obj, deadline_ms)`` — inbound app
      payloads, on the read pump thread.
    * ``on_connect(resumed: bool)`` — after every successful handshake
      (the serving worker sends its app hello + re-parks here).
    * ``on_session_reset()`` — the server did NOT recognize our session
      (state reaped / server restarted): stream state was reset and the
      app must re-establish its world.
    * ``on_disconnect()`` — the link just dropped (reconnect begins).
    * ``on_down()`` — the reconnect budget is exhausted; the session is
      closed and stays closed.
    """

    def __init__(self, address, *, token: str = "",
                 cfg: Optional[TransportConfig] = None,
                 on_message: Optional[Callable] = None,
                 on_connect: Optional[Callable] = None,
                 on_session_reset: Optional[Callable] = None,
                 on_disconnect: Optional[Callable] = None,
                 on_down: Optional[Callable] = None,
                 name: str = "transport-client"):
        if isinstance(address, str):
            address = parse_address(address)
        self.address = (address[0], int(address[1]))
        self.token = token
        self.cfg = cfg or TransportConfig()
        self.name = name
        self.on_connect = on_connect
        self.on_session_reset = on_session_reset
        self.on_disconnect = on_disconnect
        self.on_down = on_down
        self.session = Session(uuid.uuid4().hex, self.cfg,
                               on_message=on_message, name=name)
        self._lock = threading.Lock()
        self._pump_thread: Optional[threading.Thread] = None
        self._ka_thread: Optional[threading.Thread] = None
        self._reconnecting = False
        #: set by every dead pump; consumed by the reconnect loop — a
        #: reconnect REQUEST must never be lost to the in-progress
        #: guard (see _reconnect_loop)
        self._reconnect_pending = False
        self._local_close = False
        _ensure_registered()

    # ---- public surface ----

    @property
    def connected(self) -> bool:
        return self.session.connected

    @property
    def closed(self) -> bool:
        return self.session.closed

    def send(self, channel: int, obj: Any, *,
             deadline_ms: Optional[float] = None,
             timeout: Optional[float] = None,
             tc: Optional[Dict[str, Any]] = None) -> int:
        return self.session.send(channel, obj, deadline_ms=deadline_ms,
                                 timeout=timeout, tc=tc)

    def send_bytes(self, channel: int, data, *,
                   deadline_ms: Optional[float] = None,
                   timeout: Optional[float] = None) -> int:
        return self.session.send_bytes(channel, data,
                                       deadline_ms=deadline_ms,
                                       timeout=timeout)

    def connect(self, *, retries: Optional[int] = None
                ) -> "TransportClient":
        """Dial and handshake; raises on failure after the bounded
        retry budget (``cfg.reconnect_tries`` unless overridden)."""
        budget = self.cfg.reconnect_tries if retries is None else retries
        last: Optional[BaseException] = None
        for attempt in range(max(1, int(budget) + 1)):
            if attempt:
                time.sleep(self._backoff(attempt - 1))
            try:
                self._dial_once()
                return self
            except HandshakeError:
                raise    # deterministic refusal: retrying cannot help
            except (OSError, ValueError) as e:
                last = e
        raise TransportError(
            f"{self.name}: could not reach "
            f"{self.address[0]}:{self.address[1]} after "
            f"{budget + 1} attempts: {last}") from last

    def close(self) -> None:
        self._local_close = True
        self.session.close()
        t = self._pump_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    # ---- internals ----

    def _backoff(self, attempt: int) -> float:
        base, cap = self.cfg.reconnect_backoff
        delay = min(base * (2 ** attempt), cap)
        # jitter spreads simultaneous reconnects (a killed exchange
        # would otherwise see every worker re-dial in lockstep)
        return delay * random.uniform(0.5, 1.5)

    def _dial_once(self) -> None:
        sock = socket.create_connection(
            self.address, timeout=self.cfg.connect_timeout_s)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.cfg.socket_wrap is not None:
                sock = self.cfg.socket_wrap(sock)
            sock.settimeout(self.cfg.preauth_timeout_s)
            sock.sendall(MAGIC + bytes([VERSION]))
            hello = json.dumps({
                "token": self.token, "session": self.session.sid,
                "last_recv": self.session._recv_seq,
                "credits": self.cfg.initial_credits,
                "bin": 1 if self.cfg.offer_binary else 0
                }).encode("utf-8")
            sock.sendall(encode_frame(
                T_HELLO, CH_CONTROL, hello,
                max_frame_bytes=self.cfg.max_frame_bytes))
            ftype, _ch, _fl, _seq, _ack, _dl, payload = read_frame(
                sock, self.cfg.max_frame_bytes)
            if ftype == T_ERROR:
                err = json.loads(payload.decode("utf-8"))
                raise HandshakeError(
                    f"{self.name}: server refused handshake: "
                    f"{err.get('code')} ({err.get('detail')})")
            if ftype != T_HELLO_ACK:
                raise HandshakeError(
                    f"{self.name}: expected HELLO_ACK, got frame type "
                    f"{ftype}")
            ack = json.loads(payload.decode("utf-8"))
            resumed = bool(ack.get("resumed"))
            credits = int(ack.get("credits",
                                  self.cfg.initial_credits))
            # binary capability confirmed by the server (an old server
            # omits the key → JSON wire everywhere)
            self.session.peer_binary = bool(ack.get("bin"))
            sock.settimeout(None)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        had_state = self.session._next_seq > 0 \
            or self.session._recv_seq > 0
        if resumed:
            # rewind BEFORE the link opens so any concurrent send
            # flushes the replay suffix in order
            self.session.prepare_resume(int(ack.get("last_recv", 0)))
            self.session.set_credits(credits)
            self.session.attach(sock)
            transport_stats.incr("resumes")
            get_journal().emit("transport_resume", session=self.name,
                               unacked=self.session.unacked_frames)
            self.session.flush()
        else:
            if had_state:
                # the server forgot us: full stream reset — the app
                # must rebuild its world (re-hello, re-park)
                log.warning("%s: server did not recognize session %s; "
                            "resetting stream state", self.name,
                            self.session.sid[:8])
                self.session.reset_stream(credits)
            else:
                self.session.set_credits(credits)
            self.session.attach(sock)
            self.session.flush()
        self._start_pumps(sock)
        if not resumed and had_state and self.on_session_reset is not None:
            try:
                self.on_session_reset()
            except Exception:  # noqa: BLE001
                log.exception("%s: on_session_reset failed", self.name)
        if self.on_connect is not None:
            try:
                self.on_connect(resumed)
            except Exception:  # noqa: BLE001
                log.exception("%s: on_connect failed", self.name)

    def _start_pumps(self, sock) -> None:
        self._pump_thread = threading.Thread(
            target=self._pump, args=(sock,), daemon=True,
            name=f"{self.name}-pump")
        self._pump_thread.start()
        self._ka_thread = threading.Thread(
            target=self._keepalive, args=(sock,), daemon=True,
            name=f"{self.name}-keepalive")
        self._ka_thread.start()

    def _pump(self, sock) -> None:
        self.session.pump(sock)
        self.session.detach(sock)
        if self.session.closed:
            # PEER-initiated end (T_CLOSE / T_ERROR) is still "session
            # over" for the app — a worker blocked on stop_evt must
            # learn about it; a locally requested close() already has
            # its caller in control and gets no callback
            if not self._local_close and self.on_down is not None:
                try:
                    self.on_down()
                except Exception:  # noqa: BLE001
                    log.exception("%s: on_down failed", self.name)
            return
        # unexpected drop: reconnect with bounded, jittered backoff
        if self.on_disconnect is not None:
            try:
                self.on_disconnect()
            except Exception:  # noqa: BLE001
                log.exception("%s: on_disconnect failed", self.name)
        self._reconnect_loop()

    def _keepalive(self, sock) -> None:
        step = max(0.2, self.cfg.keepalive_interval_s / 2)
        while (self.session.connected and self.session._sock is sock
               and not self.session.closed):
            if not self.session.keepalive_tick():
                _kill_socket(sock)   # wake the pump → reconnect path
                return
            time.sleep(step)

    def _reconnect_loop(self) -> None:
        """Re-dial with bounded, jittered backoff.  Entry records a
        reconnect REQUEST before the in-progress guard: a link that
        dies milliseconds after a successful resume (a poisoned link
        the chaos drill builds deliberately) has its pump call here
        while the PREVIOUS loop is still unwinding past its dial — the
        old guard silently dropped that request and the client never
        reconnected again.  Now the running loop re-checks the pending
        flag after every successful dial (and once more as it exits),
        so a racing teardown always gets its redial."""
        with self._lock:
            self._reconnect_pending = True
            if self._reconnecting or self.session.closed:
                return
            self._reconnecting = True
        try:
            while True:
                with self._lock:
                    if self.session.closed \
                            or not self._reconnect_pending:
                        return
                    self._reconnect_pending = False
                redialed = False
                for attempt in range(
                        max(0, int(self.cfg.reconnect_tries))):
                    time.sleep(self._backoff(attempt))
                    if self.session.closed:
                        return
                    try:
                        self._dial_once()
                        transport_stats.incr("reconnects")
                        redialed = True
                        break
                    except (OSError, ValueError):
                        continue
                if not redialed:
                    log.warning("%s: reconnect budget exhausted; "
                                "session down", self.name)
                    self.session.close()
                    if self.on_down is not None:
                        try:
                            self.on_down()
                        except Exception:  # noqa: BLE001
                            log.exception("%s: on_down failed",
                                          self.name)
                    return
                # dialed: loop — if the new link already died, its pump
                # set _reconnect_pending and the next pass redials
        finally:
            with self._lock:
                self._reconnecting = False
                retry = self._reconnect_pending \
                    and not self.session.closed
            if retry:
                # a pump died between our last pending check and the
                # guard release: process its request (bounded — each
                # recursion consumes one pending request)
                self._reconnect_loop()
