"""Versioned model registry — the durable store behind zero-downtime
rollout (ISSUE 14).

The serving stack could hot-swap a booster in memory
(``CompiledPredictor`` + ``Booster.invalidate_cache()``) but had no
durable notion of *which* model is in production: a restart reloaded
whatever file happened to be on disk, a torn write served garbage, and
"roll back to yesterday's model" meant a human with scp.  This module
is the registry the :class:`~mmlspark_tpu.io.rollout.RolloutController`
promotes and rolls back against:

* **Monotonic versions** — :meth:`ModelRegistry.publish` assigns the
  next integer version and never reuses one; entries are immutable
  (state transitions aside) so "version 7" always names the same bytes.
* **Durable writes** — the model file is written tmp + fsync + atomic
  rename, then the manifest is replaced the same way and the directory
  fsync'd (the exact write→rename→dirfsync discipline the training
  checkpoints use, docs/fault-tolerance.md): a SIGKILL or power cut at
  ANY instant leaves either the old manifest or the new one, both
  complete — never a half-updated registry.  The manifest replace is
  the single commit point; a model file the manifest doesn't name yet
  is invisible garbage, not a torn entry.
* **Content digests** — every entry records the sha256 of its model
  text; :meth:`load` re-hashes the file on EVERY load and refuses a
  mismatch with :class:`ModelCorruption`, quarantining the entry so the
  rollout gate can never promote it.  (The model file itself also
  carries the ``Booster.save_native_model`` digest header — two
  independent detectors for bit rot; docs/rollout.md §Corruption.)
* **Promotion states** — ``candidate → active → retired`` with
  ``rolled_back`` and ``quarantined`` terminal states; exactly one
  entry is ``active`` at a time and :meth:`activate` refuses
  quarantined entries.  The manifest records the active version, so a
  restarted server resolves "what do I serve" from ONE fsync'd file.

Layout (all under the registry root)::

    manifest.json            # atomic-replaced commit point
    models/v000007.txt       # immutable native-model text per version

The registry is process-local with a lock for thread safety; the
multi-writer case (several drivers publishing concurrently) is out of
scope — production deployments run one publisher (the training loop)
per registry root, like one writer per checkpoint dir.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)

__all__ = ["ModelCorruption", "ModelRegistry", "RegistryError"]

_MANIFEST = "manifest.json"
_MODELS_DIR = "models"
_FORMAT = 1

#: entry lifecycle (docs/rollout.md §Gate state machine)
STATES = ("candidate", "active", "retired", "rolled_back", "quarantined")


class RegistryError(RuntimeError):
    """Registry contract violation (unknown version, illegal state
    transition, unreadable manifest)."""


class ModelCorruption(RegistryError):
    """A model file's bytes no longer hash to the digest recorded at
    publish time (bit rot, torn write, tampering).  The entry is
    quarantined; the caller must fall back to a healthy version."""


def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed file survives power loss
    (same rationale as the checkpoint writer's)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + atomic rename + directory fsync."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def sha256_hex(data) -> str:
    if isinstance(data, str):
        data = data.encode("utf-8")
    return hashlib.sha256(data).hexdigest()


class ModelRegistry:
    """Durable, versioned store of native-model strings.

    ``pre_commit_hook`` is a chaos/test seam: called immediately BEFORE
    each manifest replace (the commit point), so a drill can SIGKILL
    the process mid-cutover and prove recovery lands on one consistent
    version (tools/chaos_rollout.py scenario C).
    """

    def __init__(self, root: str, *,
                 pre_commit_hook: Optional[Callable[[], None]] = None):
        self.root = os.path.abspath(root)
        self._models = os.path.join(self.root, _MODELS_DIR)
        os.makedirs(self._models, exist_ok=True)
        self._lock = threading.RLock()
        self.pre_commit_hook = pre_commit_hook
        self._manifest = self._read_manifest()

    # -- manifest ------------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, _MANIFEST)

    def _read_manifest(self) -> Dict[str, Any]:
        path = self._manifest_path()
        # a stale .tmp from a crash mid-atomic-write is garbage by
        # contract (the rename never landed); ignore it
        if not os.path.exists(path):
            return {"format": _FORMAT, "next_version": 1,
                    "active": None, "entries": {}}
        try:
            with open(path, "rb") as fh:
                m = json.loads(fh.read().decode("utf-8"))
        except (OSError, ValueError) as e:
            # the manifest is replaced atomically, so an unparsable one
            # means external damage, not a torn write — refuse loudly
            # instead of silently re-initialising over real entries
            raise RegistryError(
                f"unreadable registry manifest {path}: {e}") from e
        if m.get("format") != _FORMAT:
            raise RegistryError(
                f"registry manifest format {m.get('format')!r} not "
                f"supported (want {_FORMAT})")
        return m

    def _commit(self) -> None:
        """Replace the manifest atomically — THE commit point."""
        if self.pre_commit_hook is not None:
            self.pre_commit_hook()
        data = json.dumps(self._manifest, indent=1,
                          sort_keys=True).encode("utf-8")
        _atomic_write(self._manifest_path(), data)

    def reload(self) -> None:
        """Re-read the manifest from disk, picking up commits made by
        OTHER processes sharing the registry root — e.g. a refresh
        trainer publishing a candidate while this process serves.  The
        manifest replace is atomic, so a reload sees either the old or
        the new state, never a torn one."""
        with self._lock:
            self._manifest = self._read_manifest()

    # -- queries -------------------------------------------------------------

    def entries(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {int(v): dict(e)
                    for v, e in self._manifest["entries"].items()}

    def entry(self, version: int) -> Dict[str, Any]:
        with self._lock:
            e = self._manifest["entries"].get(str(int(version)))
            if e is None:
                raise RegistryError(
                    f"registry has no version {version}")
            return dict(e)

    def active_version(self) -> Optional[int]:
        with self._lock:
            a = self._manifest.get("active")
            return None if a is None else int(a)

    def latest_version(self) -> Optional[int]:
        with self._lock:
            vs = [int(v) for v in self._manifest["entries"]]
            return max(vs) if vs else None

    def candidates(self) -> List[int]:
        """Versions still awaiting a promotion decision, oldest first."""
        with self._lock:
            return sorted(
                int(v) for v, e in self._manifest["entries"].items()
                if e.get("promoted_state") == "candidate")

    def model_path(self, version: int) -> str:
        return os.path.join(self._models, f"v{int(version):06d}.txt")

    def profile_path(self, version: int) -> str:
        """The version's fit-time reference-profile file (ISSUE 15) —
        lives beside the model file, written and verified with the
        identical tmp+fsync+rename+digest discipline."""
        return os.path.join(self._models,
                            f"v{int(version):06d}.profile.json")

    # -- writes --------------------------------------------------------------

    def publish(self, model, *, activate: bool = False,
                meta: Optional[Dict[str, Any]] = None,
                profile=None) -> int:
        """Store a model (a :class:`~mmlspark_tpu.gbdt.booster.Booster`
        or a native-model text string) as the next version.  The model
        file becomes durable BEFORE the manifest names it; a crash
        between the two leaves an invisible orphan file, never a
        dangling entry.  ``activate=True`` additionally promotes the
        new entry in the same manifest commit (the bootstrap path — a
        canaried rollout publishes a candidate and lets the gate
        promote it).

        ``profile`` (ISSUE 15): the fit-time
        :class:`~mmlspark_tpu.core.sketch.ReferenceProfile` (or its
        JSON text) persisted beside the model under the same
        digest-verified atomic-rename discipline; defaults to the
        booster's own ``reference_profile`` when the engine captured
        one.  The profile file becomes durable before the manifest
        names it, exactly like the model file."""
        text = model if isinstance(model, str) \
            else model.save_native_model_string()
        if not text:
            raise RegistryError("refusing to publish an empty model")
        if profile is None:
            profile = getattr(model, "reference_profile", None)
        profile_text = None
        if profile is not None:
            profile_text = profile if isinstance(profile, str) \
                else profile.to_json()
        # embed the booster-level digest header too, so the file is
        # self-verifying even when read outside the registry
        from ..gbdt.booster import with_digest_header
        payload = with_digest_header(text).encode("utf-8")
        digest = sha256_hex(payload)
        with self._lock:
            version = int(self._manifest["next_version"])
            path = self.model_path(version)
            _atomic_write(path, payload)
            entry = {
                "version": version,
                "digest": f"sha256:{digest}",
                "created": time.time(),
                "promoted_state": "candidate",
                "size_bytes": len(payload),
            }
            if profile_text is not None:
                pbytes = profile_text.encode("utf-8")
                _atomic_write(self.profile_path(version), pbytes)
                entry["profile_digest"] = \
                    f"sha256:{sha256_hex(pbytes)}"
            if meta:
                entry["meta"] = dict(meta)
            self._manifest["entries"][str(version)] = entry
            self._manifest["next_version"] = version + 1
            if activate:
                self._activate_locked(version)
            self._commit()
            return version

    def _activate_locked(self, version: int) -> None:
        e = self._manifest["entries"].get(str(int(version)))
        if e is None:
            raise RegistryError(f"registry has no version {version}")
        if e["promoted_state"] == "quarantined":
            raise RegistryError(
                f"version {version} is quarantined (digest mismatch); "
                "refusing to activate")
        old = self._manifest.get("active")
        if old is not None and int(old) != int(version):
            old_e = self._manifest["entries"].get(str(int(old)))
            if old_e is not None \
                    and old_e["promoted_state"] == "active":
                old_e["promoted_state"] = "retired"
        e["promoted_state"] = "active"
        e["promoted_at"] = time.time()
        self._manifest["active"] = int(version)

    def activate(self, version: int) -> int:
        """Promote ``version`` to active (the previous active entry
        retires) in one atomic manifest commit."""
        with self._lock:
            self._activate_locked(int(version))
            self._commit()
            return int(version)

    def mark(self, version: int, state: str) -> None:
        """Record a state transition (``rolled_back`` after a failed
        canary, ``quarantined`` after a digest mismatch).  Demoting the
        active entry clears the active pointer.  ``quarantined`` is
        terminal: it records proven on-disk corruption, and overwriting
        it (e.g. with ``rolled_back``) would make the entry eligible
        for re-activation — transitions out of it raise instead."""
        if state not in STATES:
            raise RegistryError(f"unknown promoted_state {state!r}")
        with self._lock:
            e = self._manifest["entries"].get(str(int(version)))
            if e is None:
                raise RegistryError(
                    f"registry has no version {version}")
            if e["promoted_state"] == "quarantined":
                if state == "quarantined":
                    return          # idempotent re-quarantine
                raise RegistryError(
                    f"version {version} is quarantined (digest "
                    f"mismatch); refusing to mark it {state!r}")
            e["promoted_state"] = state
            if self._manifest.get("active") == int(version) \
                    and state != "active":
                self._manifest["active"] = None
            self._commit()

    def quarantine(self, version: int) -> None:
        self.mark(int(version), "quarantined")

    def rollback(self, to_version: Optional[int] = None) -> int:
        """Demote the active entry to ``rolled_back`` and re-activate
        ``to_version`` (default: the newest retired entry — the model
        that was serving before the bad promote)."""
        with self._lock:
            cur = self._manifest.get("active")
            if to_version is None:
                retired = sorted(
                    (int(v) for v, e in
                     self._manifest["entries"].items()
                     if e.get("promoted_state") == "retired"),
                    reverse=True)
                if not retired:
                    raise RegistryError(
                        "no retired version to roll back to")
                to_version = retired[0]
            if cur is not None:
                ce = self._manifest["entries"].get(str(int(cur)))
                if ce is not None:
                    ce["promoted_state"] = "rolled_back"
                self._manifest["active"] = None
            self._activate_locked(int(to_version))
            self._commit()
            return int(to_version)

    def prune(self, keep_last: int = 5) -> List[int]:
        """Registry retention/GC (ISSUE 18): delete the model +
        profile files of ``retired``/``rolled_back`` entries beyond the
        newest ``keep_last`` of them.  An auto-refreshing loop
        publishes a new version per drift episode, so without GC
        ``models/`` grows until the disk fills.

        Atomicity keeps the manifest-as-commit-point invariant:
        entries leave the manifest FIRST (one atomic replace), files
        are unlinked after — a crash between the two leaves orphan
        files the manifest no longer names (invisible garbage, exactly
        like a crash mid-:meth:`publish`), never a manifest entry whose
        bytes are gone.  ``quarantined`` entries are never pruned:
        they are the forensic evidence of proven corruption.  Active
        and candidate entries are untouched by construction.  Returns
        the pruned versions, oldest first."""
        if keep_last < 0:
            raise RegistryError(
                f"prune keep_last must be >= 0, got {keep_last}")
        with self._lock:
            prunable = sorted(
                int(v) for v, e in self._manifest["entries"].items()
                if e.get("promoted_state") in ("retired", "rolled_back"))
            victims = prunable[:max(0, len(prunable) - int(keep_last))]
            if not victims:
                return []
            paths = []
            for v in victims:
                del self._manifest["entries"][str(v)]
                paths.append(self.model_path(v))
                paths.append(self.profile_path(v))
            self._commit()
        for p in paths:
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass        # profile-less entry, or a re-run after a
                            # crash between commit and unlink
        _fsync_dir(self._models)
        log.info("registry pruned %d version(s): %s",
                 len(victims), victims)
        return victims

    # -- loads ---------------------------------------------------------------

    def verify(self, version: int) -> bool:
        """Re-hash ``version``'s file against its recorded digest."""
        e = self.entry(version)
        path = self.model_path(version)
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            return False
        want = e["digest"].split(":", 1)[-1]
        return sha256_hex(data) == want

    def read_text(self, version: int) -> str:
        """The version's model text, digest-verified.  A mismatch
        quarantines the entry (one atomic manifest commit) and raises
        :class:`ModelCorruption` — a torn or bit-flipped model file is
        REJECTED at load, never served.  An :class:`OSError` (EMFILE,
        an NFS blip, a permission hiccup) raises WITHOUT a state
        transition: only the bytes themselves hashing wrong proves
        corruption, and a transient read failure must not permanently
        strand a healthy version in quarantine."""
        e = self.entry(version)
        path = self.model_path(version)
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as ex:
            raise RegistryError(
                f"model file for version {version} unreadable: "
                f"{ex}") from ex
        want = e["digest"].split(":", 1)[-1]
        got = sha256_hex(data)
        if got != want:
            self.quarantine(version)
            raise ModelCorruption(
                f"model file for version {version} fails its digest "
                f"(want sha256:{want[:12]}…, got sha256:{got[:12]}…); "
                "entry quarantined")
        return data.decode("utf-8")

    def load_profile(self, version: int):
        """The version's fit-time
        :class:`~mmlspark_tpu.core.sketch.ReferenceProfile`,
        digest-verified, or ``None`` with a warning for entries that
        never recorded one (digest-less legacy publishes, fits with
        capture disabled) — drift monitoring is simply off for that
        version, never an error.  A recorded digest that no longer
        matches the bytes is the SAME corruption contract the model
        file has: the entry is quarantined and
        :class:`ModelCorruption` raises; a transient read failure
        raises :class:`RegistryError` without a state transition."""
        e = self.entry(int(version))
        want = e.get("profile_digest")
        if want is None:
            log.warning(
                "registry version %s has no reference profile "
                "(legacy/profile-less entry); drift monitoring is off "
                "for this version", version)
            return None
        path = self.profile_path(int(version))
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as ex:
            raise RegistryError(
                f"reference profile for version {version} unreadable: "
                f"{ex}") from ex
        got = sha256_hex(data)
        if got != want.split(":", 1)[-1]:
            self.quarantine(int(version))
            raise ModelCorruption(
                f"reference profile for version {version} fails its "
                f"digest (want {want[:19]}…, got sha256:{got[:12]}…); "
                "entry quarantined")
        from ..core.sketch import ReferenceProfile
        return ReferenceProfile.from_json(data.decode("utf-8"))

    def load(self, version: Optional[int] = None):
        """Load a :class:`~mmlspark_tpu.gbdt.booster.Booster`
        (``version=None`` loads the active entry).  Both digests — the
        registry's and the file's embedded header — are verified, and
        the version's reference profile (when recorded) is attached as
        ``booster.reference_profile`` so a drift monitor can be built
        straight off the loaded model."""
        from ..gbdt.booster import Booster
        if version is None:
            version = self.active_version()
            if version is None:
                raise RegistryError("registry has no active version")
        text = self.read_text(int(version))
        booster = Booster.load_native_model_string(text)
        booster.reference_profile = self.load_profile(int(version))
        return booster
