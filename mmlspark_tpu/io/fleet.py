"""Sharded predictor fleet over the resumable transport (ISSUE 11).

The single-host :class:`~mmlspark_tpu.io.scoring.ScoringEngine` tops
out at one process's share of the machine; this module is the
"millions of users" tier ROADMAP item 2 planned on top of the PR-6
transport:

* **Tree-range sharding** (``routing="shard"``) — a large forest is
  split into contiguous tree ranges aligned to ``num_class`` boundaries
  (:func:`shard_tree_ranges`); each worker process scores ONLY its
  slice (``Booster.predictor(tree_range=...)``, init score on shard 0
  exactly once) and the driver reduces the partial margin sums in
  shard order.  :class:`ShardedPredictor` is the same partial-sum
  computation run locally — the single-host reference the fleet is
  pinned bit-exact against (the reduce order is identical, so float32
  addition associates identically).
* **Replicated pool** (``routing="replica"``) — every worker holds the
  FULL model and each request routes to exactly one replica by
  consistent hashing (:class:`ConsistentHashRing`): losing or adding a
  replica remaps only the ring arc it owned, not the whole key space —
  the right shape for small models where sharding would just add
  reduce latency.
* **Resumable wire** — every driver↔worker hop is a
  :mod:`mmlspark_tpu.io.transport` session carrying
  :mod:`mmlspark_tpu.io.wire` raw-float32 blocks (requests ship ONE
  packed feature matrix; partials come back as ONE packed margin
  block).  A link blip replays only the unacked frames in both
  directions — an in-flight request's partials survive the blip
  without rescoring, and :class:`~mmlspark_tpu.io.chaos.ChaosTransport`
  drills exactly that (tests/test_fleet.py).

:class:`PredictorFleet` is an ordinary predictor callable
(``(n, f) float32 -> margins`` with ``num_features``/``mode``), so it
plugs straight into ``ScoringEngine(predictor=fleet)`` — the whole
serving stack (admission control, deadlines, salvage, telemetry) rides
on top unchanged.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import queue
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.capacity import capacity_enabled
from ..core.profiler import get_profiler
from ..core.profiling import StageStats
from ..core.telemetry import get_registry
from . import wire
from .transport import (CH_CONTROL, CH_SCORING, TransportClient,
                        TransportConfig, TransportServer, TransportError)

log = logging.getLogger(__name__)

__all__ = [
    "ConsistentHashRing", "PredictorFleet", "ShardedPredictor",
    "shard_tree_ranges",
]


def shard_tree_ranges(num_trees: int, num_shards: int,
                      num_class: int = 1) -> List[Tuple[int, int]]:
    """Split a forest of ``num_trees`` into ``num_shards`` contiguous
    ``(lo, hi)`` tree ranges aligned to ``num_class`` boundaries (both
    forest walkers assign class = local index % K, so shards must hold
    whole boosting iterations).  Ranges are balanced to within one
    iteration; shards beyond the iteration count come back empty
    ``(T, T)`` rather than failing, so a 4-shard fleet can serve a
    3-iteration model."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    K = max(1, int(num_class))
    units = (num_trees + K - 1) // K          # boosting iterations
    base, extra = divmod(units, num_shards)
    ranges: List[Tuple[int, int]] = []
    lo_u = 0
    for s in range(num_shards):
        hi_u = lo_u + base + (1 if s < extra else 0)
        ranges.append((min(lo_u * K, num_trees),
                       min(hi_u * K, num_trees)))
        lo_u = hi_u
    return ranges


class ShardedPredictor:
    """Tree-range partial-sum scoring run locally — the single-host
    reference for the fleet's reduce (identical shard split, identical
    float32 reduce order → bit-exact), and a usable predictor in its
    own right (each call walks the same trees, just as N partial
    walks).  ``include_init_score`` lands on shard 0 exactly once."""

    def __init__(self, booster, num_shards: int = 2,
                 backend: str = "auto",
                 ranges: Optional[Sequence[Tuple[int, int]]] = None):
        self.ranges = list(ranges) if ranges is not None else \
            shard_tree_ranges(len(booster.trees), num_shards,
                              booster.num_class)
        self.num_features = booster.max_feature_idx + 1
        self._K = booster.num_class
        self._parts = [
            booster.predictor(backend=backend, tree_range=(lo, hi),
                              include_init_score=(i == 0))
            for i, (lo, hi) in enumerate(self.ranges)]

    @property
    def mode(self) -> str:
        return "sharded"

    def partials(self, X) -> List[np.ndarray]:
        """Each shard's ``(n, K)`` float32 partial margin block."""
        n = np.shape(X)[0]
        return [np.asarray(p(X), np.float32).reshape(n, -1)
                for p in self._parts]

    def __call__(self, X):
        parts = self.partials(X)
        out = parts[0]
        for p in parts[1:]:         # shard order: the pinned reduce
            out = out + p
        return out[:, 0] if self._K == 1 else out


class ConsistentHashRing:
    """Consistent hashing with virtual nodes: ``route(key)`` maps a
    request id to one replica; removing a node remaps ONLY the arcs it
    owned (its keys spread over the survivors) and re-adding it
    restores them — the property that keeps a replica loss from
    reshuffling every client's affinity."""

    def __init__(self, nodes: Sequence[Any] = (), vnodes: int = 64):
        self._vnodes = int(vnodes)
        self._ring: List[Tuple[int, Any]] = []
        self._nodes: set = set()
        for n in nodes:
            self.add(n)

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.md5(key.encode("utf-8")).digest()[:8], "big")

    def add(self, node: Any) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        # build-and-rebind (like remove): route() bisects the list
        # lock-free from scorer threads, so it must never observe a
        # mid-sort ring
        ring = self._ring + [(self._hash(f"{node}#{v}"), node)
                             for v in range(self._vnodes)]
        ring.sort()
        self._ring = ring

    def remove(self, node: Any) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._ring = [(h, n) for h, n in self._ring if n != node]

    def nodes(self) -> set:
        return set(self._nodes)

    def route(self, key: str) -> Any:
        """The node owning ``key``'s ring arc (clockwise successor)."""
        if not self._ring:
            raise RuntimeError("consistent-hash ring has no nodes")
        h = self._hash(str(key))
        ring = self._ring
        lo, hi = 0, len(ring)
        while lo < hi:                  # first vnode hash > h
            mid = (lo + hi) // 2
            if ring[mid][0] <= h:
                lo = mid + 1
            else:
                hi = mid
        return ring[lo % len(ring)][1]


class _FleetCall:
    """One in-flight fleet request: the partials collected so far and
    the shard set still owed."""

    __slots__ = ("event", "parts", "expect", "error")

    def __init__(self, expect):
        self.event = threading.Event()
        self.parts: Dict[int, np.ndarray] = {}
        self.expect = set(expect)
        self.error: Optional[str] = None


def _rid_version(rid: str) -> Optional[int]:
    """The model version a fleet rid is stamped with (``v<N>|...``), or
    ``None`` for unstamped rids (pre-rollout drivers)."""
    if rid.startswith("v"):
        head, sep, _ = rid.partition("|")
        if sep:
            try:
                return int(head[1:])
            except ValueError:
                return None
    return None


def _fleet_worker_main(driver_host: str, driver_port: int,
                       shard_id: int, model_path: Optional[str],
                       lo: int, hi: int, backend: str, token: str,
                       replica: bool = False,
                       booster=None, version: int = 0) -> None:
    """Fleet worker entrypoint (module-level for spawn pickling; tests
    run it as a thread passing ``booster`` directly).  Holds the shard's
    tree-range partial predictor (or the full model in replica mode),
    answers raw-float32 score requests with packed partial blocks, and
    rides ONE resumable transport session — a link blip replays, it
    does not rescore.

    Model rollout (ISSUE 14): the worker holds a VERSIONED predictor
    map.  ``load_version`` control messages stage a new model from a
    digest-verified file (the registry's) for this shard's new tree
    range; ``activate_version`` flips the default atomically and keeps
    the PREVIOUS version's predictor alive — every score request's rid
    is stamped with the version the driver fanned it out under, so an
    in-flight request completes on its own version on every shard and
    no reduce ever mixes tree-range shards from two models."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if booster is None:
        from ..gbdt.booster import Booster
        booster = Booster.load_native_model(model_path)
    if replica:
        pred = booster.predictor(backend=backend)
    else:
        pred = booster.predictor(backend=backend, tree_range=(lo, hi),
                                 include_init_score=(lo == 0))
    #: version -> predictor; staged entries await activate_version
    preds: Dict[int, Any] = {int(version): pred}
    staged: Dict[int, Any] = {}
    active = {"v": int(version)}
    stop_evt = threading.Event()
    work: "queue.Queue" = queue.Queue()

    def on_message(session, channel, msg, deadline_ms):
        if channel == CH_CONTROL and isinstance(msg, dict):
            op = msg.get("op")
            if op == "stop":
                stop_evt.set()
                work.put(None)
            elif op in ("load_version", "activate_version"):
                # model loads block (file read + predictor build):
                # run them on the work queue, never the read pump
                work.put(msg)
            return
        if channel == CH_SCORING:
            # scoring runs OFF the read pump (a long jit compile must
            # not stall keepalives into a false half-open teardown)
            work.put(msg)

    def handle_version_op(msg) -> None:
        op, v = msg.get("op"), int(msg.get("version", -1))
        try:
            if op == "load_version":
                from ..gbdt.booster import Booster
                # digest-verified load: a torn/bit-flipped model file
                # raises here and the driver aborts the cutover —
                # never a shard serving garbage
                b = Booster.load_native_model(msg["path"])
                if replica:
                    p = b.predictor(backend=backend)
                else:
                    nlo, nhi = int(msg["lo"]), int(msg["hi"])
                    p = b.predictor(backend=backend,
                                    tree_range=(nlo, nhi),
                                    include_init_score=(nlo == 0))
                staged[v] = p
                client.send(CH_CONTROL,
                            {"op": "version_loaded",
                             "shard": shard_id, "version": v})
            elif op == "activate_version":
                p = staged.pop(v, preds.get(v))
                if p is None:
                    raise RuntimeError(
                        f"version {v} was never staged on shard "
                        f"{shard_id}")
                prev = active["v"]
                preds[v] = p
                active["v"] = v
                # keep ONLY the previous version for in-flight
                # requests stamped with it; older ones retire
                for old in [k for k in preds
                            if k not in (v, prev)]:
                    preds.pop(old, None)
                client.send(CH_CONTROL,
                            {"op": "version_active",
                             "shard": shard_id, "version": v})
        except Exception as e:  # noqa: BLE001 - one failed cutover
            # step, reported; the worker keeps serving its current
            # version
            log.exception("fleet shard %d: %s for version %d failed",
                          shard_id, op, v)
            try:
                client.send(CH_CONTROL,
                            {"op": "version_op_failed",
                             "shard": shard_id, "version": v,
                             "req_op": op, "detail": repr(e)})
            except OSError:
                pass

    def on_connect(resumed):
        try:
            client.send(CH_CONTROL, {"op": "hello", "shard": shard_id})
        except OSError:
            pass    # link died instantly; the next reconnect re-hellos

    client = TransportClient(
        (driver_host, driver_port), token=token,
        cfg=TransportConfig(reconnect_backoff=(0.05, 1.0),
                            reconnect_tries=8),
        on_message=on_message, on_connect=on_connect,
        on_down=lambda: (stop_evt.set(), work.put(None)),
        name=f"fleet-shard{shard_id}")
    client.connect()

    def score_one(msg) -> None:
        rid = ""
        try:
            if isinstance(msg, (bytes, memoryview)):
                _kind, rid, X = wire.unpack_matrix(msg)
            elif isinstance(msg, dict):
                if msg.get("op") in ("load_version",
                                     "activate_version"):
                    handle_version_op(msg)
                    return
                if msg.get("op") != "score":
                    return
                # negotiated JSON fallback (peer without the binary
                # capability)
                rid = str(msg.get("rid", ""))
                X = np.asarray(msg["X"], np.float32)
            else:
                return
            # version pinning: score with the predictor the rid was
            # stamped for (the driver's fan-out version), falling back
            # to the active one for unstamped rids — a cutover racing
            # this request cannot make shards answer from two models
            rv = _rid_version(rid)
            p = preds.get(rv if rv is not None else active["v"])
            if p is None:
                p = staged.get(rv)
            if p is None:
                raise RuntimeError(
                    f"shard {shard_id} no longer holds version {rv}")
            m = np.asarray(p(X), np.float32).reshape(X.shape[0], -1)
            if client.session.peer_binary:
                client.send_bytes(
                    CH_SCORING,
                    wire.pack_matrix(rid, m, kind=wire.K_PARTIAL))
            else:
                client.send(CH_SCORING, {"op": "partial", "rid": rid,
                                         "shard": shard_id,
                                         "m": m.tolist()})
        except Exception as e:  # noqa: BLE001 - one request, not the loop
            log.exception("fleet shard %d: scoring failed", shard_id)
            try:
                client.send(CH_SCORING, {"op": "partial_error",
                                         "rid": rid, "shard": shard_id,
                                         "detail": repr(e)})
            except OSError:
                pass

    while not stop_evt.is_set():
        msg = work.get()
        if msg is None:
            break
        score_one(msg)
    client.close()


class PredictorFleet:
    """A multiprocess predictor pool behind one callable.

    ``routing="shard"`` — tree-range sharding with partial-sum reduce:
    every request fans out to ALL shards as one packed float32 block;
    the driver sums the partial margin blocks in shard order (the
    pinned reduce :class:`ShardedPredictor` reproduces locally).

    ``routing="replica"`` — full-model replicas behind consistent-hash
    routing: each request's id picks ONE replica on the ring.

    ``spawn=True`` forks real worker processes (the model rides a temp
    native-model file); ``spawn=False`` runs the workers as threads in
    this process sharing ``booster`` — the test topology (still real
    sockets, real frames, chaos-wrappable).
    """

    def __init__(self, booster, num_shards: int = 2, *,
                 routing: str = "shard", backend: str = "auto",
                 token: Optional[str] = None, host: str = "127.0.0.1",
                 spawn: bool = True, join_timeout: float = 60.0,
                 request_timeout_s: float = 30.0,
                 transport_config: Optional[TransportConfig] = None):
        import secrets
        if routing not in ("shard", "replica"):
            raise ValueError("routing must be 'shard' or 'replica'")
        self.routing = routing
        self.num_shards = int(num_shards)
        self.num_features = booster.max_feature_idx + 1
        self._K = booster.num_class
        self._init_score = float(booster.init_score)
        self._booster = booster
        self._backend = backend
        self._spawn = bool(spawn)
        self._join_timeout = join_timeout
        self._timeout = request_timeout_s
        self.token = secrets.token_hex(16) if token is None else token
        self.ranges = ([(0, len(booster.trees))] * self.num_shards
                       if routing == "replica" else
                       shard_tree_ranges(len(booster.trees),
                                         self.num_shards,
                                         self._K))
        self._ts = TransportServer(
            host, 0, token=self.token,
            cfg=transport_config or TransportConfig(),
            on_message=self._on_msg, on_session_lost=self._on_lost,
            name="fleet-driver")
        self._ring = ConsistentHashRing(range(self.num_shards))
        self._slot_sid: Dict[int, str] = {}
        self._calls: Dict[str, _FleetCall] = {}
        # model rollout state (ISSUE 14): per-version shard ranges +
        # reduce metadata; score() snapshots ONE version per request
        # and stamps it into the rid, so a cutover mid-fan-out can
        # never mix tree-range shards from two models in one reduce
        self._active_version = 0
        # "path" (set in start() / load_version) is what a respawned
        # worker reloads — kept per version so _worker_spec always
        # hands out the active model's file, not the original one
        self._version_meta: Dict[int, Dict[str, Any]] = {
            0: {"ranges": list(self.ranges), "K": self._K,
                "init_score": self._init_score, "path": None}}
        #: (op, version) -> {"event", "acked": set, "failed": dict}
        self._ctrl_waiters: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._closing = threading.Event()
        self._procs: List[Any] = []
        self._threads: List[threading.Thread] = []
        self._model_path: Optional[str] = None
        self._supervisor: Optional[threading.Thread] = None
        # fleet telemetry, federated like every other subsystem
        self.stats = StageStats()
        for k in ("requests", "partials", "timeouts", "shard_errors",
                  "worker_respawns", "version_cutovers"):
            self.stats.incr(k, 0)
        # resolved once: timer() locks per call — per-request tax.
        # All four are fleet-owned and ALIASED into the profile view
        # (newest fleet wins, like the scoring engine's stages) so the
        # perf_report phase table never mixes a per-instance e2e with
        # process-lifetime accumulators
        self._rtt = self.stats.timer("fleet_rtt")
        self._pt_fanout = self.stats.timer("fanout")
        self._pt_wait = self.stats.timer("wait")
        self._pt_reduce = self.stats.timer("reduce")
        prof = get_profiler()
        prof.alias("fleet.request", self._rtt)
        prof.alias("fleet.fanout", self._pt_fanout)
        prof.alias("fleet.wait", self._pt_wait)
        prof.alias("fleet.reduce", self._pt_reduce)
        # saturation taps (ISSUE 20), flag cached like the scoring
        # engine's: in-flight fan-outs and shard responses still owed
        # are the fleet's backlog gauges (summed across processes by
        # the gauge merge policy); reduce_wait_ms is the last request's
        # wait+reduce tail — the first number to grow when a shard
        # stops keeping up
        self._cap_taps = capacity_enabled()
        if self._cap_taps:
            self.stats.set_gauge("fanout_inflight", 0.0)
            self.stats.set_gauge("shards_awaited", 0.0)
        # data-quality tap (ISSUE 15): attach_drift() installs a
        # DriftMonitor; score() then sketches every request's feature
        # block + reduced margins at the fan-out point
        self._drift = None

    def _note_backlog_locked(self) -> None:
        """Refresh the fan-out backlog gauges (called under
        ``self._lock``): requests in flight, and shard responses still
        owed across them — the per-shard saturation signal."""
        self.stats.set_gauge("fanout_inflight", float(len(self._calls)))
        self.stats.set_gauge(
            "shards_awaited",
            float(sum(len(c.expect) for c in self._calls.values())))

    def attach_drift(self, monitor) -> "PredictorFleet":
        """Attach a :class:`~mmlspark_tpu.core.drift.DriftMonitor`
        (built from the served model's reference profile) and install
        it process-wide so the drift SLO objectives and the
        ``mmlspark_tpu_drift_*`` families read it."""
        from ..core.drift import set_drift_monitor
        self._drift = monitor
        set_drift_monitor(monitor)
        return self

    @property
    def mode(self) -> str:
        return "fleet"

    # ---- lifecycle ----

    def _worker_spec(self, shard: int) -> Tuple[Optional[str], int,
                                                int, int]:
        """The ``(model_path, lo, hi, version)`` a (re)spawned worker
        for ``shard`` must come up with: always the ACTIVE version's
        file and tree range.  After a cutover ``self._model_path``
        still names the version-0 model while ``self.ranges`` describes
        the new one — a respawn mixing the two would load the wrong
        forest, hold only version 0, and fail every ``vN|…`` request
        until the next cutover."""
        with self._lock:
            ver = self._active_version
            meta = self._version_meta[ver]
            lo, hi = meta["ranges"][shard]
            path = meta.get("path") or self._model_path
        return path, lo, hi, ver

    def _spawn_proc(self, shard: int):
        import multiprocessing as mp
        ctx = mp.get_context("spawn")
        dh, dp = self._ts.address
        path, lo, hi, ver = self._worker_spec(shard)
        p = ctx.Process(
            target=_fleet_worker_main,
            args=(dh, dp, shard, path, lo, hi,
                  self._backend, self.token,
                  self.routing == "replica"),
            kwargs={"version": ver},
            daemon=True)
        p.start()
        return p

    def start(self) -> "PredictorFleet":
        self._ts.start()
        if self._spawn:
            fd, self._model_path = tempfile.mkstemp(
                suffix=".lgbm.txt", prefix="fleet_model_")
            os.close(fd)
            self._booster.save_native_model(self._model_path)
            with self._lock:
                self._version_meta[0]["path"] = self._model_path
            self._procs = [self._spawn_proc(s)
                           for s in range(self.num_shards)]
        else:
            dh, dp = self._ts.address
            self._threads = [
                threading.Thread(
                    target=_fleet_worker_main,
                    args=(dh, dp, s, None, *self.ranges[s],
                          self._backend, self.token,
                          self.routing == "replica"),
                    kwargs={"booster": self._booster},
                    name=f"fleet-shard{s}", daemon=True)
                for s in range(self.num_shards)]
            for t in self._threads:
                t.start()
        deadline = time.monotonic() + self._join_timeout
        while time.monotonic() < deadline:
            with self._lock:
                if len(self._slot_sid) == self.num_shards:
                    break
            time.sleep(0.02)
        else:
            missing = [s for s in range(self.num_shards)
                       if s not in self._slot_sid]
            self.stop()
            raise RuntimeError(
                f"fleet shards {missing} never joined within "
                f"{self._join_timeout}s")
        if self._spawn:
            self._supervisor = threading.Thread(
                target=self._supervise, name="fleet-supervisor",
                daemon=True)
            self._supervisor.start()
        get_registry().register("fleet", self.stats)
        return self

    def _supervise(self) -> None:
        while not self._closing.wait(0.5):
            for s, p in enumerate(self._procs):
                if p.is_alive() or self._closing.is_set():
                    continue
                log.warning("fleet: shard %d process died (exitcode "
                            "%s); respawning", s, p.exitcode)
                self.stats.incr("worker_respawns")
                self._procs[s] = self._spawn_proc(s)

    def stop(self) -> None:
        self._closing.set()
        for session in list(self._ts.sessions.values()):
            try:
                session.send(CH_CONTROL, {"op": "stop"}, timeout=1.0)
            except OSError:
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for t in self._threads:
            t.join(timeout=5)
        self._ts.stop()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
            self._supervisor = None
        if self._model_path:
            try:
                os.unlink(self._model_path)
            except OSError:
                pass
            self._model_path = None
        # release any caller still parked on an in-flight request
        with self._lock:
            calls = list(self._calls.values())
            self._calls.clear()
        for c in calls:
            c.error = "fleet stopped"
            c.event.set()

    # ---- driver-side protocol ----

    def _on_msg(self, session, channel: int, msg, deadline_ms) -> None:
        if channel == CH_CONTROL and isinstance(msg, dict) \
                and msg.get("op") in ("version_loaded",
                                      "version_active",
                                      "version_op_failed"):
            self._on_version_ack(msg)
            return
        if channel == CH_CONTROL and isinstance(msg, dict) \
                and msg.get("op") == "hello":
            s = msg.get("shard")
            if isinstance(s, int) and 0 <= s < self.num_shards:
                stale_sid = None
                with self._lock:
                    old_sid = self._slot_sid.get(s)
                    if old_sid is not None and old_sid != session.sid:
                        # a respawned worker took the slot over: drop
                        # the superseded session NOW instead of letting
                        # it linger until resume grace fires on_lost
                        stale_sid = old_sid
                    self._slot_sid[s] = session.sid
                    session.meta["shard"] = s
                    # a (re)joined replica re-enters the routing ring —
                    # its old arcs come back, everyone else's keys stay
                    # where they were
                    self._ring.add(s)
                if stale_sid is not None:
                    self._ts.drop_session(stale_sid, notify=False)
            else:
                log.warning("fleet: ignoring hello with invalid shard "
                            "id %r", s)
            return
        if channel != CH_SCORING:
            return
        if isinstance(msg, (bytes, memoryview)):
            try:
                kind, rid, m = wire.unpack_matrix(msg)
            except wire.WireError as e:
                # one malformed partial costs one request, never the
                # session: fail the waiter if the rid is recoverable
                rid = wire.peek_rid(msg)
                self._fail_call(rid, f"malformed partial: {e}")
                return
            if kind != wire.K_PARTIAL:
                return
            self._add_partial(session, rid, m)
        elif isinstance(msg, dict):
            op = msg.get("op")
            if op == "partial":
                m = np.asarray(msg.get("m"), np.float32)
                self._add_partial(session, str(msg.get("rid")), m,
                                  shard=msg.get("shard"))
            elif op == "partial_error":
                self.stats.incr("shard_errors")
                self._fail_call(str(msg.get("rid")),
                                f"shard {msg.get('shard')} failed: "
                                f"{msg.get('detail')}")

    def _add_partial(self, session, rid: str, m: np.ndarray,
                     shard: Optional[int] = None) -> None:
        if shard is None:
            shard = session.meta.get("shard")
        with self._lock:
            call = self._calls.get(rid)
            if call is None or shard not in call.expect:
                return        # late/duplicate partial: already answered
            call.parts[shard] = np.asarray(m, np.float32)
            call.expect.discard(shard)
            done = not call.expect
        self.stats.incr("partials")
        if done:
            call.event.set()

    def _fail_call(self, rid: str, detail: str) -> None:
        with self._lock:
            call = self._calls.pop(rid, None)
        if call is not None:
            call.error = detail
            call.event.set()

    def _on_lost(self, session) -> None:
        """A shard session died for good (resume grace expired): free
        its slot for the respawned worker's hello, take a dead REPLICA
        out of the routing ring (its arcs remap to the survivors — the
        failover the ring exists for; shard-mode fan-out still needs
        every range, so a lost shard there fails calls fast instead),
        and fail the calls still waiting on it — the engine's salvage
        path rescores them once capacity returns."""
        with self._lock:
            s = session.meta.get("shard")
            held = (s is not None
                    and self._slot_sid.get(s) == session.sid)
            if held:
                self._slot_sid.pop(s, None)
                self._ring.remove(s)
            # only a session that still HELD the slot strands calls: a
            # superseded session's loss must not fail requests the NEW
            # healthy session is already serving
            stranded = ([rid for rid, c in self._calls.items()
                         if s in c.expect] if held else [])
        for rid in stranded:
            self._fail_call(rid, f"shard {s} session lost")

    def _session_for(self, shard: int):
        with self._lock:
            sid = self._slot_sid.get(shard)
        session = self._ts.sessions.get(sid) if sid else None
        if session is None:
            raise TransportError(
                f"fleet shard {shard} has no live session")
        return session

    # ---- versioned cutover (ISSUE 14) ----

    def _on_version_ack(self, msg: dict) -> None:
        op = {"version_loaded": "load_version",
              "version_active": "activate_version",
              "version_op_failed": None}[msg["op"]]
        v = int(msg.get("version", -1))
        shard = msg.get("shard")
        keys = ([(op, v)] if op is not None
                else [("load_version", v), ("activate_version", v)])
        with self._lock:
            for key in keys:
                w = self._ctrl_waiters.get(key)
                if w is None:
                    continue
                if msg["op"] == "version_op_failed":
                    w["failed"][shard] = msg.get("detail", "")
                else:
                    w["acked"].add(shard)
                if w["failed"] or len(w["acked"]) >= self.num_shards:
                    w["event"].set()

    def _version_barrier(self, op: str, version: int, payloads,
                         timeout: float) -> None:
        """Send one control message per shard and wait for EVERY shard
        to ack — the all-or-nothing half of the two-phase cutover."""
        waiter = {"event": threading.Event(), "acked": set(),
                  "failed": {}}
        with self._lock:
            self._ctrl_waiters[(op, version)] = waiter
        try:
            for s in range(self.num_shards):
                self._session_for(s).send(
                    CH_CONTROL, payloads[s], timeout=timeout)
            if not waiter["event"].wait(timeout):
                missing = sorted(set(range(self.num_shards))
                                 - waiter["acked"])
                raise TransportError(
                    f"fleet {op} v{version}: shards {missing} never "
                    f"acked within {timeout}s")
            if waiter["failed"]:
                raise TransportError(
                    f"fleet {op} v{version} failed on shards "
                    f"{waiter['failed']}")
        finally:
            with self._lock:
                self._ctrl_waiters.pop((op, version), None)

    def load_version(self, model_path: str,
                     version: Optional[int] = None,
                     timeout: Optional[float] = None) -> int:
        """Phase 1 of the shard-consistent cutover: stage
        ``model_path`` (a digest-stamped native-model file — e.g.
        ``ModelRegistry.model_path(v)``) on EVERY shard under
        ``version``, each shard building its predictor for the NEW
        model's tree ranges.  Blocks until all shards acked the load;
        any shard's failure (digest mismatch included) aborts with the
        fleet still serving the old version everywhere.  ``model_path``
        must stay readable for as long as the version serves: the
        supervisor reloads it when it respawns a crashed worker."""
        from ..gbdt.booster import Booster
        timeout = self._join_timeout if timeout is None else timeout
        # driver-side load verifies the digest once more and yields
        # the new forest's shape for the per-shard tree ranges
        b = Booster.load_native_model(model_path)
        if b.max_feature_idx + 1 > self.num_features:
            raise ValueError(
                f"new model wants {b.max_feature_idx + 1} features, "
                f"fleet clients send {self.num_features}")
        K = b.num_class
        ranges = ([(0, len(b.trees))] * self.num_shards
                  if self.routing == "replica" else
                  shard_tree_ranges(len(b.trees), self.num_shards, K))
        with self._lock:
            if version is None:
                version = max(self._version_meta) + 1
            version = int(version)
            if version in self._version_meta:
                raise ValueError(
                    f"fleet already holds version {version}")
        payloads = [{"op": "load_version", "version": version,
                     "path": model_path, "lo": lo, "hi": hi}
                    for lo, hi in ranges]
        self._version_barrier("load_version", version, payloads,
                              timeout)
        with self._lock:
            self._version_meta[version] = {
                "ranges": ranges, "K": K,
                "init_score": float(b.init_score),
                "path": model_path}
        return version

    def activate_version(self, version: int,
                         timeout: Optional[float] = None) -> int:
        """Phase 2: flip every shard's default to ``version`` (must be
        staged via :meth:`load_version` first) and then flip the
        driver's fan-out version atomically.  Requests fanned out
        before the flip carry the old version in their rids and reduce
        against the OLD model on every shard; requests after carry the
        new one — no reduce ever mixes the two."""
        timeout = self._join_timeout if timeout is None else timeout
        version = int(version)
        with self._lock:
            meta = self._version_meta.get(version)
            if meta is None:
                raise ValueError(
                    f"version {version} was never load_version()ed")
        payloads = [{"op": "activate_version", "version": version}
                    for _ in range(self.num_shards)]
        self._version_barrier("activate_version", version, payloads,
                              timeout)
        with self._lock:
            prev_active = self._active_version
            self._active_version = version
            self.ranges = list(meta["ranges"])
            self._K = meta["K"]
            self._init_score = meta["init_score"]
            # drop metadata for versions the workers retired (they
            # keep only current + previous)
            for v in [v for v in self._version_meta
                      if v not in (version, prev_active)]:
                self._version_meta.pop(v, None)
        self.stats.incr("version_cutovers")
        return version

    @property
    def active_version(self) -> int:
        return self._active_version

    # ---- the predictor contract ----

    def __call__(self, X):
        return self.score(X)

    def score(self, X, key: Optional[str] = None) -> np.ndarray:
        """Score a batch.  ``routing="shard"`` fans the packed block to
        every shard and reduces the partial sums in shard order;
        ``routing="replica"`` consistent-hash-routes the whole request
        to one replica (``key`` overrides the auto request id as the
        ring key — e.g. a client id for session affinity)."""
        X = np.ascontiguousarray(np.asarray(X, np.float32))
        if X.ndim != 2:
            raise ValueError(f"expected (n, f) input, got {X.shape}")
        # ONE version snapshot per request, stamped into the rid: every
        # shard scores this request under exactly this version, and a
        # cutover racing the fan-out changes only LATER requests — the
        # shard-consistency contract (docs/rollout.md §Fleet cutover)
        with self._lock:
            ver = self._active_version
            meta = self._version_meta[ver]
            ranges, K, init_score = (meta["ranges"], meta["K"],
                                     meta["init_score"])
        rid = f"v{ver}|f{next(self._seq)}"
        if self.routing == "shard":
            targets = [s for s, (lo, hi) in enumerate(ranges)
                       if hi > lo]
            if not targets:
                # a 0-tree forest has no shard to ask: the margin is
                # the init score — answer immediately instead of
                # parking a waiter nothing will ever complete
                out = np.full((X.shape[0], K), np.float32(init_score))
                return out[:, 0] if K == 1 else out
        else:
            targets = [self._ring.route(key if key is not None
                                        else rid)]
        call = _FleetCall(targets)
        with self._lock:
            self._calls[rid] = call
            if self._cap_taps:
                self._note_backlog_locked()
        self.stats.incr("requests")
        prof = get_profiler()
        t0 = time.perf_counter()
        try:
            buf = None
            for s in targets:
                session = self._session_for(s)
                if session.peer_binary:
                    if buf is None:
                        buf = wire.pack_matrix(rid, X)
                    session.send_bytes(CH_SCORING, buf,
                                       timeout=self._timeout)
                else:   # negotiated JSON fallback
                    session.send(CH_SCORING,
                                 {"op": "score", "rid": rid,
                                  "X": X.tolist()},
                                 timeout=self._timeout)
            self._pt_fanout.record(time.perf_counter() - t0)
            t_wait = time.perf_counter()
            if not call.event.wait(self._timeout):
                self.stats.incr("timeouts")
                raise TransportError(
                    f"fleet request {rid} timed out after "
                    f"{self._timeout}s (missing shards "
                    f"{sorted(call.expect)})")
            if call.error:
                raise TransportError(
                    f"fleet request {rid} failed: {call.error}")
        finally:
            with self._lock:
                self._calls.pop(rid, None)
                if self._cap_taps:
                    self._note_backlog_locked()
        wait_s = time.perf_counter() - t_wait
        self._pt_wait.record(wait_s)
        t_red = time.perf_counter()
        if self.routing == "replica":
            out = call.parts[targets[0]]
        else:
            # the PINNED reduce: ascending shard order, float32 — the
            # exact association ShardedPredictor uses locally, so the
            # fleet is bit-exact with the single-host reference
            order = sorted(call.parts)
            out = call.parts[order[0]]
            for s in order[1:]:
                out = out + call.parts[s]
        reduce_s = time.perf_counter() - t_red
        self._pt_reduce.record(reduce_s)
        if self._cap_taps:
            # the wait+reduce tail of THIS request, as an instantaneous
            # level — the per-shard lag signal the merged scrape shows
            # without waiting for a histogram window to fill
            self.stats.set_gauge("reduce_wait_ms",
                                 round((wait_s + reduce_s) * 1e3, 3))
        # the request window covers fanout+wait+reduce — it is the
        # fleet's e2e and the aliased fleet.request denominator; slow
        # fan-outs also land on the trace timeline (rid doubles as the
        # trace id for fleet-internal requests)
        req_s = time.perf_counter() - t0
        self._rtt.record(req_s)
        prof.span("fleet.request", req_s, tid=rid, record=False)
        out = out[:, 0] if K == 1 else out
        if self._drift is not None:
            # fleet topology's drift tap (ISSUE 15): the driver is the
            # one process that sees every request's full feature block
            # AND the reduced margin — sketching here covers all
            # shards/replicas with one monitor (duty-gated inside)
            self._drift.observe(X, out)
        return out
