"""HTTP-on-Spark equivalent: a column of requests → pooled async execution
→ a column of responses.

Reference: io/http/HTTPTransformer.scala, SimpleHTTPTransformer.scala,
Clients.scala, Parsers.scala, HandlingUtils.scala (expected paths,
UNVERIFIED — SURVEY.md §2.1).  The reference runs an async HTTP client pool
per partition; here a thread pool per transform call does the same work on
the host (this layer is pure data plane — nothing to accelerate).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, Optional

import numpy as np

from ..core.params import HasInputCol, HasOutputCol, Param, TypeConverters
from ..core.pipeline import Transformer
from ..core.schema import DataTable


class HTTPRequestData:
    """Row payload for HTTPTransformer — mirrors the reference's
    HTTPRequestData struct."""

    __slots__ = ("url", "method", "headers", "body")

    def __init__(self, url: str, method: str = "GET",
                 headers: Optional[Dict[str, str]] = None,
                 body: Optional[bytes] = None):
        self.url = url
        self.method = method
        self.headers = dict(headers or {})
        self.body = body

    @classmethod
    def coerce(cls, v: Any) -> "HTTPRequestData":
        if isinstance(v, HTTPRequestData):
            return v
        if isinstance(v, str):
            return cls(v)
        if isinstance(v, dict):
            body = v.get("body")
            if isinstance(body, str):
                body = body.encode("utf-8")
            return cls(v["url"], v.get("method", "GET"),
                       v.get("headers"), body)
        raise TypeError(f"Cannot coerce {type(v).__name__} to request")


class HTTPResponseData:
    """Response struct: status, reason, headers, body bytes."""

    __slots__ = ("statusCode", "reason", "headers", "body", "error")

    def __init__(self, statusCode: int, reason: str = "",
                 headers: Optional[Dict[str, str]] = None,
                 body: bytes = b"", error: Optional[str] = None):
        self.statusCode = statusCode
        self.reason = reason
        self.headers = dict(headers or {})
        self.body = body
        self.error = error

    def json(self) -> Any:
        return json.loads(self.body.decode("utf-8"))

    def __repr__(self) -> str:
        return (f"HTTPResponseData({self.statusCode}, "
                f"{len(self.body)} bytes)")


def _execute(req: HTTPRequestData, timeout: float, max_retries: int,
             backoff: float) -> HTTPResponseData:
    last_err = None
    for attempt in range(max_retries + 1):
        try:
            r = urllib.request.Request(
                req.url, data=req.body, headers=req.headers,
                method=req.method)
            with urllib.request.urlopen(r, timeout=timeout) as resp:
                return HTTPResponseData(
                    resp.status, getattr(resp, "reason", ""),
                    dict(resp.headers), resp.read())
        except urllib.error.HTTPError as e:
            # HTTP error statuses are responses, not transport failures
            return HTTPResponseData(e.code, str(e.reason),
                                    dict(e.headers or {}),
                                    e.read() if e.fp else b"")
        except Exception as e:  # transport error: retry with backoff
            last_err = e
            if attempt < max_retries:
                time.sleep(backoff * (2 ** attempt))
    return HTTPResponseData(0, "", {}, b"", error=str(last_err))


class HTTPTransformer(HasInputCol, HasOutputCol, Transformer):
    """Executes a column of HTTP requests through a bounded worker pool
    (io/http/HTTPTransformer.scala)."""

    concurrency = Param("concurrency", "Concurrent requests", default=8,
                        typeConverter=TypeConverters.toInt)
    timeout = Param("timeout", "Per-request timeout seconds", default=60.0,
                    typeConverter=TypeConverters.toFloat)
    maxRetries = Param("maxRetries", "Transport-failure retries", default=3,
                       typeConverter=TypeConverters.toInt)
    backoffTime = Param("backoffTime", "Initial retry backoff seconds",
                        default=0.1, typeConverter=TypeConverters.toFloat)

    def _transform(self, table: DataTable) -> DataTable:
        reqs = [HTTPRequestData.coerce(v)
                for v in table[self.getInputCol()]]
        timeout = self.getTimeout()
        retries = self.getMaxRetries()
        backoff = self.getBackoffTime()
        with ThreadPoolExecutor(max_workers=self.getConcurrency()) as pool:
            responses = list(pool.map(
                lambda r: _execute(r, timeout, retries, backoff), reqs))
        out = np.empty(len(responses), dtype=object)
        out[:] = responses
        return table.withColumn(self.getOutputCol(), out)


class JSONInputParser:
    """Builds POST requests from JSON-serializable row payloads
    (io/http/Parsers.scala)."""

    def __init__(self, url: str, headers: Optional[Dict[str, str]] = None,
                 method: str = "POST"):
        self.url = url
        self.headers = {"Content-Type": "application/json",
                        **(headers or {})}
        self.method = method

    def __call__(self, payload: Any) -> HTTPRequestData:
        return HTTPRequestData(
            self.url, self.method, self.headers,
            json.dumps(payload, default=_np_default).encode("utf-8"))


def _np_default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"Not JSON-serializable: {type(o).__name__}")


class JSONOutputParser:
    """Parses response bodies as JSON, optionally drilling into a path."""

    def __init__(self, path: Optional[str] = None):
        self.path = path

    def __call__(self, resp: HTTPResponseData) -> Any:
        if resp.error or resp.statusCode >= 400 or resp.statusCode == 0:
            return None
        obj = resp.json()
        if self.path:
            for part in self.path.split("."):
                obj = obj[int(part)] if part.isdigit() else obj[part]
        return obj


class SimpleHTTPTransformer(HasInputCol, HasOutputCol, Transformer):
    """JSON-in/JSON-out HTTP with error handling in one stage
    (io/http/SimpleHTTPTransformer.scala)."""

    url = Param("url", "Target URL", typeConverter=TypeConverters.toString)
    method = Param("method", "HTTP method", default="POST",
                   typeConverter=TypeConverters.toString)
    errorCol = Param("errorCol", "Column collecting failures",
                     default="error", typeConverter=TypeConverters.toString)
    concurrency = HTTPTransformer.concurrency
    timeout = HTTPTransformer.timeout
    maxRetries = HTTPTransformer.maxRetries
    backoffTime = HTTPTransformer.backoffTime
    flattenOutput = Param("flattenOutput",
                          "JSON path to extract from responses (optional)",
                          default=None, typeConverter=TypeConverters.toString)

    def _headers(self) -> Dict[str, str]:
        return {"Content-Type": "application/json"}

    def _prepare(self, payload: Any) -> HTTPRequestData:
        parser = JSONInputParser(self.getUrl(), self._headers(),
                                 self.getMethod())
        return parser(payload)

    def _transform(self, table: DataTable) -> DataTable:
        payloads = table[self.getInputCol()]
        reqs = [self._prepare(v) for v in payloads]
        timeout, retries = self.getTimeout(), self.getMaxRetries()
        backoff = self.getBackoffTime()
        with ThreadPoolExecutor(max_workers=self.getConcurrency()) as pool:
            responses = list(pool.map(
                lambda r: _execute(r, timeout, retries, backoff), reqs))
        parse = JSONOutputParser(self.getFlattenOutput())
        parsed = np.empty(len(responses), dtype=object)
        errors = np.empty(len(responses), dtype=object)
        for i, resp in enumerate(responses):
            try:
                parsed[i] = parse(resp)
            except (ValueError, KeyError, IndexError) as e:
                parsed[i] = None
                errors[i] = f"parse error: {e}"
                continue
            errors[i] = (resp.error if resp.error
                         else (f"HTTP {resp.statusCode}"
                               if resp.statusCode >= 400 else None))
        return table.withColumns({self.getOutputCol(): parsed,
                                  self.getErrorCol(): errors})


class PartitionConsolidator(Transformer):
    """Coalesce sparse micro-batches into dense ones.

    Reference: io/http/PartitionConsolidator.scala (expected path,
    UNVERIFIED — SURVEY.md §2.1): low-volume HTTP request streams spread
    over many partitions are funneled into few, so downstream batching
    stages see full batches.  Table-in/table-out transform is the
    identity (one table IS one partition here); the streaming surface is
    :meth:`consolidate`, which re-chunks an iterator of small micro-batch
    tables into ``targetBatchSize``-row tables — used between a
    micro-batch source (serving's ``get_batch``, the streaming binary
    reader) and a device-batched model stage.
    """

    targetBatchSize = Param("targetBatchSize",
                            "Rows per consolidated batch", default=64,
                            typeConverter=TypeConverters.toInt)

    def _transform(self, table: DataTable) -> DataTable:
        return table

    def consolidate(self, tables) -> "Iterator[DataTable]":
        """Re-chunk an iterable of tables into target-size tables."""
        target = self.getTargetBatchSize()
        if target < 1:
            raise ValueError(
                f"targetBatchSize must be >= 1, got {target}")
        held: Optional[DataTable] = None
        for t in tables:
            held = t if held is None else held.concat(t)
            while held is not None and len(held) >= target:
                yield held.slice(0, target)
                held = held.slice(target, len(held)) \
                    if len(held) > target else None
        if held is not None and len(held):
            yield held
