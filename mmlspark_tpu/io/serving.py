"""Serving: turn a pipeline into a web service (Spark Serving equivalent).

Reference: io/http/HTTPSourceV2.scala, DistributedHTTPSource.scala,
ServingImplicits.scala (expected paths, UNVERIFIED — SURVEY.md §2.1, §3.4).
The reference parks each HTTP request's open socket keyed by request-id,
emits (id, request) rows into a streaming micro-batch, runs the user's
pipeline, and routes replies back via HTTPSink.

This build keeps that exact architecture, minus Spark streaming: an
:class:`HTTPServer` accepts requests into a queue; the driver loop pulls
micro-batches with :func:`HTTPServer.get_batch`, converts them to a table
(:func:`request_table`), runs any pipeline/model, and answers with
:func:`reply_from_table` — replies route to the still-open sockets by id.
``serve_forever`` wires the loop up for the one-liner case.  Batching is
the TPU-relevant part: requests accumulate into one fixed-size device batch
instead of per-request forwards.
"""

from __future__ import annotations


import json
import logging
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.profiling import StageStats
from ..core.schema import DataTable
from ..core.telemetry import (current_fit_span, get_journal,
                              get_registry, merge_snapshots,
                              mirror_journal_from_env, record_flight,
                              render_prometheus)
from . import wire
from .transport import (CH_CONTROL, CH_METRICS, CH_SCORING, CH_STATS,
                        parse_address)

log = logging.getLogger(__name__)


# numpy → JSON-able, for the negotiated JSON fallback reply path (a
# binary-mode engine hands numpy values through; a session without the
# binary capability still gets correct JSON).  One shared definition —
# the engine's transform path uses the same conversion.
from .scoring import _json_value as _jsonable  # noqa: E402


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """Serving-wide HTTP server invariants, in ONE place for both the
    in-process and worker-process paths:

    * accept backlog 128 — the default (5) overflows under concurrent-
      client bursts; the kernel drops SYNs and clients stall on 1s/3s
      retransmit timers, a serving p99 disaster;
    * quiet ``handle_error`` — a client that resets or abandons its
      connection is business as usual for a public-facing server (the
      chaos drill injects exactly these); log at debug instead of
      spraying tracebacks to stderr.  Anything else still gets a full
      traceback.
    """

    request_queue_size = 128

    def handle_error(self, request, client_address):
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError,
                            BrokenPipeError)):
            log.debug("serving: client %s dropped: %r",
                      client_address, exc)
            return
        log.exception("serving: unhandled error for client %s",
                      client_address)


class _ServingHandler(BaseHTTPRequestHandler):
    """Shared plumbing for every serving HTTP handler: quiet logging,
    HTTP/1.1 keep-alive, JSON replies, and the /healthz + /readyz +
    /metrics endpoints.  Subclasses define ``do_POST``, a ``timeout``
    (the slow-client read deadline — http.server applies it as the
    socket timeout and closes the connection on expiry), ``_ready()``,
    and optionally ``_metrics()`` (defaults to rendering this process's
    global :class:`~mmlspark_tpu.core.telemetry.MetricsRegistry`)."""

    disable_nagle_algorithm = True   # ms-latency serving contract
    # HTTP/1.1 keep-alive: a closed-loop client reuses its connection
    # instead of paying a TCP connect per request (every reply carries
    # Content-Length, so this is safe)
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet
        pass

    def _send_json(self, status, obj):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _ready(self) -> bool:
        return False

    def _model_info(self) -> Optional[dict]:
        """The active model version/digest block ``/readyz`` carries
        when a rollout controller is installed (ISSUE 14 satellite);
        ``None`` keeps the legacy ready-only body."""
        return None

    def _metrics(self) -> Optional[str]:
        """Prometheus text for /metrics; ``None`` -> 503.  Default:
        this process's global registry (scoring engine, train stats,
        whatever else registered).  Instantiating the SLO monitor here
        means the ``mmlspark_tpu_slo_*`` families ride every serving
        scrape from the first one — not only after someone probes
        ``/slo``."""
        from ..core.slo import get_monitor
        get_monitor()
        return get_registry().render_prometheus()

    def _slo(self) -> dict:
        """JSON report for /slo: the process-global SLO monitor's
        burn-rate evaluation (sampling on demand, so two scrapes a few
        seconds apart yield meaningful windowed rates)."""
        from ..core.slo import get_monitor
        return get_monitor().report()

    def _statusz(self) -> str:
        """Plain text for /statusz: the one-page operational summary
        (model version, SLO burn, capacity headroom, top phases,
        worker liveness) assembled from the registries that already
        exist — no new state (ISSUE 20 satellite)."""
        from ..core.capacity import render_statusz
        try:
            info = self._model_info()
        except Exception:  # noqa: BLE001 - advisory block
            info = None
        return render_statusz(model_info=info)

    def do_GET(self):
        if self.path == "/healthz":
            # liveness: the accept loop is running
            self._send_json(200, {"status": "ok"})
        elif self.path == "/readyz":
            try:
                ready = bool(self._ready())
            except Exception:  # noqa: BLE001
                ready = False
            body = {"ready": ready}
            try:
                info = self._model_info()
            except Exception:  # noqa: BLE001 - the model block is
                info = None    # advisory; readiness must still answer
            if info:
                body["model"] = info
            self._send_json(200 if ready else 503, body)
        elif self.path == "/slo":
            try:
                report = self._slo()
            except Exception:  # noqa: BLE001 - the route must degrade
                log.exception("serving: /slo evaluation failed")
                self.send_error(503, "slo monitor unavailable")
                return
            self._send_json(200, report)
        elif self.path == "/metrics":
            try:
                text = self._metrics()
            except Exception:  # noqa: BLE001 - a scrape must degrade,
                log.exception("serving: /metrics render failed")
                text = None
            if text is None:
                self.send_error(503, "metrics unavailable")
                return
            body = text.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/statusz":
            try:
                text = self._statusz()
            except Exception:  # noqa: BLE001 - a status page must
                log.exception("serving: /statusz render failed")
                self.send_error(503, "statusz unavailable")
                return
            body = text.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)


class _Pending:
    __slots__ = ("event", "response", "status", "t_park")

    def __init__(self):
        self.event = threading.Event()
        self.response: Any = None
        self.status = 200
        self.t_park = time.monotonic()


class _TrackedQueue(queue.Queue):
    """A Queue that tracks the request ids currently aboard, so a
    reconnecting worker's re-park can restore the reply route WITHOUT
    double-enqueueing a request whose first copy is still queued
    (scoring it twice would burn batch slots and, in transform mode,
    run user code twice).  ``_put``/``_get`` are Queue's documented
    under-mutex extension hooks."""

    def __init__(self):
        super().__init__()
        self.rids = set()

    def _put(self, item):
        self.rids.add(item[0])
        super()._put(item)

    def _get(self):
        item = super()._get()
        self.rids.discard(item[0])
        return item

    def put_unique(self, item) -> bool:
        """Enqueue unless this rid is already aboard; returns whether
        the item was enqueued."""
        with self.not_full:
            if item[0] in self.rids:
                return False
            self._put(item)
            self.unfinished_tasks += 1
            self.not_empty.notify()
            return True


class _Exchange:
    """Shared request queue + parked-reply table.

    One exchange can back many worker servers: requests from every worker
    land in ONE micro-batch queue, and a reply routes to the parked socket
    by request-id regardless of which worker accepted it — the
    cross-worker reply routing of the reference's DistributedHTTPSource /
    HTTPSink pair (expected path io/http/DistributedHTTPSource.scala,
    UNVERIFIED; SURVEY.md §3.4).

    Lifecycle of a ``pending`` entry: the handler that parked it always
    pops it via :meth:`unpark` (reply, timeout, or client error alike),
    and request ids are uuid4 — never recycled, so a late reply can
    never deliver into a reused id.  As a backstop against a handler
    thread dying between park and unpark (daemon teardown, a killed
    worker thread), :meth:`park` amortizes a sweep that drops entries
    older than ``2 * reply_timeout + sweep_grace`` — a leaked entry
    outlives its client by a bounded margin instead of forever.
    """

    _SWEEP_EVERY = 256

    def __init__(self, reply_timeout: float = 30.0,
                 sweep_grace: float = 10.0):
        self.queue: "queue.Queue[Tuple[str, Any, float]]" = queue.Queue()
        self.pending: Dict[str, _Pending] = {}
        self.lock = threading.Lock()
        self.reply_timeout = reply_timeout
        self.sweep_grace = sweep_grace
        self._parks = 0

    def park(self, payload: Any) -> Tuple[str, _Pending]:
        rid = uuid.uuid4().hex
        pending = _Pending()
        with self.lock:
            self.pending[rid] = pending
            self._parks += 1
            if self._parks % self._SWEEP_EVERY == 0:
                self._sweep_locked()
        # queue items carry the enqueue stamp so the scoring engine's
        # wait-shedding and per-request deadlines see true queue age
        self.queue.put((rid, payload, time.perf_counter()))
        return rid, pending

    def _sweep_locked(self) -> None:
        """Drop pending entries whose handler must be gone (no event is
        set — a live handler unparks within ``reply_timeout``).  Called
        under ``self.lock``."""
        horizon = time.monotonic() - (2 * self.reply_timeout
                                      + self.sweep_grace)
        stale = [r for r, p in self.pending.items()
                 if p.t_park < horizon]
        for r in stale:
            del self.pending[r]
        if stale:
            log.warning("serving: swept %d orphaned pending replies "
                        "(handler died between park and unpark)",
                        len(stale))

    def unpark(self, rid: str) -> bool:
        """Remove a parked request after its wait ended.  Returns whether a
        reply landed — re-checked under the lock: once the entry is popped
        here, any later reply() sees no entry and reports undelivered, so
        a reply racing the timeout either fully delivers or fully fails,
        never both."""
        with self.lock:
            pending = self.pending.pop(rid, None)
            return pending is not None and pending.event.is_set()

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        """Pull a micro-batch as legacy ``(rid, payload)`` 2-tuples (the
        enqueue stamps ride the raw queue only — direct-queue readers
        like the scoring engine use them; batch pullers keep the
        pre-resilience contract)."""
        batch: List[Tuple[str, Any]] = []
        try:
            batch.append(self.queue.get(timeout=timeout)[:2])
            while len(batch) < max_rows:
                batch.append(self.queue.get_nowait()[:2])
        except queue.Empty:
            pass
        return batch

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        with self.lock:
            pending = self.pending.get(request_id)
            if pending is None:
                return False  # socket gone (timeout/disconnect)
            pending.response = response
            pending.status = status
            pending.event.set()
            return True

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        """Batched reply delivery: one lock acquisition for the whole
        micro-batch instead of one per row — the scoring engine's reply
        hot path.  Returns the number delivered."""
        delivered = 0
        with self.lock:
            for rid, response, status in entries:
                pending = self.pending.get(rid)
                if pending is None:
                    continue
                pending.response = response
                pending.status = status
                pending.event.set()
                delivered += 1
        return delivered


class HTTPServer:
    """Accepts JSON POSTs, parks the socket, exposes micro-batches.

    Analog of ``DistributedHTTPSource`` for one process; a mesh deployment
    runs one server per host exactly like the reference runs one per
    executor (SURVEY.md §3.4).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout: float = 30.0,
                 exchange: Optional[_Exchange] = None,
                 request_read_timeout: float = 30.0):
        self._exchange = exchange or _Exchange(reply_timeout)
        # /readyz hook: the scoring engine installs its liveness check
        # here at start(); None means "no engine attached yet" → 503
        self.ready_check: Optional[Callable[[], bool]] = None
        # /metrics hook: None -> the process-global MetricsRegistry;
        # a custom provider returns the full exposition text itself
        self.metrics_provider: Optional[Callable[[], str]] = None
        # /readyz model block: RolloutController.install() points this
        # at its model_info() so operators can read the active
        # version/digest off the readiness probe (ISSUE 14 satellite)
        self.model_info_provider: Optional[Callable[[], dict]] = None
        # /statusz hook: None -> the default one-page summary built
        # from the process-global registries; the multiprocess driver
        # points every worker's route at its fleet-wide render
        self.statusz_provider: Optional[Callable[[], str]] = None
        outer = self

        class Handler(_ServingHandler):
            # slow-client read deadline: a peer that opens a connection
            # and trickles (or never sends) its request body gets cut
            # off instead of parking a handler thread forever
            timeout = request_read_timeout

            def _ready(self):
                check = outer.ready_check
                return check is not None and bool(check())

            def _model_info(self):
                provider = outer.model_info_provider
                return provider() if provider is not None else None

            def _metrics(self):
                provider = outer.metrics_provider
                if provider is not None:
                    return provider()
                return super()._metrics()

            def _statusz(self):
                provider = outer.statusz_provider
                if provider is not None:
                    return provider()
                return super()._statusz()

            def do_POST(self):
                if api_path not in ("/", self.path):
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(
                        self.rfile.read(length).decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    self.send_error(400, "invalid JSON")
                    return
                rid, pending = outer._exchange.park(payload)
                ok = pending.event.wait(outer._exchange.reply_timeout)
                # unpark re-checks under the lock: a reply racing the
                # timeout is either fully delivered or fully refused
                if not outer._exchange.unpark(rid) and not ok:
                    self.send_error(504, "pipeline timeout")
                    return
                body = json.dumps(pending.response).encode("utf-8")
                self.send_response(pending.status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = _QuietThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> "HTTPServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def request_queue(self) -> "queue.Queue[Tuple[str, Any, float]]":
        """The raw parked-request queue (enqueue-stamped 3-tuples) — the
        scoring engine's batcher reads it directly for deadline-aware
        batch forming and queue-age shedding."""
        return self._exchange.queue

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        """Pull up to ``max_rows`` parked requests (micro-batch trigger)."""
        return self._exchange.get_batch(max_rows, timeout)

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        """HTTPSink: route a reply to the parked socket by request-id."""
        return self._exchange.reply(request_id, response, status)

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        """Batched reply routing (one lock for the whole micro-batch)."""
        return self._exchange.reply_many(entries)


class DistributedHTTPServer:
    """N worker HTTP servers over ONE shared exchange.

    The reference's DistributedHTTPSource runs one server per executor
    and routes each reply back to whichever executor parked the socket
    (SURVEY.md §3.4).  Here: every worker pushes into the shared micro-
    batch queue, the driver loop pulls interleaved batches, and
    ``reply``/``reply_from_table`` deliver by request-id across workers.
    """

    def __init__(self, num_workers: int = 2, host: str = "127.0.0.1",
                 api_path: str = "/", reply_timeout: float = 30.0,
                 request_read_timeout: float = 30.0):
        self._exchange = _Exchange(reply_timeout)
        self.workers = [
            HTTPServer(host, 0, api_path, reply_timeout,
                       exchange=self._exchange,
                       request_read_timeout=request_read_timeout)
            for _ in range(num_workers)]

    @property
    def addresses(self) -> List[str]:
        return [w.address for w in self.workers]

    @property
    def ready_check(self) -> Optional[Callable[[], bool]]:
        """/readyz hook, fanned out to every worker server."""
        return self.workers[0].ready_check if self.workers else None

    @ready_check.setter
    def ready_check(self, check: Optional[Callable[[], bool]]) -> None:
        for w in self.workers:
            w.ready_check = check

    @property
    def metrics_provider(self) -> Optional[Callable[[], str]]:
        """/metrics hook, fanned out to every worker server."""
        return self.workers[0].metrics_provider if self.workers else None

    @metrics_provider.setter
    def metrics_provider(self,
                         provider: Optional[Callable[[], str]]) -> None:
        for w in self.workers:
            w.metrics_provider = provider

    @property
    def model_info_provider(self) -> Optional[Callable[[], dict]]:
        """/readyz model-block hook, fanned out to every worker."""
        return self.workers[0].model_info_provider if self.workers \
            else None

    @model_info_provider.setter
    def model_info_provider(
            self, provider: Optional[Callable[[], dict]]) -> None:
        for w in self.workers:
            w.model_info_provider = provider

    @property
    def statusz_provider(self) -> Optional[Callable[[], str]]:
        """/statusz hook, fanned out to every worker server."""
        return self.workers[0].statusz_provider if self.workers \
            else None

    @statusz_provider.setter
    def statusz_provider(
            self, provider: Optional[Callable[[], str]]) -> None:
        for w in self.workers:
            w.statusz_provider = provider

    @property
    def request_queue(self) -> "queue.Queue[Tuple[str, Any, float]]":
        return self._exchange.queue

    def start(self) -> "DistributedHTTPServer":
        for w in self.workers:
            w.start()
        return self

    def stop(self) -> None:
        for w in self.workers:
            w.stop()

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        return self._exchange.get_batch(max_rows, timeout)

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        return self._exchange.reply(request_id, response, status)

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        return self._exchange.reply_many(entries)


def join_exchange(exchange: str, worker_id: int,
                  http_host: str = "0.0.0.0", api_path: str = "/",
                  reply_timeout: float = 30.0, token: str = "",
                  request_read_timeout: float = 30.0,
                  reconnect_tries: int = 5,
                  reconnect_backoff: Tuple[float, float] = (0.1, 2.0)
                  ) -> None:
    """Run ONE serving worker against a remote exchange — the multi-host
    entrypoint (each machine runs this next to its accelerator; the
    reference's per-executor DistributedHTTPSource server,
    SURVEY.md §3.4).  Blocks until the exchange sends ``stop`` or the
    transport session drops beyond repair: the exchange link is an
    :mod:`mmlspark_tpu.io.transport` resumable session, so a link blip
    is re-dialed with bounded, jittered exponential backoff
    (``reconnect_tries`` attempts, delays from
    ``reconnect_backoff=(base, cap)`` seconds), unacked frames are
    replayed, and this worker's still-parked requests survive.
    ``exchange`` is the driver's
    ``MultiprocessHTTPServer(spawn_workers=False).exchange_address``
    (``host:port``, or ``[v6]:port`` for IPv6 — validated up front with
    a clear error instead of failing deep in ``create_connection``);
    ``worker_id`` must be the unique slot index in [0, num_workers);
    ``token`` is the driver's ``MultiprocessHTTPServer.token`` shared
    secret, checked by the transport handshake.  Security posture
    (what the token does and does NOT protect): docs/transport.md
    §Security."""
    host, port = parse_address(exchange)
    _mp_worker_main(host, port, int(worker_id), http_host, api_path,
                    reply_timeout, token, request_read_timeout,
                    reconnect_tries, reconnect_backoff)


def _mp_worker_main(driver_host: str, driver_port: int, worker_id: int,
                    http_host: str, api_path: str,
                    reply_timeout: float, token: str = "",
                    request_read_timeout: float = 30.0,
                    reconnect_tries: int = 5,
                    reconnect_backoff: Tuple[float, float] = (0.1, 2.0)
                    ) -> None:
    """Worker-process entrypoint (module-level for spawn-pickling).

    Owns REAL client sockets in its own process: parks each HTTP request
    locally, forwards (rid, payload) to the driver over ONE
    :class:`~mmlspark_tpu.io.transport.TransportClient` session, and
    delivers driver replies to the parked socket.  Delivery is decided
    ATOMICALLY here (the process that holds the socket), and reported
    back as an app-level ack — that keeps ``reply()``'s delivered/
    undelivered contract exact across process boundaries, matching the
    reference where HTTPSink's reply lands on whichever executor parked
    the socket (SURVEY.md §3.4).

    Resilience now lives in the transport: a link blip reconnects with
    bounded, jittered backoff, resumes the session and replays unacked
    frames in both directions — no park or reply is lost to the blip
    and none is duplicated (sequence dedup).  On every (re)connect the
    worker re-hellos and re-parks its still-pending requests: a no-op
    on a clean resume (the driver's ``put_unique`` dedups), and exactly
    the rebuild required after a session RESET (driver restarted or
    resume grace expired).  ``/healthz`` reports process liveness;
    ``/readyz`` reports whether the exchange session is up.
    """
    from .transport import TransportClient, TransportConfig

    # cross-process tracing: when the driver-side tool set
    # MMLSPARK_TPU_JOURNAL_DIR, this worker's journal (request_recv /
    # request_reply app events + hop_* transport spans) is mirrored to
    # a per-pid JSONL the trace reader can merge with the driver's
    mirror_journal_from_env(f"w{worker_id}")
    journal = get_journal()

    # "engine_ready" mirrors the driver's ready beacon (None until the
    # first beacon arrives — treated as ready so a beacon-less driver
    # degrades to link-up readiness, the pre-beacon contract);
    # "model_info" mirrors the beacon's rollout model block so this
    # worker's /readyz names the active version/digest (ISSUE 14)
    link: Dict[str, Any] = {"engine_ready": None, "model_info": None}
    stop_evt = threading.Event()
    pending: Dict[str, _Pending] = {}
    payloads: Dict[str, Any] = {}   # rid -> payload, kept for re-park
    plock = threading.Lock()
    # worker-local telemetry: what THIS process did with its sockets.
    # Reported to the driver (periodically + on every scrape) so the
    # driver's exposition shows the whole multiprocess topology.
    wstats = StageStats()
    wstats.incr("parked", 0)
    wstats.incr("replied", 0)
    wstats.set_gauge("exchange_link_up", 1.0)
    # /metrics scrape waiters: nonce -> _Pending holding the driver's
    # rendered exposition text
    mwaiters: Dict[str, _Pending] = {}

    def _deliver_binary_replies(buf):
        """One raw-float32 reply block (ISSUE 11): the driver batched a
        whole micro-batch of margins into one frame; unpack, deliver to
        the parked sockets, and answer with ONE batched delivery ack
        instead of a JSON ack per row."""
        try:
            entries = wire.unpack_replies(buf)
        except wire.WireError as e:
            log.warning("worker %d: malformed binary reply block "
                        "dropped: %s", worker_id, e)
            return
        rids, flags = [], []
        for rid, vals in entries:
            # the HTTP egress is JSON regardless — the one conversion
            # happens HERE at the socket owner, not in the driver loop
            v = vals.item() if vals.size == 1 else vals.tolist()
            with plock:
                p = pending.get(rid)
                if p is not None:
                    p.response = v
                    p.status = 200
                    p.event.set()
                pl = payloads.get(rid)
            if p is not None:
                wstats.incr("replied")
            journal.emit("request_reply", rid=rid,
                         tid=_payload_tid(rid, pl), status=200,
                         delivered=p is not None)
            rids.append(rid)
            flags.append(p is not None)
        try:
            # short timeout: this runs ON the read pump (see the JSON
            # ack send below for the rationale)
            client.send(CH_SCORING, {"op": "ack_many", "rids": rids,
                                     "delivered": flags}, timeout=2.0)
        except OSError:
            pass

    def on_message(session, channel, msg, deadline_ms):
        if isinstance(msg, (bytes, memoryview)):
            if channel == CH_SCORING:
                _deliver_binary_replies(msg)
            return
        op = msg.get("op")
        if channel == CH_CONTROL:
            if op == "stop":
                stop_evt.set()
            elif op == "ready":
                # driver readiness beacon → worker /readyz truth; a
                # None value means "no engine check installed" (the
                # beacon only carried model info) and must not flip
                # readiness
                if msg.get("value") is not None:
                    link["engine_ready"] = bool(msg.get("value"))
                if msg.get("model") is not None:
                    link["model_info"] = msg.get("model")
        elif channel == CH_SCORING and op == "reply":
            rid = msg["rid"]
            with plock:
                p = pending.get(rid)
                if p is not None:
                    p.response = msg["response"]
                    p.status = msg.get("status", 200)
                    p.event.set()
                pl = payloads.get(rid)
            if p is not None:
                wstats.incr("replied")
            journal.emit("request_reply", rid=rid,
                         tid=_payload_tid(rid, pl),
                         status=msg.get("status", 200),
                         delivered=p is not None)
            try:
                # short timeout: this runs ON the read pump — blocking
                # on credits here would also block the inbound CREDIT
                # frames that could unblock it.  A dropped ack degrades
                # to reply() reporting undelivered, which is bounded.
                client.send(CH_SCORING, {"op": "ack", "rid": rid,
                                         "delivered": p is not None},
                            timeout=2.0)
            except OSError:
                pass
        elif channel == CH_METRICS and op in ("metrics_txt",
                                              "slo_json",
                                              "statusz_txt"):
            # driver's answer to a /metrics, /slo or /statusz round-trip
            with plock:
                mw = mwaiters.pop(msg.get("req"), None)
            if mw is not None:
                mw.response = (msg.get("report") if op == "slo_json"
                               else msg.get("text"))
                mw.event.set()

    def _payload_tid(rid, payload):
        """A request's trace id in the worker process: the client's
        ``_trace_id`` payload key, else the rid this worker minted —
        the same contract the engine applies driver-side, so both
        journals speak about one request under one id."""
        if isinstance(payload, dict) and payload.get("_trace_id"):
            return str(payload["_trace_id"])
        return str(rid)

    adv = {"host": ""}

    def on_connect(resumed):
        # app hello on EVERY (re)connect: the driver keys the slot on
        # the session, so a duplicate hello is idempotent — and after a
        # session reset it is the required re-introduction.  Then
        # re-park everything still waiting here: ``put_unique`` on the
        # driver dedups rids already queued, the route-restore half is
        # what un-strands requests whose reply failed during the blip.
        try:
            if adv["host"] in ("0.0.0.0", "", "::"):
                # a wildcard bind must not advertise 0.0.0.0: report
                # the interface this worker reaches the exchange
                # through (multi-host dial-ability contract)
                sock = client.session._sock
                if sock is not None:
                    adv["host"] = sock.getsockname()[0]
            client.send(CH_CONTROL, {
                "op": "hello", "worker": worker_id,
                "host": adv["host"], "port": httpd.server_address[1]})
            # first stats beacon NOW, not a full period later: the
            # driver's per-worker `worker_up` gauge must read fresh
            # from the moment the slot joins (a scrape right after
            # start would otherwise show a healthy worker as dark)
            client.send(CH_STATS, {"op": "stats",
                                   "snapshot": wstats.snapshot(),
                                   "fit": current_fit_span()})
            with plock:
                requeue = [(r, payloads[r]) for r in pending
                           if r in payloads]
            for rid, payload in requeue:
                client.send(CH_SCORING,
                            {"op": "park", "rid": rid,
                             "payload": payload},
                            tc={"tid": _payload_tid(rid, payload)})
        except OSError:
            pass   # link died instantly — the next reconnect retries

    class Handler(_ServingHandler):
        timeout = request_read_timeout   # slow-client read deadline

        def _ready(self):
            # session up AND the driver's engine (if it beacons
            # readiness over the exchange) has not declared itself down
            return (client.connected
                    and link["engine_ready"] is not False)

        def _model_info(self):
            # the driver's rollout model block, as last beaconed
            return link.get("model_info")

        def _metrics(self):
            # the engine (and its StageStats) lives in the DRIVER
            # process — a scrape of this worker asks the driver for the
            # whole-topology exposition over the exchange session,
            # carrying this worker's local stats along so the driver's
            # view is fresh.  Link down / driver silent -> degrade to a
            # worker-local render rather than a 503 (a half-scrape
            # beats none during an exchange blip).
            if not client.connected:
                return _local_metrics()
            nonce = uuid.uuid4().hex
            waiter = _Pending()
            with plock:
                mwaiters[nonce] = waiter
            try:
                client.send(CH_METRICS,
                            {"op": "metrics_req", "req": nonce,
                             "stats": wstats.snapshot()},
                            deadline_ms=5000)
            except OSError:
                with plock:
                    mwaiters.pop(nonce, None)
                return _local_metrics()
            if not waiter.event.wait(5.0):
                with plock:
                    mwaiters.pop(nonce, None)
                return _local_metrics()
            return waiter.response

        def _slo(self):
            # like /metrics: the scoring counters the SLO objectives
            # read live in the DRIVER process, so a worker's /slo does
            # one exchange round-trip; link down / driver silent
            # degrades to the worker-local monitor (its transport
            # objectives still evaluate) instead of a 503
            from ..core.slo import get_monitor
            if not client.connected:
                return get_monitor().report()
            nonce = uuid.uuid4().hex
            waiter = _Pending()
            with plock:
                mwaiters[nonce] = waiter
            try:
                client.send(CH_METRICS,
                            {"op": "slo_req", "req": nonce},
                            deadline_ms=5000)
            except OSError:
                with plock:
                    mwaiters.pop(nonce, None)
                return get_monitor().report()
            if not waiter.event.wait(5.0):
                with plock:
                    mwaiters.pop(nonce, None)
                return get_monitor().report()
            return waiter.response

        def _statusz(self):
            # the fleet-wide status page (SLO burn, headroom, worker
            # liveness) is assembled in the DRIVER process — one
            # exchange round-trip like /slo; link down / driver silent
            # degrades to this worker's local summary
            from ..core.capacity import render_statusz
            local = lambda: render_statusz(  # noqa: E731
                model_info=link.get("model_info"))
            if not client.connected:
                return local()
            nonce = uuid.uuid4().hex
            waiter = _Pending()
            with plock:
                mwaiters[nonce] = waiter
            try:
                client.send(CH_METRICS,
                            {"op": "statusz_req", "req": nonce},
                            deadline_ms=5000)
            except OSError:
                with plock:
                    mwaiters.pop(nonce, None)
                return local()
            if not waiter.event.wait(5.0):
                with plock:
                    mwaiters.pop(nonce, None)
                return local()
            return waiter.response

        def do_POST(self):
            if api_path not in ("/", self.path):
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(
                    self.rfile.read(length).decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                self.send_error(400, "invalid JSON")
                return
            rid = uuid.uuid4().hex
            p = _Pending()
            with plock:
                pending[rid] = p
                payloads[rid] = payload
            wstats.incr("parked")
            tid = _payload_tid(rid, payload)
            journal.emit("request_recv", rid=rid, tid=tid,
                         worker=worker_id)
            # deadline propagation: a client-declared budget rides the
            # frame header so the driver can 504 dead work unscored
            dl = payload.get("_deadline_ms") \
                if isinstance(payload, dict) else None
            dl = dl if isinstance(dl, (int, float)) and dl > 0 else None
            # raw-float32 park (ISSUE 11): a plain features-vector
            # request on a binary-negotiated session ships as ONE
            # packed float32 row — no JSON re-encode on this hop.
            # Anything richer (explicit _trace_id, extra keys, ragged
            # vectors) takes the negotiated JSON fallback below.
            sent = False
            # a _deadline_ms the header cannot carry AT ALL (a
            # string-typed or non-positive value the ENGINE would still
            # parse from the payload) keeps the JSON wire.  Note the
            # carried semantics intentionally differ in one way: the
            # header deadline is the REMAINING budget at frame-send
            # time (decremented by worker-side queueing/replay — the
            # transport's propagation contract), while the JSON
            # payload key keeps the original budget; the binary wire
            # is therefore the stricter of the two, never the looser.
            if (client.session.peer_binary and isinstance(payload, dict)
                    and "features" in payload
                    and set(payload) <= {"features", "_deadline_ms"}
                    and ("_deadline_ms" not in payload
                         or dl is not None)):
                try:
                    row = np.asarray(payload["features"],
                                     dtype=np.float32)
                    if row.ndim == 1 and row.size:
                        client.session.send_bytes(
                            CH_SCORING,
                            wire.pack_matrix(rid, row.reshape(1, -1)),
                            deadline_ms=dl)
                        sent = True
                except (TypeError, ValueError):
                    sent = False         # undecodable: JSON carries it
                except OSError:
                    sent = True          # session closed; same exposure
                    #                      bound as the JSON path below
            if not sent:
                try:
                    client.send(CH_SCORING,
                                {"op": "park", "rid": rid,
                                 "payload": payload},
                                deadline_ms=dl,
                                tc={"tid": tid})
                except OSError:
                    # session closed for good; the wait below bounds
                    # the client's exposure (a mere blip queues the
                    # frame for replay instead of landing here)
                    pass
            ok = p.event.wait(reply_timeout)
            with plock:
                # atomic here, where the socket lives: once popped, a
                # racing reply acks delivered=False and the driver
                # reports the timeout truthfully
                p2 = pending.pop(rid, None)
                payloads.pop(rid, None)
            delivered = p2 is not None and p2.event.is_set()
            if not delivered and not ok:
                try:
                    client.send(CH_SCORING, {"op": "expire",
                                             "rid": rid})
                except OSError:
                    pass   # session gone — the route dies with it
                self.send_error(504, "pipeline timeout")
                return
            body = json.dumps(p.response).encode("utf-8")
            self.send_response(p.status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    def _local_metrics():
        # degraded scrape: this worker's own stats only, flagged so a
        # dashboard can tell a partial exposition from a healthy one
        return (render_prometheus({"worker_local": wstats.snapshot()})
                + "# driver unreachable: worker-local metrics only\n")

    httpd = _QuietThreadingHTTPServer((http_host, 0), Handler)
    adv["host"] = httpd.server_address[0]
    base, cap = reconnect_backoff
    client = TransportClient(
        (driver_host, driver_port), token=token,
        cfg=TransportConfig(reconnect_tries=reconnect_tries,
                            reconnect_backoff=(base, cap)),
        on_message=on_message, on_connect=on_connect,
        on_down=lambda: stop_evt.set(),   # budget exhausted: shut down
        name=f"exchange-worker{worker_id}")
    try:
        client.connect()
    except OSError:
        httpd.server_close()
        raise
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def stats_beacon():
        # periodic worker-stats report: keeps the driver's per-worker
        # blocks fresh so a scrape against ANY server (or the driver's
        # own render_metrics()) sees every worker, not just the one
        # being scraped.  Best-effort, and only while the session is
        # up — beacons must not burn replay credits during an outage.
        while not stop_evt.wait(1.0):
            wstats.set_gauge("exchange_link_up",
                             1.0 if client.connected else 0.0)
            if not client.connected:
                continue
            try:
                # the beacon names the fit span this process is inside
                # (None outside training) — the trace reader can tie a
                # worker's stats to the fit they served under
                payload = {"op": "stats",
                           "snapshot": wstats.snapshot(),
                           "fit": current_fit_span()}
                # drift sketches ride the same beacon (ISSUE 15): the
                # driver key-wise sums the counters across workers —
                # cross-process sketch merging through the metrics
                # scrape, exactly like StageStats
                from ..core.drift import peek_drift_monitor
                dm = peek_drift_monitor()
                if dm is not None:
                    payload["drift"] = dm.snapshot()
                # the saturation block rides the same beacon (ISSUE
                # 20): per-worker headroom/busy gauges merge into the
                # driver scrape under the gauge merge policy
                from ..core.capacity import peek_capacity_monitor
                cm = peek_capacity_monitor()
                if cm is not None:
                    payload["capacity"] = cm.snapshot()
                client.send(CH_STATS, payload)
            except OSError:
                pass

    threading.Thread(target=stats_beacon, name="worker-stats-beacon",
                     daemon=True).start()

    stop_evt.wait()
    httpd.shutdown()
    httpd.server_close()
    client.close()


class MultiprocessHTTPServer:
    """N worker HTTP servers as SEPARATE OS PROCESSES over one TCP
    exchange — the cross-process topology of the reference's
    DistributedHTTPSource, where each executor process accepts requests
    and replies route back to the process holding the socket
    (SURVEY.md §3.4).  Driver-facing API is identical to
    :class:`DistributedHTTPServer` (start/stop/addresses/get_batch/
    reply), so the same micro-batch loop drives either topology.

    With ``spawn_workers=False`` nothing is forked: the exchange waits
    for ``num_workers`` REMOTE workers to dial in via
    :func:`join_exchange` — the multi-HOST deployment, each machine
    running one worker next to its accelerator (the reference's
    per-executor HTTP server).  Pass ``host="0.0.0.0"`` so remote
    workers can reach the exchange; ``exchange_address`` is the
    ``host:port`` to hand them, along with the ``token`` shared secret
    each ``join_exchange`` must present (auto-generated unless given).

    The exchange runs on :mod:`mmlspark_tpu.io.transport` — ONE framed,
    CRC-checked, flow-controlled, resumable transport multiplexing the
    scoring channel (park/reply/expire/ack), the worker stats beacons,
    the ``/metrics`` scrape round-trips and session control.  The
    transport handshake enforces the token before any state is touched
    (non-protocol and wrong-token peers are dropped at the preamble;
    security posture: docs/transport.md §Security).

    Failure handling (the reference's executor-loss story applied to
    serving): a link BLIP is invisible above the transport — the worker
    reconnects with jittered backoff, the session resumes, and unacked
    frames replay with sequence dedup (no lost, no duplicated
    messages).  A session that dies for good (worker crash, resume
    grace expired, respawn takeover) purges the worker's reply routes
    (so replies report undelivered immediately instead of hanging),
    releases its ack waiters, and reopens its worker slot for a fresh
    hello.  With ``supervise_workers=True`` (spawned topology) a dead
    worker PROCESS is respawned automatically; its parked client
    sockets died with it (those clients see a reset and retry), but
    capacity and readiness recover without operator action.
    ``self.counters`` tracks ``worker_deaths`` / ``worker_respawns``.

    Every timeout is constructor-level config so drills and tests can
    tighten them: ``request_read_timeout`` (worker HTTP slow-client
    deadline), ``preauth_timeout`` (transport handshake deadline),
    ``ack_grace`` (reply-ack wait beyond ``reply_timeout``),
    ``reconnect_tries``/``reconnect_backoff`` (worker session re-dial),
    ``sweep_grace`` (orphaned route sweep slack), and
    ``transport_config`` (frame/flow/keepalive/resume knobs, including
    the chaos ``socket_wrap`` hook).
    """

    _SWEEP_EVERY = 512

    #: the scoring engine reads this: replies may stay numpy (sliced
    #: straight off the margin ndarray) — this exchange serializes them
    #: per session: a raw-float32 block on binary-negotiated sessions,
    #: the JSON fallback otherwise (ISSUE 11)
    binary_wire = True

    def __init__(self, num_workers: int = 2, host: str = "127.0.0.1",
                 api_path: str = "/", reply_timeout: float = 30.0,
                 spawn_workers: bool = True, join_timeout: float = 20.0,
                 token: Optional[str] = None,
                 request_read_timeout: float = 30.0,
                 preauth_timeout: float = 30.0,
                 ack_grace: float = 5.0,
                 reconnect_tries: int = 5,
                 reconnect_backoff: Tuple[float, float] = (0.1, 2.0),
                 supervise_workers: bool = True,
                 sweep_grace: float = 10.0,
                 transport_config: Optional[Any] = None):
        import dataclasses
        import secrets

        from .transport import TransportConfig, TransportServer

        self.token = secrets.token_hex(16) if token is None else token
        tcfg = transport_config or TransportConfig()
        # exchange-level timeouts override the transport defaults so
        # ONE knob set governs the whole topology
        tcfg = dataclasses.replace(
            tcfg, preauth_timeout_s=preauth_timeout,
            reconnect_tries=reconnect_tries,
            reconnect_backoff=reconnect_backoff)
        self._ts = TransportServer(
            host, 0, token=self.token, cfg=tcfg,
            on_message=self._on_transport_msg,
            on_session_lost=self._on_session_lost, name="exchange")
        self.queue: _TrackedQueue = _TrackedQueue()
        # rid -> (session id, monotonic park time, trace id); the stamp
        # bounds how long an orphaned route can leak (_sweep_routes);
        # the trace id lets the reply frame carry the request's trace
        # context back through the worker hop
        self._route: Dict[str, Tuple[str, float, str]] = {}
        self._acks: Dict[str, Tuple[_Pending, str]] = {}  # rid -> waiter
        self._lock = threading.Lock()
        self._slot_sid: Dict[int, str] = {}   # worker slot -> session id
        self.addresses: List[str] = [""] * num_workers
        self.counters = {"worker_deaths": 0, "worker_respawns": 0}
        # telemetry: the exchange's own StageStats mirror of `counters`
        # (registered under "serving_exchange" at start()) plus the
        # per-worker snapshots the worker processes beacon over the
        # link — render_metrics() turns all of it into one exposition
        self.stats = StageStats()
        for _k in ("worker_deaths", "worker_respawns"):
            self.stats.incr(_k, 0)
        self.worker_stats: Dict[int, dict] = {}
        # per-worker drift-sketch snapshots (ISSUE 15): workers whose
        # scoring engine carries a DriftMonitor piggyback its
        # StageStats-shaped block on the stats beacon; render_metrics
        # merges them (counters SUM = the merged sketch, gauges take
        # the worst arm) into one ns="drift" block
        self.worker_drift: Dict[int, dict] = {}
        # per-worker saturation blocks (ISSUE 20): capacity monitors
        # piggyback their headroom/busy gauges on the stats beacon;
        # render_metrics merges them (depth gauges SUM, levels take
        # the worst arm) into one ns="capacity" view
        self.worker_capacity: Dict[int, dict] = {}
        # worker slot -> monotonic instant of its last stats beacon (or
        # scrape piggyback): the per-worker `worker_up` gauge ages from
        # here, so a silent worker is visible from ONE scrape
        self._beacon_seen: Dict[int, float] = {}
        #: beacon age beyond which a worker's `worker_up` gauge reads 0
        #: (3x the 1 s beacon period + slack)
        self.beacon_stale_s = 4.0
        # the scoring engine installs its liveness check here; the
        # beacon thread broadcasts it to worker processes so their
        # /readyz reflects ENGINE readiness, not just link liveness
        self.ready_check: Optional[Callable[[], bool]] = None
        # rollout model info (ISSUE 14): the driver-side controller
        # installs model_info() here; the ready beacon carries it to
        # every worker process so THEIR /readyz names the active
        # model version/digest too
        self.model_info_provider: Optional[Callable[[], dict]] = None
        self._reply_timeout = reply_timeout
        self._join_timeout = join_timeout
        self._request_read_timeout = request_read_timeout
        self._preauth_timeout = preauth_timeout
        self._ack_grace = ack_grace
        self._reconnect_tries = reconnect_tries
        self._reconnect_backoff = reconnect_backoff
        self._supervise_workers = bool(supervise_workers)
        self._sweep_grace = sweep_grace
        self._parks = 0
        self._host = host
        self._api_path = api_path
        self._closing = threading.Event()
        self._proc_supervisor: Optional[threading.Thread] = None
        self._ready_beacon: Optional[threading.Thread] = None

        self._procs = []
        self._spawn_workers = spawn_workers
        if spawn_workers:
            self._procs = [self._make_proc(i)
                           for i in range(num_workers)]

    def _make_proc(self, worker_id: int):
        import multiprocessing as mp
        ctx = mp.get_context("spawn")  # no inherited jax/thread state
        dh, dp = self._ts.address
        return ctx.Process(
            target=_mp_worker_main,
            args=(dh, dp, worker_id, self._host, self._api_path,
                  self._reply_timeout, self.token,
                  self._request_read_timeout, self._reconnect_tries,
                  self._reconnect_backoff),
            daemon=True)

    @property
    def exchange_address(self) -> str:
        """``host:port`` remote workers dial via :func:`join_exchange`.
        A wildcard bind advertises this machine's primary outbound
        interface, not ``0.0.0.0`` — the same dial-ability rule the
        workers follow for their own hello addresses."""
        import socket as _socket
        h, p = self._ts.address
        if h in ("0.0.0.0", "", "::"):
            probe = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
            try:
                # UDP connect sends nothing; it just resolves the route
                probe.connect(("10.255.255.255", 1))
                h = probe.getsockname()[0]
            except OSError:
                try:
                    h = _socket.gethostbyname(_socket.gethostname())
                except OSError:
                    h = "127.0.0.1"
            finally:
                probe.close()
        return f"{h}:{p}"

    def start(self) -> "MultiprocessHTTPServer":
        for p in self._procs:
            p.start()
        import time
        # The transport server authenticates and pumps every
        # connection; this loop only waits for the APP-LEVEL hellos
        # that fill the worker slots.  Garbage, wrong-token and
        # invalid-id peers never consume a slot (the handshake drops
        # them before any exchange state exists).  Budgets: 60 s for
        # spawned workers (a loaded single-core host can take >20 s
        # just to spawn and import N interpreters), join_timeout for
        # external ones.
        self._ts.start()
        budget = 60.0 if self._procs else self._join_timeout
        deadline = time.monotonic() + budget
        while (any(not a for a in self.addresses)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        if any(not a for a in self.addresses):
            missing = [i for i, a in enumerate(self.addresses) if not a]
            xaddr = self.exchange_address  # before stop() closes it
            saw_peer = bool(self._ts.sessions)
            self.stop()
            if self._procs and not saw_peer:
                raise RuntimeError(
                    "worker processes failed to connect; if this is "
                    "a script, MultiprocessHTTPServer must be "
                    "started under `if __name__ == '__main__':` "
                    "(spawn re-imports the main module)")
            raise RuntimeError(
                f"worker slots {missing} never joined {xaddr} within "
                f"{budget}s: start one join_exchange(...) per slot with "
                f"a unique id in [0, {len(self.addresses)}) and this "
                f"server's .token (invalid ids and missing or wrong "
                f"tokens are dropped and land here; a duplicate id "
                f"takes over its slot)")
        if self._procs and self._supervise_workers:
            self._proc_supervisor = threading.Thread(
                target=self._supervise_procs, name="worker-supervisor",
                daemon=True)
            self._proc_supervisor.start()
        self._ready_beacon = threading.Thread(
            target=self._beacon_loop, name="ready-beacon", daemon=True)
        self._ready_beacon.start()
        get_registry().register("serving_exchange", self.stats)
        return self

    def render_metrics(self) -> str:
        """One Prometheus exposition for the whole multiprocess
        topology: the driver's registry (scoring engine, train stats,
        this exchange's own counters) plus each worker's last-reported
        stats under ``ns="worker<N>"`` and their aggregate under
        ``ns="workers"``.  EVERY slot appears, beaconing or not: a
        ``worker_up`` gauge (1 while the slot's beacons are fresh, 0
        for a silent/dead/never-joined worker — ``_up`` suffix, so the
        ``workers`` aggregate takes the MIN and one dark worker shows
        there too) and a ``last_beacon_age_ms`` gauge make a silent
        worker visible from ONE scrape instead of requiring a
        dashboard diff against the slot count."""
        from ..core.slo import get_monitor
        get_monitor()   # slo families ride every topology scrape
        now = time.monotonic()
        with self._lock:
            # copy the gauges level too: the synthetic worker_up /
            # beacon-age gauges are inserted below OUTSIDE the lock,
            # and a shallow dict(s) would mutate the stored snapshot a
            # concurrent scrape (HTTP thread vs transport pump) is
            # iterating
            per_worker = {
                w: {**s, "gauges": dict(s.get("gauges") or {})}
                for w, s in self.worker_stats.items()}
            worker_drift = list(self.worker_drift.values())
            worker_cap = list(self.worker_capacity.values())
            seen = dict(self._beacon_seen)
        for w in range(len(self.addresses)):
            snap = per_worker.setdefault(
                w, {"rows": 0, "rows_per_s": 0.0, "counters": {},
                    "gauges": {}, "stages": {}})
            gauges = snap.setdefault("gauges", {})
            age_s = (now - seen[w]) if w in seen else float("inf")
            gauges["worker_up"] = \
                1.0 if age_s <= self.beacon_stale_s else 0.0
            gauges["last_beacon_age_ms"] = (
                round(age_s * 1e3, 1) if age_s != float("inf")
                else float("inf"))
        extra = {f"worker{w}": snap
                 for w, snap in sorted(per_worker.items())}
        if per_worker:
            extra["workers"] = merge_snapshots(per_worker.values())
        if worker_drift:
            # merged drift sketches for the whole topology: counter
            # sums ARE the concatenated-rows sketch (ISSUE 15); the
            # driver's own monitor (if any) joins the merge
            from ..core.drift import peek_drift_monitor
            dm = peek_drift_monitor()
            blocks = worker_drift + ([dm.snapshot()]
                                     if dm is not None else [])
            extra["drift"] = merge_snapshots(blocks)
        # merged saturation view: the gauge merge policy (min for
        # *_up, sum for *_depth/*_inflight, max otherwise) makes the
        # fold meaningful — total queued work sums, worst headroom
        # dominates (ISSUE 20)
        from ..core.capacity import peek_capacity_monitor
        cm = peek_capacity_monitor()
        cap_blocks = worker_cap + ([cm.snapshot()]
                                   if cm is not None else [])
        if cap_blocks:
            extra["capacity"] = merge_snapshots(cap_blocks)
        return get_registry().render_prometheus(extra=extra)

    def render_statusz(self) -> str:
        """Topology-wide ``/statusz``: the capacity module's operator
        page plus per-slot worker liveness from the beacon ages — the
        one-glance saturation answer for the whole serving fleet."""
        from ..core.capacity import render_statusz
        now = time.monotonic()
        with self._lock:
            seen = dict(self._beacon_seen)
            n = len(self.addresses)
        workers = {}
        for w in range(n):
            age_s = (now - seen[w]) if w in seen else float("inf")
            workers[f"worker{w}"] = {
                "up": age_s <= self.beacon_stale_s,
                "beacon_age_s": round(age_s, 3)}
        info = None
        if self.model_info_provider is not None:
            try:
                info = self.model_info_provider()
            except Exception:  # noqa: BLE001 - advisory block
                info = None
        return render_statusz(model_info=info, workers=workers)

    def _beacon_loop(self) -> None:
        """Broadcast the installed ``ready_check`` verdict to every
        slotted worker so worker-process ``/readyz`` tells the truth
        about the ENGINE, not just the exchange link.  No check
        installed → no beacons → workers fall back to link-up
        readiness."""
        while not self._closing.wait(0.5):
            check = self.ready_check
            info_provider = self.model_info_provider
            if check is None and info_provider is None:
                continue
            r = None
            if check is not None:
                try:
                    r = bool(check())
                except Exception:  # noqa: BLE001
                    r = False
            msg = {"op": "ready", "value": r}
            if info_provider is not None:
                try:
                    msg["model"] = info_provider()
                except Exception:  # noqa: BLE001 - advisory block
                    pass
            for session in self._worker_sessions():
                try:
                    session.send(CH_CONTROL, msg, timeout=0.5)
                except OSError:
                    pass   # dying link: the transport handles it

    def _worker_sessions(self) -> List[Any]:
        """Connected sessions currently holding a worker slot."""
        with self._lock:
            sids = list(self._slot_sid.values())
        out = []
        for sid in sids:
            s = self._ts.sessions.get(sid)
            if s is not None and s.connected:
                out.append(s)
        return out

    def _supervise_procs(self) -> None:
        """Spawned-worker supervision: a dead worker PROCESS is
        respawned into its slot (the reader-death purge already freed
        the slot and failed its in-flight replies).  The respawn binds
        a fresh HTTP port — ``addresses`` updates on its hello, so
        callers should re-read it rather than cache."""
        while not self._closing.wait(0.5):
            for i, p in enumerate(self._procs):
                if p.is_alive() or self._closing.is_set():
                    continue
                log.warning("serving: worker process %d died "
                            "(exitcode %s); respawning", i, p.exitcode)
                self.counters["worker_respawns"] += 1
                self.stats.incr("worker_respawns")
                # flight record BEFORE the respawn overwrites state:
                # the journal tail + metrics + thread stacks at the
                # moment the death was noticed are the post-mortem
                record_flight("serving_worker_death",
                              {"worker": i, "exitcode": p.exitcode,
                               "pid": p.pid})
                newp = self._make_proc(i)
                self._procs[i] = newp
                newp.start()

    def _on_transport_msg(self, session, channel: int, msg: dict,
                          deadline_ms) -> None:
        """App-protocol dispatch for one authenticated exchange
        session.  The transport already enforced magic/version/token,
        framing, CRC and sequencing — by the time a message lands here
        it is a well-formed JSON object from a tokened peer, or a raw
        binary scoring payload (FLAG_BINARY frame) this method routes
        to the zero-copy park path."""
        if isinstance(msg, (bytes, memoryview)):
            self._on_binary_scoring(session, channel, msg, deadline_ms)
            return
        op = msg.get("op")
        if channel == CH_CONTROL and op == "hello":
            self._on_worker_hello(session, msg)
        elif channel == CH_SCORING:
            if op == "park":
                rid, payload = msg["rid"], msg["payload"]
                # deadline propagation: a frame-header deadline becomes
                # the engine's per-request budget unless the payload
                # already carries an explicit one
                if (deadline_ms and isinstance(payload, dict)
                        and "_deadline_ms" not in payload):
                    payload["_deadline_ms"] = deadline_ms
                tid = str(rid)
                if isinstance(payload, dict) \
                        and payload.get("_trace_id"):
                    tid = str(payload["_trace_id"])
                with self._lock:
                    self._route[rid] = (session.sid, time.monotonic(),
                                        tid)
                    self._parks += 1
                    if self._parks % self._SWEEP_EVERY == 0:
                        self._sweep_routes_locked()
                # put_unique: a reconnect re-park whose first copy is
                # still queued only restores the route (above) — it
                # must not enqueue a second copy to be scored twice
                self.queue.put_unique((rid, payload,
                                       time.perf_counter()))
            elif op == "expire":
                with self._lock:
                    self._route.pop(msg["rid"], None)
            elif op == "ack":
                with self._lock:
                    entry = self._acks.pop(msg["rid"], None)
                if entry is not None:
                    waiter = entry[0]
                    waiter.response = msg["delivered"]
                    waiter.event.set()
            elif op == "ack_many":
                # batched delivery ack answering a binary reply block:
                # one frame resolves the whole micro-batch's waiters
                resolved = []
                with self._lock:
                    for rid, d in zip(msg.get("rids") or (),
                                      msg.get("delivered") or ()):
                        entry = self._acks.pop(rid, None)
                        if entry is not None:
                            resolved.append((entry[0], bool(d)))
                for waiter, d in resolved:
                    waiter.response = d
                    waiter.event.set()
        elif channel == CH_STATS and op == "stats":
            # periodic worker-stats beacon: keep the last-known
            # snapshot per WORKER SLOT (not session) so the
            # whole-topology exposition names stable workers
            with self._lock:
                w = session.meta.get("worker")
                if w is not None and isinstance(msg.get("snapshot"),
                                                dict):
                    self.worker_stats[w] = msg["snapshot"]
                    self._beacon_seen[w] = time.monotonic()
                if w is not None and isinstance(msg.get("drift"),
                                                dict):
                    self.worker_drift[w] = msg["drift"]
                if w is not None and isinstance(msg.get("capacity"),
                                                dict):
                    self.worker_capacity[w] = msg["capacity"]
        elif channel == CH_METRICS and op == "metrics_req":
            # a /metrics scrape hit this worker: fold its piggybacked
            # stats in, render the WHOLE topology (driver registry +
            # every worker's last report + aggregated totals), and
            # answer the round-trip
            with self._lock:
                w = session.meta.get("worker")
                if w is not None and isinstance(msg.get("stats"), dict):
                    self.worker_stats[w] = msg["stats"]
                    self._beacon_seen[w] = time.monotonic()
            try:
                text = self.render_metrics()
            except Exception:  # noqa: BLE001 - scrape must degrade
                log.exception("serving: metrics render failed")
                text = "# metrics render failed\n"
            try:
                # short timeout: this runs ON the read pump (see the
                # worker-side ack send for the rationale); a dropped
                # scrape answer degrades to the worker's local render
                session.send(CH_METRICS, {"op": "metrics_txt",
                                          "req": msg.get("req"),
                                          "text": text}, timeout=2.0)
            except OSError:
                pass   # dying link: the transport handles the purge
        elif channel == CH_METRICS and op == "slo_req":
            # a /slo probe hit a worker: evaluate the driver's monitor
            # (the scoring counters live here) and answer
            from ..core.slo import get_monitor
            try:
                report = get_monitor().report()
            except Exception:  # noqa: BLE001 - probe must degrade
                log.exception("serving: slo evaluation failed")
                report = {"error": "slo evaluation failed"}
            try:
                session.send(CH_METRICS, {"op": "slo_json",
                                          "req": msg.get("req"),
                                          "report": report},
                             timeout=2.0)
            except OSError:
                pass
        elif channel == CH_METRICS and op == "statusz_req":
            # a /statusz probe hit a worker: the authoritative view
            # (burn states, headroom, fleet liveness) lives on the
            # driver — render here and answer
            try:
                text = self.render_statusz()
            except Exception:  # noqa: BLE001 - probe must degrade
                log.exception("serving: statusz render failed")
                text = "statusz render failed\n"
            try:
                session.send(CH_METRICS, {"op": "statusz_txt",
                                          "req": msg.get("req"),
                                          "text": text}, timeout=2.0)
            except OSError:
                pass

    def _on_binary_scoring(self, session, channel: int, buf,
                           deadline_ms) -> None:
        """Zero-copy park: a raw-float32 scoring request
        (io/wire.py preamble + packed row block) lands on the queue as
        a float32 view — no JSON, no per-value Python objects.  A
        malformed preamble costs exactly ONE request (a per-row 400
        when the rid is recoverable), never the connection — the same
        blast-radius contract the JSON decode path gives."""
        def refuse(rid):
            # the per-request 400 of the blast-radius contract: one
            # bad payload costs ONE request, never the connection
            if not rid:
                return
            try:
                session.send(CH_SCORING,
                             {"op": "reply", "rid": rid,
                              "response": {"error": "bad request"},
                              "status": 400}, timeout=2.0)
            except OSError:
                pass

        if channel != CH_SCORING:
            log.warning("serving: unexpected binary payload on "
                        "channel %d dropped", channel)
            return
        try:
            kind, rid, X = wire.unpack_matrix(buf)
        except wire.WireError as e:
            rid = wire.peek_rid(buf)
            log.warning("serving: malformed binary scoring payload "
                        "(%s); %s", e,
                        f"400ing request {rid[:8]}" if rid
                        else "rid unrecoverable, dropping")
            refuse(rid)
            return
        if kind != wire.K_REQ:
            log.warning("serving: unexpected binary payload kind %d "
                        "dropped", kind)
            return
        if X.shape[0] != 1:
            # the exchange park contract is ONE row per request id —
            # the engine maps one decoded row to one batch entry, so a
            # multi-row block under a single rid would misalign scores
            # across co-batched requests.  Multi-row matrices are the
            # FLEET protocol (io/fleet.py).
            log.warning("serving: %d-row binary park %s rejected "
                        "(one row per request)", X.shape[0], rid[:8])
            refuse(rid)
            return
        payload = (wire.BinaryReq(X, deadline_ms) if deadline_ms
                   else X)
        with self._lock:
            self._route[rid] = (session.sid, time.monotonic(),
                                str(rid))
            self._parks += 1
            if self._parks % self._SWEEP_EVERY == 0:
                self._sweep_routes_locked()
        self.queue.put_unique((rid, payload, time.perf_counter()))

    def _on_worker_hello(self, session, msg: dict) -> None:
        w = msg.get("worker")
        if (not isinstance(w, int)
                or not 0 <= w < len(self.addresses)):
            log.warning("serving: ignoring hello with invalid "
                        "worker id %r (need 0..%d)", w,
                        len(self.addresses) - 1)
            return
        # newest-wins slot claim: a hello for an occupied slot from a
        # DIFFERENT session means the worker process was respawned (or
        # re-dialed before its old session's loss was declared).  The
        # new session takes the slot; the old one is dropped and its
        # routes purged WITHOUT counting a worker death twice —
        # clearing its slot claim first means its teardown cannot wipe
        # the live worker's address.  A re-hello on the SAME session
        # (reconnect after a session reset, or the routine re-hello on
        # every resume) is idempotent.
        stale_sid = None
        with self._lock:
            old_sid = self._slot_sid.get(w)
            if old_sid is not None and old_sid != session.sid:
                log.warning("serving: worker slot %d re-helloed on a "
                            "new session; replacing the stale one", w)
                stale_sid = old_sid
                old_sess = self._ts.sessions.get(old_sid)
                if old_sess is not None:
                    old_sess.meta.pop("worker", None)
            self._slot_sid[w] = session.sid
            session.meta["worker"] = w
        self.addresses[w] = f"http://{msg['host']}:{msg['port']}"
        if stale_sid is not None:
            self._ts.drop_session(stale_sid, notify=False)
            self._purge_session(stale_sid)

    def _on_session_lost(self, session) -> None:
        """A session died for good (resume grace expired, peer CLOSEd,
        or an explicit drop): purge its routes so replies report
        undelivered immediately, release its ack waiters, and reopen
        its worker slot for a fresh hello — the surviving workers keep
        serving (the reference's executor-loss story, SURVEY.md §5.3
        applied to serving).  Requests from this worker still in
        ``self.queue`` score normally; their replies find no route and
        report undelivered."""
        held_slot = False
        with self._lock:
            w = session.meta.get("worker")
            if w is not None and self._slot_sid.get(w) == session.sid:
                self._slot_sid.pop(w, None)
                if 0 <= w < len(self.addresses):
                    self.addresses[w] = ""   # slot freed for rejoin
                held_slot = True
        self._purge_session(session.sid)
        if held_slot and not self._closing.is_set():
            # only a session that actually HELD a worker slot counts as
            # a worker death — an authed peer with an invalid or
            # superseded hello never represented capacity
            self.counters["worker_deaths"] += 1
            self.stats.incr("worker_deaths")

    def _purge_session(self, sid: str) -> None:
        """Drop every route and ack waiter still pointing at ``sid``."""
        with self._lock:
            for r in [r for r, entry in self._route.items()
                      if entry[0] == sid]:
                self._route.pop(r, None)
            dead_acks = [r for r, (_, s) in self._acks.items()
                         if s == sid]
            waiters = [self._acks.pop(r)[0] for r in dead_acks]
        for waiter in waiters:
            waiter.response = False
            waiter.event.set()

    def _sweep_routes_locked(self) -> None:
        """Drop routes whose worker-side handler must be gone: a live
        handler expires its rid at ``reply_timeout``; entries older
        than twice that (+ grace) mean the expire never arrived (wedged
        worker handler thread).  Called under ``self._lock``."""
        horizon = time.monotonic() - (2 * self._reply_timeout
                                      + self._sweep_grace)
        stale = [r for r, entry in self._route.items()
                 if entry[1] < horizon]
        for r in stale:
            del self._route[r]
        if stale:
            log.warning("serving: swept %d orphaned reply routes",
                        len(stale))

    @property
    def request_queue(self) -> "queue.Queue[Tuple[str, Any, float]]":
        return self.queue

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        """Micro-batch pull as legacy ``(rid, payload)`` 2-tuples; the
        enqueue stamps stay on the raw queue for the scoring engine."""
        batch: List[Tuple[str, Any]] = []
        try:
            batch.append(self.queue.get(timeout=timeout)[:2])
            while len(batch) < max_rows:
                batch.append(self.queue.get_nowait()[:2])
        except queue.Empty:
            pass
        return batch

    def _reply_session(self, rid: str):
        """Pop the route for ``rid`` and return ``(live session, trace
        id)``, or ``(None, None)``.  A session that is down RIGHT NOW
        reports undelivered immediately (the old fail-fast contract):
        if the worker is merely mid-blip it re-parks the request on
        resume and the engine scores it again — at-least-once scoring,
        with exactly-once CLIENT delivery still decided atomically by
        the socket owner."""
        with self._lock:
            entry = self._route.pop(rid, None)
        if entry is None:
            return None, None
        session = self._ts.sessions.get(entry[0])
        if session is None or not session.connected:
            return None, None
        return session, entry[2]

    @staticmethod
    def _binary_value_ok(v) -> bool:
        """Can this reply value ride the raw-float32 block?  Only
        values that are ALREADY float32 (the predictor hot path's
        margin dtype) — anything wider (python floats, float64
        transform columns) or integer would be silently narrowed, so
        those keep the exact JSON path, as do error dicts, strings and
        object columns."""
        if isinstance(v, (np.ndarray, np.generic)):
            a = np.asarray(v)
            # size cap mirrors the wire's u16 n_values field, so the
            # pack cannot fail after classification
            return a.dtype == np.float32 and a.size <= 0xFFFF
        return False

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        """Route a reply to the worker PROCESS holding the socket; blocks
        on that worker's delivered/undelivered ack (the socket owner
        decides atomically, so a reply racing the worker-side timeout
        reports exactly what the client saw)."""
        session, tid = self._reply_session(request_id)
        if session is None:
            return False
        waiter = _Pending()
        with self._lock:
            self._acks[request_id] = (waiter, session.sid)
        try:
            sent_binary = False
            if (status == 200 and session.peer_binary
                    and self._binary_value_ok(response)):
                try:
                    session.send_bytes(
                        CH_SCORING,
                        wire.pack_replies([(request_id, response)]))
                    sent_binary = True
                except ValueError:
                    # a value that refuses to pack (e.g. >u16 floats)
                    # falls back to the JSON frame, like reply_many
                    sent_binary = False
            if not sent_binary:
                session.send(CH_SCORING,
                             {"op": "reply", "rid": request_id,
                              "response": _jsonable(response),
                              "status": status},
                             tc={"tid": tid})
        except OSError:
            # worker session closed between park and reply: undelivered
            with self._lock:
                self._acks.pop(request_id, None)
            return False
        if not waiter.event.wait(self._reply_timeout + self._ack_grace):
            with self._lock:
                self._acks.pop(request_id, None)
            return False
        return bool(waiter.response)

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        """Pipelined batch reply: send every reply frame first, then
        collect the delivery acks — one exchange round-trip for the
        whole micro-batch instead of a blocking RTT per row.

        Binary-negotiated sessions get their whole micro-batch as ONE
        raw-float32 reply block serialized straight from the margin
        values (no ``tolist()``, no per-row JSON frames) and answer
        with one batched ``ack_many``; error replies and non-binary
        sessions keep the per-row JSON frames (the negotiated
        fallback/error path)."""
        waiting: List[Tuple[str, _Pending]] = []
        #: session.sid -> (session, [(rid, value), ...]) — one binary
        #: block per (session, batch)
        bin_groups: Dict[str, Tuple[Any, List[Tuple[str, Any]]]] = {}
        for rid, response, status in entries:
            session, tid = self._reply_session(rid)
            if session is None:
                continue
            waiter = _Pending()
            with self._lock:
                self._acks[rid] = (waiter, session.sid)
            if (status == 200 and session.peer_binary
                    and self._binary_value_ok(response)):
                bin_groups.setdefault(
                    session.sid, (session, []))[1].append(
                        (rid, response))
                waiting.append((rid, waiter))
                continue
            try:
                session.send(CH_SCORING,
                             {"op": "reply", "rid": rid,
                              "response": _jsonable(response),
                              "status": status},
                             tc={"tid": tid})
            except OSError:
                with self._lock:
                    self._acks.pop(rid, None)
                continue
            waiting.append((rid, waiter))
        dead: set = set()
        for session, items in bin_groups.values():
            try:
                session.send_bytes(CH_SCORING,
                                   wire.pack_replies(items))
            except (OSError, ValueError):
                # session died (or a value refused to pack): those
                # waiters are undelivered NOW, not after the ack wait
                with self._lock:
                    for rid, _v in items:
                        self._acks.pop(rid, None)
                        dead.add(rid)
        delivered = 0
        deadline = time.monotonic() + self._reply_timeout \
            + self._ack_grace
        for rid, waiter in waiting:
            if rid in dead:
                continue
            if waiter.event.wait(max(0.0, deadline - time.monotonic())) \
                    and bool(waiter.response):
                delivered += 1
            else:
                with self._lock:
                    self._acks.pop(rid, None)
        return delivered

    def stop(self) -> None:
        self._closing.set()    # supervisor + beacon wind down
        for session in list(self._ts.sessions.values()):
            try:
                session.send(CH_CONTROL, {"op": "stop"}, timeout=1.0)
            except OSError:
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._ts.stop()
        if self._proc_supervisor is not None:
            self._proc_supervisor.join(timeout=5)
            self._proc_supervisor = None
        if self._ready_beacon is not None:
            self._ready_beacon.join(timeout=5)
            self._ready_beacon = None


def request_table(batch: List[Tuple[str, Any]]) -> DataTable:
    """(id, payload) micro-batch → table with ``id`` + payload columns.

    Dict payloads with shared keys become real columns (vector columns for
    list values); anything else lands in a ``value`` object column.
    Entries may be ``(rid, payload)`` or the stamped ``(rid, payload,
    t_enqueue)`` triples the resilience-aware queue carries.

    Binary-wire payloads (float32 row views /
    :class:`~mmlspark_tpu.io.wire.BinaryReq`, ISSUE 11) are converted
    back to ``{"features": [...]}`` dicts here so a TRANSFORM-mode
    engine behind the binary exchange keeps its column contract — the
    per-value cost lands only on this legacy path, never on the
    predictor hot path (which consumes the views directly).
    """
    ids = np.asarray([e[0] for e in batch], dtype=object)
    payloads = [e[1] for e in batch]
    payloads = [
        {"features": (p.X if isinstance(p, wire.BinaryReq)
                      else p).ravel().tolist()}
        if isinstance(p, (np.ndarray, wire.BinaryReq)) else p
        for p in payloads]
    cols: Dict[str, Any] = {"id": ids}
    if payloads and all(isinstance(p, dict) for p in payloads):
        keys = set(payloads[0])
        for p in payloads[1:]:
            keys &= set(p)
        for k in sorted(keys):
            vals = [p[k] for p in payloads]
            if all(isinstance(v, (list, tuple)) for v in vals):
                try:
                    cols[k] = np.asarray(vals, dtype=np.float64)
                    continue
                except (ValueError, TypeError):
                    pass
            arr = np.empty(len(vals), dtype=object)
            arr[:] = vals
            cols[k] = arr
    else:
        arr = np.empty(len(payloads), dtype=object)
        arr[:] = payloads
        cols["value"] = arr
    return DataTable(cols)


def reply_from_table(server: HTTPServer, table: DataTable,
                     reply_col: str, id_col: str = "id") -> int:
    """Route one reply per row back through the server; returns #delivered."""
    delivered = 0
    ids = table[id_col]
    vals = table[reply_col]
    for rid, v in zip(ids, vals):
        if isinstance(v, np.ndarray):
            v = v.tolist()
        elif isinstance(v, np.generic):
            v = v.item()
        if server.reply(str(rid), v):
            delivered += 1
    return delivered


def serve_forever(server: HTTPServer,
                  transform: Callable[[DataTable], DataTable],
                  reply_col: str, max_rows: int = 64,
                  stop_event: Optional[threading.Event] = None) -> None:
    """Micro-batch loop: accumulate → transform → route replies.

    Thin shim over :class:`~mmlspark_tpu.io.scoring.ScoringEngine` in
    legacy transform mode: one worker with inline replies is exactly the
    old loop's thread shape, and the small 2 ms batch budget
    approximates its drain-what's-queued behavior, so lone requests keep
    their sub-poll latency.  Kept so existing callers and notebooks run
    unchanged; new code should construct a ``ScoringEngine`` directly
    for the pipelined hot path (deadline batching knobs, padded
    buckets, stage stats)."""
    from .scoring import ScoringEngine
    engine = ScoringEngine(server, transform=transform,
                           reply_col=reply_col, max_rows=max_rows,
                           latency_budget_ms=2.0, num_scorers=1,
                           num_repliers=0, on_error="raise")
    engine.serve(stop_event)
