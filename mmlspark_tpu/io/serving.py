"""Serving: turn a pipeline into a web service (Spark Serving equivalent).

Reference: io/http/HTTPSourceV2.scala, DistributedHTTPSource.scala,
ServingImplicits.scala (expected paths, UNVERIFIED — SURVEY.md §2.1, §3.4).
The reference parks each HTTP request's open socket keyed by request-id,
emits (id, request) rows into a streaming micro-batch, runs the user's
pipeline, and routes replies back via HTTPSink.

This build keeps that exact architecture, minus Spark streaming: an
:class:`HTTPServer` accepts requests into a queue; the driver loop pulls
micro-batches with :func:`HTTPServer.get_batch`, converts them to a table
(:func:`request_table`), runs any pipeline/model, and answers with
:func:`reply_from_table` — replies route to the still-open sockets by id.
``serve_forever`` wires the loop up for the one-liner case.  Batching is
the TPU-relevant part: requests accumulate into one fixed-size device batch
instead of per-request forwards.
"""

from __future__ import annotations

import hmac
import json
import logging
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.schema import DataTable

log = logging.getLogger(__name__)


class _Pending:
    __slots__ = ("event", "response", "status")

    def __init__(self):
        self.event = threading.Event()
        self.response: Any = None
        self.status = 200


class _Exchange:
    """Shared request queue + parked-reply table.

    One exchange can back many worker servers: requests from every worker
    land in ONE micro-batch queue, and a reply routes to the parked socket
    by request-id regardless of which worker accepted it — the
    cross-worker reply routing of the reference's DistributedHTTPSource /
    HTTPSink pair (expected path io/http/DistributedHTTPSource.scala,
    UNVERIFIED; SURVEY.md §3.4).
    """

    def __init__(self, reply_timeout: float = 30.0):
        self.queue: "queue.Queue[Tuple[str, Any]]" = queue.Queue()
        self.pending: Dict[str, _Pending] = {}
        self.lock = threading.Lock()
        self.reply_timeout = reply_timeout

    def park(self, payload: Any) -> Tuple[str, _Pending]:
        rid = uuid.uuid4().hex
        pending = _Pending()
        with self.lock:
            self.pending[rid] = pending
        self.queue.put((rid, payload))
        return rid, pending

    def unpark(self, rid: str) -> bool:
        """Remove a parked request after its wait ended.  Returns whether a
        reply landed — re-checked under the lock: once the entry is popped
        here, any later reply() sees no entry and reports undelivered, so
        a reply racing the timeout either fully delivers or fully fails,
        never both."""
        with self.lock:
            pending = self.pending.pop(rid, None)
            return pending is not None and pending.event.is_set()

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        batch: List[Tuple[str, Any]] = []
        try:
            batch.append(self.queue.get(timeout=timeout))
            while len(batch) < max_rows:
                batch.append(self.queue.get_nowait())
        except queue.Empty:
            pass
        return batch

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        with self.lock:
            pending = self.pending.get(request_id)
            if pending is None:
                return False  # socket gone (timeout/disconnect)
            pending.response = response
            pending.status = status
            pending.event.set()
            return True

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        """Batched reply delivery: one lock acquisition for the whole
        micro-batch instead of one per row — the scoring engine's reply
        hot path.  Returns the number delivered."""
        delivered = 0
        with self.lock:
            for rid, response, status in entries:
                pending = self.pending.get(rid)
                if pending is None:
                    continue
                pending.response = response
                pending.status = status
                pending.event.set()
                delivered += 1
        return delivered


class HTTPServer:
    """Accepts JSON POSTs, parks the socket, exposes micro-batches.

    Analog of ``DistributedHTTPSource`` for one process; a mesh deployment
    runs one server per host exactly like the reference runs one per
    executor (SURVEY.md §3.4).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout: float = 30.0,
                 exchange: Optional[_Exchange] = None):
        self._exchange = exchange or _Exchange(reply_timeout)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            disable_nagle_algorithm = True   # ms-latency serving contract
            # HTTP/1.1 keep-alive: a closed-loop client reuses its
            # connection instead of paying a TCP connect per request
            # (every reply carries Content-Length, so this is safe)
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                if api_path not in ("/", self.path):
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(
                        self.rfile.read(length).decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    self.send_error(400, "invalid JSON")
                    return
                rid, pending = outer._exchange.park(payload)
                ok = pending.event.wait(outer._exchange.reply_timeout)
                # unpark re-checks under the lock: a reply racing the
                # timeout is either fully delivered or fully refused
                if not outer._exchange.unpark(rid) and not ok:
                    self.send_error(504, "pipeline timeout")
                    return
                body = json.dumps(pending.response).encode("utf-8")
                self.send_response(pending.status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        # default accept backlog (5) overflows under concurrent-client
        # bursts — the kernel drops SYNs and clients stall on 1s/3s
        # retransmit timers, a serving p99 disaster
        server_cls = type("_Server", (ThreadingHTTPServer,),
                          {"request_queue_size": 128})
        self._server = server_cls((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> "HTTPServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def request_queue(self) -> "queue.Queue[Tuple[str, Any]]":
        """The raw parked-request queue — the scoring engine's batcher
        reads it directly for deadline-aware batch forming."""
        return self._exchange.queue

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        """Pull up to ``max_rows`` parked requests (micro-batch trigger)."""
        return self._exchange.get_batch(max_rows, timeout)

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        """HTTPSink: route a reply to the parked socket by request-id."""
        return self._exchange.reply(request_id, response, status)

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        """Batched reply routing (one lock for the whole micro-batch)."""
        return self._exchange.reply_many(entries)


class DistributedHTTPServer:
    """N worker HTTP servers over ONE shared exchange.

    The reference's DistributedHTTPSource runs one server per executor
    and routes each reply back to whichever executor parked the socket
    (SURVEY.md §3.4).  Here: every worker pushes into the shared micro-
    batch queue, the driver loop pulls interleaved batches, and
    ``reply``/``reply_from_table`` deliver by request-id across workers.
    """

    def __init__(self, num_workers: int = 2, host: str = "127.0.0.1",
                 api_path: str = "/", reply_timeout: float = 30.0):
        self._exchange = _Exchange(reply_timeout)
        self.workers = [
            HTTPServer(host, 0, api_path, reply_timeout,
                       exchange=self._exchange)
            for _ in range(num_workers)]

    @property
    def addresses(self) -> List[str]:
        return [w.address for w in self.workers]

    @property
    def request_queue(self) -> "queue.Queue[Tuple[str, Any]]":
        return self._exchange.queue

    def start(self) -> "DistributedHTTPServer":
        for w in self.workers:
            w.start()
        return self

    def stop(self) -> None:
        for w in self.workers:
            w.stop()

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        return self._exchange.get_batch(max_rows, timeout)

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        return self._exchange.reply(request_id, response, status)

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        return self._exchange.reply_many(entries)


def join_exchange(exchange: str, worker_id: int,
                  http_host: str = "0.0.0.0", api_path: str = "/",
                  reply_timeout: float = 30.0, token: str = "") -> None:
    """Run ONE serving worker against a remote exchange — the multi-host
    entrypoint (each machine runs this next to its accelerator; the
    reference's per-executor DistributedHTTPSource server,
    SURVEY.md §3.4).  Blocks until the exchange sends ``stop`` or the
    connection drops.  ``exchange`` is the driver's
    ``MultiprocessHTTPServer(spawn_workers=False).exchange_address``;
    ``worker_id`` must be the unique slot index in [0, num_workers);
    ``token`` is the driver's ``MultiprocessHTTPServer.token`` shared
    secret — the exchange drops any connection that does not present it
    (the worker-id/duplicate checks guard mistakes; the token guards
    adversaries).  The exchange port should additionally be firewalled
    to cluster hosts — the token authenticates joiners, it does not
    encrypt the line protocol."""
    host, _, port = exchange.rpartition(":")
    _mp_worker_main(host, int(port), int(worker_id), http_host, api_path,
                    reply_timeout, token)


def _mp_worker_main(driver_host: str, driver_port: int, worker_id: int,
                    http_host: str, api_path: str,
                    reply_timeout: float, token: str = "") -> None:
    """Worker-process entrypoint (module-level for spawn-pickling).

    Owns REAL client sockets in its own process: parks each HTTP request
    locally, forwards (rid, payload) to the driver over one TCP line
    stream, and delivers driver replies to the parked socket.  Delivery
    is decided ATOMICALLY here (the process that holds the socket), and
    reported back as an ack — that keeps ``reply()``'s delivered/
    undelivered contract exact across process boundaries, matching the
    reference where HTTPSink's reply lands on whichever executor parked
    the socket (expected path io/http/DistributedHTTPSource.scala,
    UNVERIFIED; SURVEY.md §3.4).
    """
    import socket as _socket

    conn = _socket.create_connection((driver_host, driver_port))
    # the exchange is a request/reply line protocol: without TCP_NODELAY,
    # Nagle + delayed-ACK quantizes every reply at ~40 ms
    conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    rfile = conn.makefile("r", encoding="utf-8")
    wlock = threading.Lock()

    def send(obj):
        data = (json.dumps(obj) + "\n").encode("utf-8")
        with wlock:
            conn.sendall(data)

    pending: Dict[str, _Pending] = {}
    plock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        disable_nagle_algorithm = True   # ms-latency serving contract
        protocol_version = "HTTP/1.1"    # keep-alive (see HTTPServer)

        def log_message(self, *a):  # quiet
            pass

        def do_POST(self):
            if api_path not in ("/", self.path):
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(
                    self.rfile.read(length).decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                self.send_error(400, "invalid JSON")
                return
            rid = uuid.uuid4().hex
            p = _Pending()
            with plock:
                pending[rid] = p
            send({"op": "park", "rid": rid, "payload": payload})
            ok = p.event.wait(reply_timeout)
            with plock:
                # atomic here, where the socket lives: once popped, a
                # racing reply acks delivered=False and the driver
                # reports the timeout truthfully
                p2 = pending.pop(rid, None)
            delivered = p2 is not None and p2.event.is_set()
            if not delivered and not ok:
                send({"op": "expire", "rid": rid})
                self.send_error(504, "pipeline timeout")
                return
            body = json.dumps(p.response).encode("utf-8")
            self.send_response(p.status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = type("_Server", (ThreadingHTTPServer,),
                 {"request_queue_size": 128})((http_host, 0), Handler)
    # a wildcard bind must not advertise 0.0.0.0: report the interface
    # this worker reaches the exchange through — the address a client on
    # another machine can actually dial (multi-host contract)
    adv_host = httpd.server_address[0]
    if adv_host in ("0.0.0.0", "", "::"):
        adv_host = conn.getsockname()[0]
    send({"op": "hello", "worker": worker_id, "token": token,
          "host": adv_host, "port": httpd.server_address[1]})
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    for line in rfile:
        msg = json.loads(line)
        if msg["op"] == "stop":
            break
        if msg["op"] == "reply":
            rid = msg["rid"]
            with plock:
                p = pending.get(rid)
                if p is not None:
                    p.response = msg["response"]
                    p.status = msg.get("status", 200)
                    p.event.set()
            send({"op": "ack", "rid": rid, "delivered": p is not None})
    httpd.shutdown()
    httpd.server_close()
    conn.close()


class MultiprocessHTTPServer:
    """N worker HTTP servers as SEPARATE OS PROCESSES over one TCP
    exchange — the cross-process topology of the reference's
    DistributedHTTPSource, where each executor process accepts requests
    and replies route back to the process holding the socket
    (SURVEY.md §3.4).  Driver-facing API is identical to
    :class:`DistributedHTTPServer` (start/stop/addresses/get_batch/
    reply), so the same micro-batch loop drives either topology.

    With ``spawn_workers=False`` nothing is forked: the exchange waits
    for ``num_workers`` REMOTE workers to dial in via
    :func:`join_exchange` — the multi-HOST deployment, each machine
    running one worker next to its accelerator (the reference's
    per-executor HTTP server).  Pass ``host="0.0.0.0"`` so remote
    workers can reach the exchange; ``exchange_address`` is the
    ``host:port`` to hand them, along with the ``token`` shared secret
    each ``join_exchange`` must present (auto-generated unless given).
    The exchange rejects any connection whose first message is not a
    correctly-tokened hello; still firewall the exchange port to
    cluster hosts — the token authenticates joiners, the line protocol
    itself is plaintext.
    """

    def __init__(self, num_workers: int = 2, host: str = "127.0.0.1",
                 api_path: str = "/", reply_timeout: float = 30.0,
                 spawn_workers: bool = True, join_timeout: float = 20.0,
                 token: Optional[str] = None):
        import secrets
        import socket as _socket

        self.token = secrets.token_hex(16) if token is None else token
        self._listener = _socket.socket()
        self._listener.bind((host, 0))
        self._listener.listen(num_workers)
        self.queue: "queue.Queue[Tuple[str, Any]]" = queue.Queue()
        self._route: Dict[str, int] = {}       # rid -> worker index
        self._acks: Dict[str, _Pending] = {}   # rid -> ack waiter
        self._lock = threading.Lock()
        self._conns: List[Any] = []
        self._wlocks: List[threading.Lock] = []
        self.addresses: List[str] = [""] * num_workers
        self._reply_timeout = reply_timeout
        self._join_timeout = join_timeout

        self._procs = []
        if spawn_workers:
            import multiprocessing as mp
            ctx = mp.get_context("spawn")  # no inherited jax/thread state
            dh, dp = self._listener.getsockname()
            self._procs = [
                ctx.Process(target=_mp_worker_main,
                            args=(dh, dp, i, host, api_path,
                                  reply_timeout, self.token),
                            daemon=True)
                for i in range(num_workers)]

    @property
    def exchange_address(self) -> str:
        """``host:port`` remote workers dial via :func:`join_exchange`.
        A wildcard bind advertises this machine's primary outbound
        interface, not ``0.0.0.0`` — the same dial-ability rule the
        workers follow for their own hello addresses."""
        import socket as _socket
        h, p = self._listener.getsockname()
        if h in ("0.0.0.0", "", "::"):
            probe = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
            try:
                # UDP connect sends nothing; it just resolves the route
                probe.connect(("10.255.255.255", 1))
                h = probe.getsockname()[0]
            except OSError:
                try:
                    h = _socket.gethostbyname(_socket.gethostname())
                except OSError:
                    h = "127.0.0.1"
            finally:
                probe.close()
        return f"{h}:{p}"

    def start(self) -> "MultiprocessHTTPServer":
        for p in self._procs:
            p.start()
        import socket as _socket
        import time
        # Accept until every worker slot has said a (tokened) hello or
        # the budget runs out — NOT exactly num_workers connections: a
        # rejected or garbage peer must not consume a slot's accept and
        # lock the legit worker out (a single adversarial connect would
        # otherwise be a join DoS).  Budgets: 60 s for spawned workers
        # (a loaded single-core host can take >20 s just to spawn and
        # import N interpreters), join_timeout for external ones.
        budget = 60.0 if self._procs else self._join_timeout
        deadline = time.monotonic() + budget
        self._listener.settimeout(0.2)
        got_conn = False
        while (any(not a for a in self.addresses)
               and time.monotonic() < deadline):
            try:
                conn, _ = self._listener.accept()
            except (TimeoutError, OSError):
                continue
            got_conn = True
            conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            # NOT registered yet: the reader claims a _conns/_wlocks slot
            # only after a correctly-tokened hello, so rejected or
            # garbage peers never occupy exchange state (ADVICE r5)
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()
        # hellos are parsed asynchronously by reader threads — a worker
        # whose connection landed just before the deadline may not have
        # its address recorded yet; grace-drain before declaring failure
        grace = time.monotonic() + 2.0
        while (any(not a for a in self.addresses)
               and time.monotonic() < grace):
            time.sleep(0.05)
        if any(not a for a in self.addresses):
            missing = [i for i, a in enumerate(self.addresses) if not a]
            xaddr = self.exchange_address  # before stop() closes it
            self.stop()
            if self._procs and not got_conn:
                raise RuntimeError(
                    "worker processes failed to connect; if this is "
                    "a script, MultiprocessHTTPServer must be "
                    "started under `if __name__ == '__main__':` "
                    "(spawn re-imports the main module)")
            raise RuntimeError(
                f"worker slots {missing} never joined {xaddr} within "
                f"{budget}s: start one join_exchange(...) per slot with "
                f"a unique id in [0, {len(self.addresses)}) and this "
                f"server's .token (invalid/duplicate ids and missing or "
                f"wrong tokens are dropped and land here)")
        return self

    def _reader(self, conn) -> None:
        # pre-auth read timeout: a silent non-protocol peer must not
        # park a reader thread on the exchange forever
        conn.settimeout(30.0)
        rfile = conn.makefile("r", encoding="utf-8")
        # registration is reported through a mutable slot so a socket
        # error AFTER auth (worker crash mid-read) still reaches the
        # purge below with the registered index
        reg = [-1]   # _conns slot; claimed only after a tokened hello
        try:
            self._reader_loop(conn, rfile, reg)
        except OSError:
            pass   # pre-auth timeout, or peer reset mid-stream
        except Exception:  # noqa: BLE001
            # Anything else — UnicodeDecodeError from the utf-8
            # makefile (binary/TLS peer), KeyError from a version-
            # skewed worker's malformed park/hello — must not kill the
            # reader with an unhandled traceback: the purge below is
            # what unblocks reply() waiters for this worker's rids.
            log.exception("serving: exchange reader failed; dropping "
                          "connection")
        idx = reg[0]
        if idx < 0:
            # never authed: nothing was registered for this conn, so
            # there is no exchange state to purge — just drop it
            try:
                conn.close()
            except OSError:
                pass
            return
        # worker gone (crash/kill): its parked sockets died with it.
        # Purge its routes so replies report undelivered immediately and
        # release any reply() calls waiting on acks FROM THIS WORKER
        # (acks carry the worker index — routes and acks are disjoint
        # because reply() pops the route before registering the ack) —
        # the surviving workers keep serving (the reference's executor
        # loss story, SURVEY.md §5.3 applied to serving).
        with self._lock:
            for r in [r for r, i in self._route.items() if i == idx]:
                self._route.pop(r, None)
            dead_acks = [r for r, (_, i) in self._acks.items()
                         if i == idx]
            for r in dead_acks:
                waiter, _ = self._acks.pop(r)
                waiter.response = False
                waiter.event.set()
        # close the link so a still-alive (but protocol-broken) worker
        # notices, and later _send()s fail fast instead of queueing
        try:
            conn.close()
        except OSError:
            pass

    def _reader_loop(self, conn, rfile, reg: List[int]) -> None:
        """Line-protocol pump for one exchange connection.  Writes the
        registered ``_conns`` index into ``reg[0]`` at auth time (stays
        -1 when the peer is dropped before authenticating — nothing
        registered)."""
        idx = -1
        for line in rfile:
            try:
                msg = json.loads(line)
            except ValueError:
                if idx < 0:
                    # garbage before auth: a non-protocol peer must not
                    # stay parked on the exchange
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                continue
            op = msg.get("op")
            if idx < 0:
                # first message MUST be a correctly-tokened hello: an
                # unauthenticated peer never gets to claim a worker slot
                # or route client traffic (ADVICE r4)
                if op != "hello" or not hmac.compare_digest(
                        str(msg.get("token", "")).encode("utf-8"),
                        self.token.encode("utf-8")):
                    log.warning("serving: dropping unauthenticated "
                                "exchange connection (bad or missing "
                                "token)")
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return  # nothing registered — no purge
                # authed: only now claim exchange state (ADVICE r5 — a
                # dropped peer must never consume a _conns slot)
                conn.settimeout(None)
                with self._lock:
                    idx = len(self._conns)
                    self._conns.append(conn)
                    self._wlocks.append(threading.Lock())
                reg[0] = idx
            if op == "hello":
                w = msg.get("worker")
                if (not isinstance(w, int) or not
                        0 <= w < len(self.addresses)):
                    log.warning("serving: ignoring hello with invalid "
                                "worker id %r (need 0..%d)", w,
                                len(self.addresses) - 1)
                    continue
                if self.addresses[w]:
                    log.warning("serving: duplicate hello for worker "
                                "slot %d ignored (unique ids required)",
                                w)
                    continue
                self.addresses[w] = f"http://{msg['host']}:{msg['port']}"
            elif op == "park":
                with self._lock:
                    self._route[msg["rid"]] = idx
                self.queue.put((msg["rid"], msg["payload"]))
            elif op == "expire":
                with self._lock:
                    self._route.pop(msg["rid"], None)
            elif op == "ack":
                with self._lock:
                    entry = self._acks.pop(msg["rid"], None)
                if entry is not None:
                    waiter = entry[0]
                    waiter.response = msg["delivered"]
                    waiter.event.set()

    def _send(self, idx: int, obj) -> None:
        data = (json.dumps(obj) + "\n").encode("utf-8")
        with self._wlocks[idx]:
            self._conns[idx].sendall(data)

    @property
    def request_queue(self) -> "queue.Queue[Tuple[str, Any]]":
        return self.queue

    def get_batch(self, max_rows: int = 64, timeout: float = 0.05
                  ) -> List[Tuple[str, Any]]:
        batch: List[Tuple[str, Any]] = []
        try:
            batch.append(self.queue.get(timeout=timeout))
            while len(batch) < max_rows:
                batch.append(self.queue.get_nowait())
        except queue.Empty:
            pass
        return batch

    def reply(self, request_id: str, response: Any,
              status: int = 200) -> bool:
        """Route a reply to the worker PROCESS holding the socket; blocks
        on that worker's delivered/undelivered ack (the socket owner
        decides atomically, so a reply racing the worker-side timeout
        reports exactly what the client saw)."""
        with self._lock:
            idx = self._route.pop(request_id, None)
            if idx is None:
                return False
            waiter = _Pending()
            self._acks[request_id] = (waiter, idx)
        try:
            self._send(idx, {"op": "reply", "rid": request_id,
                             "response": response, "status": status})
        except OSError:
            # worker process died between park and reply: undelivered
            with self._lock:
                self._acks.pop(request_id, None)
            return False
        if not waiter.event.wait(self._reply_timeout + 5.0):
            with self._lock:
                self._acks.pop(request_id, None)
            return False
        return bool(waiter.response)

    def reply_many(self, entries: List[Tuple[str, Any, int]]) -> int:
        """Pipelined batch reply: send every reply line first, then
        collect the delivery acks — one exchange round-trip for the
        whole micro-batch instead of a blocking RTT per row."""
        waiting: List[_Pending] = []
        for rid, response, status in entries:
            with self._lock:
                idx = self._route.pop(rid, None)
                if idx is None:
                    continue
                waiter = _Pending()
                self._acks[rid] = (waiter, idx)
            try:
                self._send(idx, {"op": "reply", "rid": rid,
                                 "response": response, "status": status})
            except OSError:
                with self._lock:
                    self._acks.pop(rid, None)
                continue
            waiting.append((rid, waiter))
        delivered = 0
        deadline = time.monotonic() + self._reply_timeout + 5.0
        for rid, waiter in waiting:
            if waiter.event.wait(max(0.0, deadline - time.monotonic())) \
                    and bool(waiter.response):
                delivered += 1
            else:
                with self._lock:
                    self._acks.pop(rid, None)
        return delivered

    def stop(self) -> None:
        for i in range(len(self._conns)):
            try:
                self._send(i, {"op": "stop"})
            except OSError:
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._listener.close()


def request_table(batch: List[Tuple[str, Any]]) -> DataTable:
    """(id, payload) micro-batch → table with ``id`` + payload columns.

    Dict payloads with shared keys become real columns (vector columns for
    list values); anything else lands in a ``value`` object column.
    """
    ids = np.asarray([rid for rid, _ in batch], dtype=object)
    payloads = [p for _, p in batch]
    cols: Dict[str, Any] = {"id": ids}
    if payloads and all(isinstance(p, dict) for p in payloads):
        keys = set(payloads[0])
        for p in payloads[1:]:
            keys &= set(p)
        for k in sorted(keys):
            vals = [p[k] for p in payloads]
            if all(isinstance(v, (list, tuple)) for v in vals):
                try:
                    cols[k] = np.asarray(vals, dtype=np.float64)
                    continue
                except (ValueError, TypeError):
                    pass
            arr = np.empty(len(vals), dtype=object)
            arr[:] = vals
            cols[k] = arr
    else:
        arr = np.empty(len(payloads), dtype=object)
        arr[:] = payloads
        cols["value"] = arr
    return DataTable(cols)


def reply_from_table(server: HTTPServer, table: DataTable,
                     reply_col: str, id_col: str = "id") -> int:
    """Route one reply per row back through the server; returns #delivered."""
    delivered = 0
    ids = table[id_col]
    vals = table[reply_col]
    for rid, v in zip(ids, vals):
        if isinstance(v, np.ndarray):
            v = v.tolist()
        elif isinstance(v, np.generic):
            v = v.item()
        if server.reply(str(rid), v):
            delivered += 1
    return delivered


def serve_forever(server: HTTPServer,
                  transform: Callable[[DataTable], DataTable],
                  reply_col: str, max_rows: int = 64,
                  stop_event: Optional[threading.Event] = None) -> None:
    """Micro-batch loop: accumulate → transform → route replies.

    Thin shim over :class:`~mmlspark_tpu.io.scoring.ScoringEngine` in
    legacy transform mode: one worker with inline replies is exactly the
    old loop's thread shape, and the small 2 ms batch budget
    approximates its drain-what's-queued behavior, so lone requests keep
    their sub-poll latency.  Kept so existing callers and notebooks run
    unchanged; new code should construct a ``ScoringEngine`` directly
    for the pipelined hot path (deadline batching knobs, padded
    buckets, stage stats)."""
    from .scoring import ScoringEngine
    engine = ScoringEngine(server, transform=transform,
                           reply_col=reply_col, max_rows=max_rows,
                           latency_budget_ms=2.0, num_scorers=1,
                           num_repliers=0, on_error="raise")
    engine.serve(stop_event)
