"""Raw-float32 scoring wire: the zero-copy binary payload codec (ISSUE 11).

The transport's JSON payloads were the last per-row cost on the serving
hot path: every park frame JSON-encoded a feature vector (one Python
float object per value, both directions) and every reply re-encoded the
margins.  This module is the negotiated binary alternative that rides
:data:`~mmlspark_tpu.io.transport.FLAG_BINARY` frames on the SCORING
channel:

* **Requests** (:func:`pack_matrix` / :func:`unpack_matrix`) — a 12-byte
  preamble ``(kind, rid_len, rows, cols)`` + the request id + one packed
  C-order ``(rows, cols)`` float32 block.  The receiver decodes the
  whole block with ONE ``np.frombuffer`` reshape
  (:meth:`~mmlspark_tpu.io.scoring.ColumnPlan.decode` accepts the
  resulting array views directly): zero JSON, zero per-value Python
  objects.  Column order is the model's canonical feature order — the
  same contract the JSON wire's ``features`` vector already used.
* **Replies** (:func:`pack_replies` / :func:`unpack_replies`) — ONE
  frame per (session, micro-batch): an entry table
  ``(rid_len, n_values)`` per row followed by a single contiguous
  float32 block holding every row's margins back to back.  The sender
  serializes straight from the margin ndarray — no ``tolist()``, no
  per-row tuples of Python floats.
* **Partials** (``kind=K_PARTIAL`` on :func:`pack_matrix`) — the
  sharded fleet's tree-range partial margin blocks
  (:mod:`mmlspark_tpu.io.fleet`): same matrix layout, the ``rid`` is
  the fleet request id.

Malformed payloads raise the typed :class:`WireError` — the serving
driver turns that into a per-request 400 (when the rid is recoverable,
:func:`peek_rid`), NEVER a connection teardown: one bad client costs
one request, exactly the per-row-400 contract the JSON decode path
already gives.

Telemetry: pack/unpack times land in the shared transport stats
(``encode_binary`` / ``decode_binary`` timers under ``ns="transport"``)
so the JSON-vs-binary codec cost is readable off any ``/metrics``
scrape; ``tools/bench_serving.py --wire`` commits the A/B.
"""

from __future__ import annotations

import struct
import time
from typing import Any, List, Sequence, Tuple

import numpy as np

from ..core.profiler import get_profiler
from .transport import transport_stats

__all__ = [
    "BinaryReq", "K_PARTIAL", "K_REPLY", "K_REQ", "WireError",
    "pack_matrix", "pack_replies", "peek_rid", "unpack_matrix",
    "unpack_replies",
]

#: payload kinds (first byte of every binary scoring payload)
K_REQ = 1        # feature matrix: score these rows
K_REPLY = 2      # batched margin replies (entry table + value block)
K_PARTIAL = 3    # tree-range partial margin sums (fleet reduce input)

#: matrix preamble: kind(u8) reserved(u8) rid_len(u16) rows(u32) cols(u32)
_MAT = struct.Struct("<BBHII")
#: reply preamble: kind(u8) reserved(u8) pad(u16) count(u32)
_REP = struct.Struct("<BBHI")
#: reply entry: rid_len(u16) n_values(u16)
_ENT = struct.Struct("<HH")

#: sanity ceiling on matrix width — a corrupt preamble must fail the
#: typed way, not attempt a terabyte reshape
MAX_COLS = 1 << 20

# the codec timers, resolved ONCE: StageStats.timer() takes a lock per
# call, a measurable tax at per-frame rates on the hot path
_ENC = transport_stats.timer("encode_binary")
_DEC = transport_stats.timer("decode_binary")
# profile-view aliases (ISSUE 12): shared histogram objects, so the
# binary codec phases cost nothing extra per frame
get_profiler().alias("transport.encode_binary", _ENC)
get_profiler().alias("transport.decode_binary", _DEC)


class WireError(ValueError):
    """Malformed binary scoring payload (truncated preamble, length
    mismatch, absurd dimensions).  Costs one request, never the
    connection."""


class BinaryReq:
    """A decoded binary scoring request as parked on the exchange
    queue: the float32 row view plus the frame-header deadline (binary
    payloads carry no ``_deadline_ms`` key — the deadline rides the
    transport header instead).  The engine's
    :class:`~mmlspark_tpu.io.scoring.ColumnPlan` consumes the ``X``
    view directly."""

    __slots__ = ("X", "deadline_ms")

    def __init__(self, X: np.ndarray, deadline_ms=None):
        self.X = X
        self.deadline_ms = deadline_ms


def pack_matrix(rid: str, X: np.ndarray, kind: int = K_REQ) -> bytes:
    """Pack a ``(rows, cols)`` float32 matrix (a scoring request, or a
    fleet partial with ``kind=K_PARTIAL``).  ``X`` is made C-contiguous
    float32; the payload is preamble + rid + the raw block — one memcpy
    into the frame, nothing per value."""
    t0 = time.perf_counter()
    X = np.ascontiguousarray(X, dtype=np.float32)
    if X.ndim == 1:
        X = X.reshape(1, -1)
    if X.ndim != 2:
        raise WireError(f"matrix payload must be 2-D, got shape "
                        f"{X.shape}")
    rid_b = rid.encode("utf-8")
    if len(rid_b) > 0xFFFF:
        raise WireError(f"rid of {len(rid_b)} bytes exceeds the u16 "
                        "preamble field")
    buf = b"".join((_MAT.pack(kind, 0, len(rid_b), X.shape[0],
                              X.shape[1]),
                    rid_b, memoryview(X).cast("B")))
    _ENC.record(time.perf_counter() - t0)
    return buf


def peek_rid(buf) -> str:
    """Best-effort request id recovery from a (possibly malformed)
    matrix payload, so a bad preamble can still be answered with a
    per-request 400 instead of silently timing out the client.
    Returns ``""`` when unrecoverable."""
    if len(buf) < _MAT.size:
        return ""
    _k, _r, rid_len, _rows, _cols = _MAT.unpack_from(buf)
    end = _MAT.size + rid_len
    if rid_len == 0 or end > len(buf):
        return ""
    try:
        return bytes(buf[_MAT.size:end]).decode("utf-8")
    except UnicodeDecodeError:
        return ""


def unpack_matrix(buf) -> Tuple[int, str, np.ndarray]:
    """Decode a matrix payload: ``(kind, rid, X)`` where ``X`` is a
    read-only ``(rows, cols)`` float32 view over the frame bytes — ONE
    ``np.frombuffer`` reshape, no copies, no per-value objects.  Raises
    :class:`WireError` on any structural problem."""
    t0 = time.perf_counter()
    if len(buf) < _MAT.size:
        raise WireError(f"matrix payload of {len(buf)} bytes is shorter "
                        f"than the {_MAT.size}-byte preamble")
    kind, _r, rid_len, rows, cols = _MAT.unpack_from(buf)
    if kind not in (K_REQ, K_PARTIAL):
        raise WireError(f"unexpected matrix payload kind {kind}")
    if cols == 0 or cols > MAX_COLS:
        raise WireError(f"matrix payload claims {cols} columns")
    off = _MAT.size + rid_len
    want = off + rows * cols * 4
    if want != len(buf):
        raise WireError(
            f"matrix payload length mismatch: preamble claims "
            f"{rows}x{cols} float32 (+{rid_len}B rid = {want}B), frame "
            f"carries {len(buf)}B")
    try:
        rid = bytes(buf[_MAT.size:off]).decode("utf-8")
    except UnicodeDecodeError as e:
        raise WireError(f"non-UTF-8 rid in matrix payload: {e}") from e
    X = np.frombuffer(buf, np.float32, rows * cols, off).reshape(
        rows, cols)
    _DEC.record(time.perf_counter() - t0)
    return kind, rid, X


def pack_replies(entries: Sequence[Tuple[str, Any]]) -> bytes:
    """Pack one micro-batch of scored replies — ``entries`` is
    ``[(rid, values), ...]`` where ``values`` is a numpy scalar (single
    class) or a ``(K,)`` margin row.  The values serialize straight
    from the ndarray rows into ONE contiguous float32 block (this is
    the reply path that skips the per-row ``tolist()`` build)."""
    t0 = time.perf_counter()
    heads: List[bytes] = [b""]      # slot 0 becomes the preamble
    rids: List[bytes] = []
    vals: List[np.ndarray] = []
    for rid, v in entries:
        rid_b = rid.encode("utf-8")
        row = np.atleast_1d(np.asarray(v, dtype=np.float32)).ravel()
        if len(rid_b) > 0xFFFF or row.size > 0xFFFF:
            raise WireError("reply entry exceeds u16 preamble fields")
        heads.append(_ENT.pack(len(rid_b), row.size))
        rids.append(rid_b)
        vals.append(row)
    heads[0] = _REP.pack(K_REPLY, 0, 0, len(entries))
    block = (np.concatenate(vals) if vals
             else np.empty(0, np.float32))
    buf = b"".join(heads + rids + [memoryview(block).cast("B")])
    _ENC.record(time.perf_counter() - t0)
    return buf


def unpack_replies(buf) -> List[Tuple[str, np.ndarray]]:
    """Decode a reply payload into ``[(rid, values), ...]`` — the value
    arrays are float32 views into one frombuffer over the shared block.
    Raises :class:`WireError` on structural problems."""
    t0 = time.perf_counter()
    if len(buf) < _REP.size or buf[0] != K_REPLY:
        raise WireError("not a reply payload")
    _k, _r, _p, count = _REP.unpack_from(buf)
    off = _REP.size
    ent_bytes = count * _ENT.size
    if off + ent_bytes > len(buf):
        raise WireError(f"reply payload truncated in its {count}-entry "
                        "table")
    lens = [_ENT.unpack_from(buf, off + i * _ENT.size)
            for i in range(count)]
    off += ent_bytes
    rids: List[str] = []
    for rid_len, _n in lens:
        if off + rid_len > len(buf):
            raise WireError("reply payload truncated in its rid table")
        try:
            rids.append(bytes(buf[off:off + rid_len]).decode("utf-8"))
        except UnicodeDecodeError as e:
            raise WireError(f"non-UTF-8 rid in reply payload: "
                            f"{e}") from e
        off += rid_len
    total = sum(n for _l, n in lens)
    if off + total * 4 != len(buf):
        raise WireError(
            f"reply payload length mismatch: entry table claims "
            f"{total} float32 values, frame carries "
            f"{len(buf) - off} trailing bytes")
    block = np.frombuffer(buf, np.float32, total, off)
    out: List[Tuple[str, np.ndarray]] = []
    pos = 0
    for rid, (_l, n) in zip(rids, lens):
        out.append((rid, block[pos:pos + n]))
        pos += n
    _DEC.record(time.perf_counter() - t0)
    return out
