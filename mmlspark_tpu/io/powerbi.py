"""PowerBI streaming-dataset writer.

Reference: io/powerbi/PowerBIWriter.scala (expected path, UNVERIFIED —
SURVEY.md §2.1): ``df.writeToPowerBI(url)`` pushes row batches to a
PowerBI push-dataset REST endpoint.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ..core.schema import DataTable, TableLike, to_table
from .http import HTTPRequestData, _execute, _np_default


class PowerBIWriter:
    """Batched JSON POSTs to a PowerBI push URL with retry/backoff."""

    def __init__(self, url: str, batch_size: int = 1000,
                 max_retries: int = 3, timeout: float = 30.0,
                 backoff: float = 0.2):
        self.url = url
        self.batch_size = batch_size
        self.max_retries = max_retries
        self.timeout = timeout
        self.backoff = backoff

    def _rows(self, table: DataTable) -> List[dict]:
        cols = {}
        for name in table.columns:
            col = table[name]
            cols[name] = col.tolist() if col.dtype != object else list(col)
        return [dict(zip(cols, vals)) for vals in zip(*cols.values())]

    def write(self, dataset: TableLike) -> int:
        """Pushes all rows; returns the number of successful batches.
        Raises on any failed batch (PowerBI contract: at-least-once)."""
        table = to_table(dataset)
        rows = self._rows(table)
        ok = 0
        for start in range(0, len(rows), self.batch_size):
            chunk = rows[start:start + self.batch_size]
            body = json.dumps({"rows": chunk}, default=_np_default).encode()
            req = HTTPRequestData(
                self.url, "POST",
                {"Content-Type": "application/json"}, body)
            resp = _execute(req, self.timeout, self.max_retries,
                            self.backoff)
            if resp.error or resp.statusCode >= 400:
                raise IOError(
                    f"PowerBI push failed at batch {start // self.batch_size}"
                    f": {resp.error or resp.statusCode}")
            ok += 1
        return ok


def write_to_power_bi(dataset: TableLike, url: str, **kwargs) -> int:
    """Functional form, mirroring ``df.writeToPowerBI`` in the reference."""
    return PowerBIWriter(url, **kwargs).write(dataset)
