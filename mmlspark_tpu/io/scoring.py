"""Pipelined micro-batch scoring engine — the serving hot path.

The legacy ``serve_forever`` loop is fully serial: one thread does a
blocking ``get_batch`` (fixed 50 ms poll, fixed ``max_rows``) → JSON/dict
decode → predict → reply, so socket I/O, Python decode, and the
GIL-releasing native ``predict_forest`` kernel all wait on each other,
and every distinct batch shape re-compiles the jitted walk.  This module
replaces it with the canonical serving-throughput levers (Clipper,
Crankshaw et al. 2017; the reference's Spark Serving micro-batch trigger,
SURVEY.md §3.4):

* **Deadline-aware batching** — a batch closes when ``max_rows`` is
  reached OR the oldest parked request exceeds ``latency_budget_ms``,
  instead of a fixed poll.  Bursts fill big batches immediately; a lone
  request waits at most the budget.
* **Power-of-two padded buckets** — feature matrices are padded to the
  next power-of-two row count before scoring, so the jitted
  ``_predict_forest`` path compiles once per bucket instead of once per
  distinct batch size (results are sliced back before reply).
* **Pipelining** — N workers each form (serialized by a lock), decode,
  and score batches: while one worker is inside the GIL-releasing
  native kernel, another accumulates and decodes the next batch, and an
  optional replier thread routes the previous batch's responses (the
  reply path of the multiprocess topology blocks on cross-process
  acks).
* **Instrumentation** — every stage (batch forming, queue wait, decode,
  score, reply, end-to-end) records into
  :class:`~mmlspark_tpu.core.profiling.StageStats`; ``stats_snapshot()``
  exposes rows/s and p50/p99 counters, the numbers
  ``tools/bench_serving.py`` commits as a BENCH artifact.

The fast decode path is :class:`ColumnPlan`: the payload-key → feature-
column mapping is resolved ONCE, so each batch becomes one contiguous
float32 matrix build instead of per-row dict walks through
``request_table``.

Works with any server exposing the exchange contract
(:class:`~mmlspark_tpu.io.serving.HTTPServer`,
:class:`~mmlspark_tpu.io.serving.DistributedHTTPServer`,
:class:`~mmlspark_tpu.io.serving.MultiprocessHTTPServer`).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..core.profiling import StageStats
from ..core.schema import DataTable

log = logging.getLogger(__name__)


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (bucket ladder for padded scoring)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


class ColumnPlan:
    """Pre-resolved request → float32 feature-matrix decode plan.

    Two layouts, resolved once at construction instead of per batch:

    * ``features="features"`` — each payload carries one key holding a
      length-``num_features`` list (the reference's vector-column
      serving contract).
    * ``features=["f0", "f1", ...]`` — each payload carries one scalar
      per named key; columns are assembled in the given order.

    ``decode`` builds the contiguous ``(n, f)`` float32 matrix straight
    from the payload list — no intermediate :class:`DataTable`, no
    per-row dict-intersection walk.  ``decode_table`` covers callers
    that already hold a table.
    """

    def __init__(self, features: Union[str, Sequence[str]] = "features",
                 num_features: Optional[int] = None):
        if isinstance(features, str):
            self.vector_key: Optional[str] = features
            self.scalar_keys: Tuple[str, ...] = ()
        else:
            self.vector_key = None
            self.scalar_keys = tuple(features)
            if num_features is not None \
                    and num_features != len(self.scalar_keys):
                raise ValueError(
                    f"num_features={num_features} but plan names "
                    f"{len(self.scalar_keys)} scalar columns")
            num_features = len(self.scalar_keys)
        self.num_features = num_features

    def decode(self, payloads: List[Any]) -> np.ndarray:
        """Payload dicts → C-contiguous ``(n, f)`` float32 matrix."""
        if self.vector_key is not None:
            key = self.vector_key
            X = np.asarray([p[key] for p in payloads], dtype=np.float32)
            if X.ndim != 2:
                raise ValueError(
                    f"payload key {key!r} must hold fixed-length "
                    f"vectors; got ragged/scalar values")
        else:
            X = np.empty((len(payloads), len(self.scalar_keys)),
                         dtype=np.float32)
            for j, key in enumerate(self.scalar_keys):
                X[:, j] = [p[key] for p in payloads]
        if self.num_features is not None \
                and X.shape[1] != self.num_features:
            raise ValueError(
                f"decoded {X.shape[1]} features, model expects "
                f"{self.num_features}")
        return np.ascontiguousarray(X)

    def decode_table(self, table: DataTable) -> np.ndarray:
        """Same plan applied to an already-built :class:`DataTable`."""
        if self.vector_key is not None:
            col = table[self.vector_key]
            if col.dtype == object:
                X = np.asarray([np.asarray(v, np.float32) for v in col],
                               dtype=np.float32)
            else:
                X = np.asarray(col, np.float32)
        else:
            X = np.column_stack(
                [np.asarray(table[k], np.float32)
                 for k in self.scalar_keys])
        return np.ascontiguousarray(X.astype(np.float32, copy=False))


def _json_value(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    return v


class ScoringEngine:
    """Deadline-batched, pipelined scoring over a serving exchange.

    Two scoring modes (exactly one of ``predictor``/``transform``):

    * ``predictor`` — the hot path: a callable ``(n, f) float32 ->
      margins`` (typically ``Booster.predictor()``), fed by a
      :class:`ColumnPlan` fast decode, with power-of-two padded buckets.
      Each reply body is the row's score (scalar for single-class, list
      for multiclass), or whatever ``reply_fn(values) -> list`` builds.
    * ``transform`` — legacy-compatible: a ``DataTable -> DataTable``
      callable; the batch goes through
      :func:`~mmlspark_tpu.io.serving.request_table` and replies come
      from ``reply_col``, exactly like the old ``serve_forever`` body.

    Threads: ``num_scorers`` pipeline workers and ``num_repliers``
    repliers.  Each worker forms its own batch (one former at a time,
    serialized by a lock — deadline semantics preserved), then decodes
    and scores it; while one worker is inside the GIL-releasing native
    kernel, another holds the form lock accumulating the next batch.
    Forming in the scorer thread instead of a dedicated batcher saves a
    bounded-queue hop per batch — two thread wakeups that measurably
    cost throughput at saturation on small hosts.  Repliers are
    separate because ``MultiprocessHTTPServer.reply`` blocks on a
    cross-process ack; ``num_repliers=0`` replies inline on the worker
    (the right choice for in-process exchanges with non-blocking
    ``reply_many`` — and what the ``serve_forever`` shim uses to match
    the old loop's shape exactly).  The reply queue is bounded: when
    repliers fall behind, workers stop pulling and requests
    back-pressure into the exchange queue.
    """

    def __init__(self, server, *,
                 predictor: Optional[Callable] = None,
                 plan: Optional[ColumnPlan] = None,
                 transform: Optional[Callable[[DataTable], DataTable]]
                 = None,
                 reply_col: str = "prediction",
                 max_rows: int = 256,
                 latency_budget_ms: float = 5.0,
                 num_scorers: int = 2,
                 num_repliers: int = 1,
                 queue_depth: int = 8,
                 pad_buckets: Optional[bool] = None,
                 reply_fn: Optional[Callable[[np.ndarray], List[Any]]]
                 = None,
                 on_error: str = "reply",
                 stats: Optional[StageStats] = None):
        if (predictor is None) == (transform is None):
            raise ValueError(
                "pass exactly one of predictor= (hot path) or "
                "transform= (DataTable->DataTable legacy path)")
        if on_error not in ("reply", "raise"):
            raise ValueError("on_error must be 'reply' (500 the batch, "
                             "keep serving) or 'raise' (stop and "
                             "re-raise from serve())")
        if predictor is not None and plan is None:
            plan = ColumnPlan()
        if pad_buckets is None:
            # padding buys a bounded compile cache on the JIT walk; the
            # native kernel has no shape-specialized compilation, so
            # padding there only scores phantom rows.  Unknown callables
            # (no .mode) are assumed jit-like and padded.
            pad_buckets = getattr(predictor, "mode", "jit") != "native"
        self._server = server
        self._predictor = predictor
        self._plan = plan
        self._transform = transform
        self._reply_col = reply_col
        self._max_rows = int(max_rows)
        self._budget = float(latency_budget_ms) / 1e3
        self._num_scorers = max(1, int(num_scorers))
        self._num_repliers = max(0, int(num_repliers))
        self._pad_buckets = bool(pad_buckets)
        self._reply_fn = reply_fn
        self._on_error = on_error
        self._fatal: Optional[BaseException] = None
        self._died = threading.Event()
        self.stats = stats or StageStats()
        self._reply_q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._form_lock = threading.Lock()   # one batch former at a time
        self._inflight = 0          # batches being decoded/scored
        self._inflight_lock = threading.Lock()
        self._reply_many = getattr(server, "reply_many", None)
        self._request_q = getattr(server, "request_queue", None)
        if self._request_q is None:  # duck-typed custom servers
            exchange = getattr(server, "_exchange", None)
            self._request_q = getattr(exchange, "queue", None)
        self._get_batch = None
        if self._request_q is None:
            # legacy duck type (pre-engine serve_forever contract): a
            # server exposing only get_batch()/reply() still works —
            # batches form through pulls instead of raw queue reads
            self._get_batch = getattr(server, "get_batch", None)
            if self._get_batch is None:
                raise TypeError(
                    "server must expose request_queue, _exchange.queue, "
                    "or the legacy get_batch() contract")

    # -- batch forming -------------------------------------------------------

    def _form_batch(self) -> Optional[Tuple[List[Tuple[str, Any]], float]]:
        """Adaptive, deadline-aware close.  A batch closes when:

        * ``max_rows`` requests are aboard (size cap), or
        * the batch has been open for ``latency_budget`` (deadline), or
        * the queue is dry AND no other worker is scoring a batch
          (work-conserving: holding requests to fill a batch only pays
          while the pipeline couldn't start them anyway — if every
          scorer is idle, shipping now costs nothing and saves the
          wait).

        The budget clock starts when the batch OPENS (first dequeue) —
        the exchange does not timestamp requests at park, so time spent
        queued while every worker was mid-score is not counted here and
        not in the ``e2e`` stat; under sustained overload the
        client-observed latency exceeds ``e2e`` by that queueing delay
        (the benchmark's client-side percentiles capture it).

        Returns ``(batch, t_first)``; ``None`` on an idle poll tick."""
        if self._request_q is None:
            return self._form_batch_pulling()
        q = self._request_q
        try:
            first = q.get(timeout=0.05)
        except queue.Empty:
            return None
        t_first = time.perf_counter()
        batch = [first]
        deadline = t_first + self._budget
        while len(batch) < self._max_rows:
            try:
                batch.append(q.get_nowait())
                continue
            except queue.Empty:
                pass
            now = time.perf_counter()
            if now >= deadline:
                break
            with self._inflight_lock:
                busy = self._inflight > 0
            if not busy:
                break    # scorers idle: ship immediately
            try:
                batch.append(q.get(timeout=min(deadline - now, 1e-3)))
            except queue.Empty:
                continue
        return batch, t_first

    def _form_batch_pulling(self
                            ) -> Optional[Tuple[List[Tuple[str, Any]],
                                                float]]:
        """Same close policy over the legacy ``get_batch()`` contract
        (servers that expose no raw queue)."""
        batch = self._get_batch(self._max_rows, 0.05)
        if not batch:
            return None
        t_first = time.perf_counter()
        deadline = t_first + self._budget
        while len(batch) < self._max_rows:
            now = time.perf_counter()
            if now >= deadline:
                break
            with self._inflight_lock:
                busy = self._inflight > 0
            if not busy:
                break    # scorers idle: ship immediately
            batch += self._get_batch(self._max_rows - len(batch),
                                     min(deadline - now, 1e-3))
        return batch, t_first

    def _worker(self) -> None:
        """Pipeline worker: form (serialized) → decode → score → reply
        (inline or handed to a replier)."""
        while True:
            with self._form_lock:
                if self._stop.is_set():
                    return
                formed = self._form_batch()
            if formed is None:
                continue
            batch, t_first = formed
            self.stats.timer("batch_form").record(
                time.perf_counter() - t_first)
            with self._inflight_lock:
                self._inflight += 1
            try:
                if self._predictor is not None:
                    pairs = self._score_predictor(batch)
                else:
                    pairs = self._score_transform(batch)
            except Exception as e:  # noqa: BLE001
                if self._on_error == "raise":
                    # legacy serve_forever semantics: a transform bug
                    # stops the loop and surfaces from serve()
                    self._fatal = e
                    self._died.set()
                    self._stop.set()
                    return
                # hot-path semantics: a bad batch must not kill the
                # worker — 500 it and keep serving
                log.exception("scoring batch of %d failed; replying 500",
                              len(batch))
                pairs = [(rid, {"error": "scoring failed"}, 500)
                         for rid, _ in batch]
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
            if self._num_repliers == 0:
                self._deliver(pairs, t_first)
            else:
                self._reply_q.put((pairs, t_first, time.perf_counter()))

    # -- scoring -------------------------------------------------------------

    def _score_matrix(self, X: np.ndarray, n: int) -> List[Any]:
        """Pad to the power-of-two bucket, score, slice, format."""
        with self.stats.time("score"):
            if self._pad_buckets:
                b = next_pow2(n)
                if b > n:
                    Xp = np.zeros((b, X.shape[1]), np.float32)
                    Xp[:n] = X
                    X = Xp
            m = np.asarray(self._predictor(X))[:n]
        if self._reply_fn is not None:
            return self._reply_fn(m)
        return m.tolist()

    def _score_predictor(self, batch):
        payloads = [p for _, p in batch]
        with self.stats.time("decode"):
            try:
                X = self._plan.decode(payloads)
            except Exception:  # noqa: BLE001 - malformed row(s) aboard
                X = None
        if X is None:
            return self._score_predictor_salvage(batch)
        vals = self._score_matrix(X, X.shape[0])
        return [(rid, vals[i]) for i, (rid, _) in enumerate(batch)]

    def _score_predictor_salvage(self, batch):
        """The vectorized decode failed: decode per row so ONE malformed
        payload gets its own 400 instead of failing every co-batched
        request (a single misbehaving client must not error out up to
        ``max_rows`` innocent neighbors)."""
        rows, order, bad = [], [], []
        width = self._plan.num_features
        for rid, p in batch:
            try:
                r = self._plan.decode([p])
            except Exception:  # noqa: BLE001
                bad.append(rid)
                continue
            if width is None:
                width = r.shape[1]
            if r.shape[1] != width:
                bad.append(rid)
                continue
            rows.append(r[0])
            order.append(rid)
        out = [(rid, {"error": "bad request"}, 400) for rid in bad]
        if rows:
            X = np.ascontiguousarray(np.stack(rows))
            vals = self._score_matrix(X, len(rows))
            out += [(rid, vals[i]) for i, rid in enumerate(order)]
        return out

    def _score_transform(self, batch):
        from .serving import request_table
        with self.stats.time("decode"):
            table = request_table(batch)
        with self.stats.time("score"):
            out = self._transform(table)
        ids = out["id"]
        vals = out[self._reply_col]
        return [(str(rid), _json_value(v)) for rid, v in zip(ids, vals)]

    # -- replies -------------------------------------------------------------

    def _deliver(self, pairs, t_first: float) -> None:
        with self.stats.time("reply"):
            if self._reply_many is not None:
                self._reply_many(
                    [(e[0], e[1], e[2] if len(e) > 2 else 200)
                     for e in pairs])
            else:
                for entry in pairs:
                    rid, val = entry[0], entry[1]
                    status = entry[2] if len(entry) > 2 else 200
                    self._server.reply(rid, val, status)
        self.stats.timer("e2e").record(time.perf_counter() - t_first)
        self.stats.add_rows(len(pairs))

    def _replier(self) -> None:
        while True:
            item = self._reply_q.get()
            if item is None:
                return
            pairs, t_first, t_handoff = item
            self.stats.timer("queue_wait").record(
                time.perf_counter() - t_handoff)
            self._deliver(pairs, t_first)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ScoringEngine":
        self._stop.clear()
        self._died.clear()
        self._fatal = None
        self._threads = [
            threading.Thread(target=self._worker,
                             name=f"scoring-worker-{i}", daemon=True)
            for i in range(self._num_scorers)]
        self._threads += [
            threading.Thread(target=self._replier,
                             name=f"scoring-replier-{i}", daemon=True)
            for i in range(self._num_repliers)]
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        """Drain-and-join: workers stop pulling at their next form tick
        (finishing the batch in hand, replies included), then repliers
        drain on sentinels."""
        self._stop.set()
        for t in self._threads[:self._num_scorers]:
            t.join(timeout=5)
        for _ in range(self._num_repliers):
            self._reply_q.put(None)
        for t in self._threads[self._num_scorers:]:
            t.join(timeout=5)
        self._threads = []

    def serve(self, stop_event: Optional[threading.Event] = None) -> None:
        """Blocking convenience: start, wait for ``stop_event`` (forever
        when ``None``), then drain and stop — the ``serve_forever``
        calling convention.  With ``on_error="raise"``, a scoring
        exception stops the engine and re-raises here."""
        self.start()
        try:
            while not self._died.is_set() \
                    and (stop_event is None or not stop_event.is_set()):
                if stop_event is not None:
                    stop_event.wait(0.2)
                else:
                    self._died.wait(0.2)
        finally:
            self.stop()
        if self._fatal is not None:
            raise self._fatal

    # -- observability -------------------------------------------------------

    def stats_snapshot(self) -> dict:
        """Rows/s plus per-stage count/mean/p50/p99 — the counters the
        serving BENCH artifact records."""
        return self.stats.snapshot()
