"""Pipelined micro-batch scoring engine — the serving hot path.

The legacy ``serve_forever`` loop is fully serial: one thread does a
blocking ``get_batch`` (fixed 50 ms poll, fixed ``max_rows``) → JSON/dict
decode → predict → reply, so socket I/O, Python decode, and the
GIL-releasing native ``predict_forest`` kernel all wait on each other,
and every distinct batch shape re-compiles the jitted walk.  This module
replaces it with the canonical serving-throughput levers (Clipper,
Crankshaw et al. 2017; the reference's Spark Serving micro-batch trigger,
SURVEY.md §3.4):

* **Deadline-aware batching** — a batch closes when ``max_rows`` is
  reached OR the oldest parked request exceeds ``latency_budget_ms``,
  instead of a fixed poll.  Bursts fill big batches immediately; a lone
  request waits at most the budget.
* **Power-of-two padded buckets** — feature matrices are padded to the
  next power-of-two row count before scoring, so the jitted
  ``_predict_forest`` path compiles once per bucket instead of once per
  distinct batch size (results are sliced back before reply).
* **Pipelining** — N workers each form (serialized by a lock), decode,
  and score batches: while one worker is inside the GIL-releasing
  native kernel, another accumulates and decodes the next batch, and an
  optional replier thread routes the previous batch's responses (the
  reply path of the multiprocess topology blocks on cross-process
  acks).
* **Instrumentation** — every stage (batch forming, queue wait, decode,
  score, reply, end-to-end) records into
  :class:`~mmlspark_tpu.core.profiling.StageStats`; ``stats_snapshot()``
  exposes rows/s and p50/p99 counters, the numbers
  ``tools/bench_serving.py`` commits as a BENCH artifact.

On top of the fast path sits the **resilience layer** (the reference's
operational story — executor restarts, socket allreduce recovery —
applied to serving, SURVEY.md §5.3):

* **Admission control / load shedding** — ``max_queue_depth`` bounds
  intake: once the parked-request queue exceeds it, the overflow gets
  an explicit ``503 {"error": "shed"}`` instead of unbounded queueing;
  ``shed_wait_ms`` sheds requests that already waited past the budget.
  Shedding drops from the HEAD of the queue (the oldest requests are
  the ones closest to their deadlines — answering them late helps
  nobody, while the fresh arrivals behind them can still make their
  SLO).
* **Per-request deadlines** — ``deadline_ms`` (overridable per request
  via a ``_deadline_ms`` payload key) rejects expired requests with
  ``504 {"error": "expired"}`` at batch-close time, BEFORE scoring —
  an expired request never burns a batch slot.
* **Worker supervision + per-row salvage** — a scoring worker that
  crashes (anything escaping the per-batch handler, including the
  chaos harness's :class:`WorkerKilled`) is restarted in place, and
  the batch it held is salvaged row by row: rows that score get their
  real answers, so one poison payload fails only its own request.  A
  batch-level predictor exception takes the same per-row salvage path.
  A supervisor thread additionally respawns any thread that truly
  died.
* **Graceful drain** — ``stop(drain=True)`` finishes the queued and
  in-flight work (bounded by a timeout) before the workers exit, so a
  rolling restart answers what it already accepted.

Every degradation is counted: ``stats_snapshot()["counters"]`` always
carries ``shed`` / ``expired`` / ``salvaged`` / ``restarted`` (seeded to
zero), the numbers ``tools/chaos_serving.py`` asserts on.

The fast decode path is :class:`ColumnPlan`: the payload-key → feature-
column mapping is resolved ONCE, so each batch becomes one contiguous
float32 matrix build instead of per-row dict walks through
``request_table``.

Works with any server exposing the exchange contract
(:class:`~mmlspark_tpu.io.serving.HTTPServer`,
:class:`~mmlspark_tpu.io.serving.DistributedHTTPServer`,
:class:`~mmlspark_tpu.io.serving.MultiprocessHTTPServer`).  Queue items
may be ``(rid, payload)`` or ``(rid, payload, t_enqueue)`` — the
in-repo exchanges stamp enqueue time so wait-shedding and deadlines
measure true queue age; unstamped items age from first dequeue.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..core.capacity import capacity_enabled, ensure_capacity_sampler
from ..core.profiler import get_profiler
from ..core.profiling import StageStats
from ..core.schema import DataTable
from ..core.telemetry import get_journal, get_registry, record_flight
from .wire import BinaryReq

log = logging.getLogger(__name__)


class WorkerKilled(BaseException):
    """Chaos/test hook: raised inside a scoring worker to simulate the
    thread dying (a ``BaseException`` so the per-batch ``except
    Exception`` handler does NOT absorb it — it escapes to the worker
    shell exactly like a real crash would)."""


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (bucket ladder for padded scoring)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


class ColumnPlan:
    """Pre-resolved request → float32 feature-matrix decode plan.

    Two layouts, resolved once at construction instead of per batch:

    * ``features="features"`` — each payload carries one key holding a
      length-``num_features`` list (the reference's vector-column
      serving contract).
    * ``features=["f0", "f1", ...]`` — each payload carries one scalar
      per named key; columns are assembled in the given order.

    ``decode`` builds the contiguous ``(n, f)`` float32 matrix straight
    from the payload list — no intermediate :class:`DataTable`, no
    per-row dict-intersection walk.  ``decode_table`` covers callers
    that already hold a table.

    Binary wire (ISSUE 11): payloads may also be float32 row views
    (``np.ndarray`` or :class:`~mmlspark_tpu.io.wire.BinaryReq`) — the
    negotiated raw-float32 wire's ``np.frombuffer`` output.  A batch of
    those assembles with one ``np.concatenate`` (a single-row batch is
    ZERO-copy: the view passes straight through), with the same width
    validation the JSON paths get.  Column order on the binary wire is
    the model's canonical feature order — the identical contract the
    JSON ``features`` vector already used.
    """

    def __init__(self, features: Union[str, Sequence[str]] = "features",
                 num_features: Optional[int] = None):
        if isinstance(features, str):
            self.vector_key: Optional[str] = features
            self.scalar_keys: Tuple[str, ...] = ()
        else:
            self.vector_key = None
            self.scalar_keys = tuple(features)
            if num_features is not None \
                    and num_features != len(self.scalar_keys):
                raise ValueError(
                    f"num_features={num_features} but plan names "
                    f"{len(self.scalar_keys)} scalar columns")
            num_features = len(self.scalar_keys)
        self.num_features = num_features

    def decode(self, payloads: List[Any]) -> np.ndarray:
        """Payload dicts (or binary row views) → C-contiguous ``(n, f)``
        float32 matrix.  A mixed JSON/binary batch takes the engine's
        per-row salvage path (each singleton re-enters here and picks
        its own layout)."""
        if payloads and isinstance(payloads[0], (np.ndarray, BinaryReq)):
            return self.decode_binary(payloads)
        if self.vector_key is not None:
            key = self.vector_key
            X = np.asarray([p[key] for p in payloads], dtype=np.float32)
            if X.ndim != 2:
                raise ValueError(
                    f"payload key {key!r} must hold fixed-length "
                    f"vectors; got ragged/scalar values")
        else:
            X = np.empty((len(payloads), len(self.scalar_keys)),
                         dtype=np.float32)
            for j, key in enumerate(self.scalar_keys):
                X[:, j] = [p[key] for p in payloads]
        if self.num_features is not None \
                and X.shape[1] != self.num_features:
            raise ValueError(
                f"decoded {X.shape[1]} features, model expects "
                f"{self.num_features}")
        return np.ascontiguousarray(X)

    def decode_binary(self, payloads: List[Any]) -> np.ndarray:
        """Binary-wire fast path: each payload is already a float32
        ``(r, f)`` view (``np.frombuffer`` output of
        :func:`~mmlspark_tpu.io.wire.unpack_matrix`); a multi-entry
        batch is ONE ``np.concatenate``, a single entry passes through
        zero-copy.  No JSON, no per-value Python objects."""
        rows = [p.X if isinstance(p, BinaryReq) else p for p in payloads]
        X = rows[0] if len(rows) == 1 else np.concatenate(rows, axis=0)
        if not isinstance(X, np.ndarray) or X.ndim != 2 \
                or X.dtype != np.float32:
            raise ValueError(
                "binary payloads must be (r, f) float32 row blocks")
        if self.num_features is not None \
                and X.shape[1] != self.num_features:
            raise ValueError(
                f"decoded {X.shape[1]} features, model expects "
                f"{self.num_features}")
        return X

    def decode_table(self, table: DataTable) -> np.ndarray:
        """Same plan applied to an already-built :class:`DataTable`."""
        if self.vector_key is not None:
            col = table[self.vector_key]
            if col.dtype == object:
                X = np.asarray([np.asarray(v, np.float32) for v in col],
                               dtype=np.float32)
            else:
                X = np.asarray(col, np.float32)
        else:
            X = np.column_stack(
                [np.asarray(table[k], np.float32)
                 for k in self.scalar_keys])
        return np.ascontiguousarray(X.astype(np.float32, copy=False))


def _json_value(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    return v


class ScoringEngine:
    """Deadline-batched, pipelined scoring over a serving exchange.

    Two scoring modes (exactly one of ``predictor``/``transform``):

    * ``predictor`` — the hot path: a callable ``(n, f) float32 ->
      margins`` (typically ``Booster.predictor()``), fed by a
      :class:`ColumnPlan` fast decode, with power-of-two padded buckets.
      Each reply body is the row's score (scalar for single-class, list
      for multiclass), or whatever ``reply_fn(values) -> list`` builds.
    * ``transform`` — legacy-compatible: a ``DataTable -> DataTable``
      callable; the batch goes through
      :func:`~mmlspark_tpu.io.serving.request_table` and replies come
      from ``reply_col``, exactly like the old ``serve_forever`` body.

    Threads: ``num_scorers`` pipeline workers and ``num_repliers``
    repliers.  Each worker forms its own batch (one former at a time,
    serialized by a lock — deadline semantics preserved), then decodes
    and scores it; while one worker is inside the GIL-releasing native
    kernel, another holds the form lock accumulating the next batch.
    Forming in the scorer thread instead of a dedicated batcher saves a
    bounded-queue hop per batch — two thread wakeups that measurably
    cost throughput at saturation on small hosts.  Repliers are
    separate because ``MultiprocessHTTPServer.reply`` blocks on a
    cross-process ack; ``num_repliers=0`` replies inline on the worker
    (the right choice for in-process exchanges with non-blocking
    ``reply_many`` — and what the ``serve_forever`` shim uses to match
    the old loop's shape exactly).  The reply queue is bounded: when
    repliers fall behind, workers stop pulling and requests
    back-pressure into the exchange queue.

    Resilience knobs (all off/None by default except supervision — the
    fast path is unchanged unless asked):

    * ``max_queue_depth`` — shed (503) the oldest queued requests
      whenever the backlog exceeds this after forming a batch.
    * ``shed_wait_ms`` — shed (503) any request that already waited
      longer than this when a batch closes.
    * ``deadline_ms`` — expire (504) any request older than this at
      batch-close time; a ``_deadline_ms`` payload key overrides it per
      request.  Expired rows are rejected BEFORE scoring.
    * ``supervise`` — run the supervisor thread that respawns worker or
      replier threads that died (the in-place restart on a crash
      happens regardless; see :meth:`_worker_shell`).
    """

    RESILIENCE_COUNTERS = ("shed", "expired", "salvaged", "restarted")

    def __init__(self, server, *,
                 predictor: Optional[Callable] = None,
                 plan: Optional[ColumnPlan] = None,
                 transform: Optional[Callable[[DataTable], DataTable]]
                 = None,
                 reply_col: str = "prediction",
                 max_rows: int = 256,
                 latency_budget_ms: float = 5.0,
                 num_scorers: int = 2,
                 num_repliers: int = 1,
                 queue_depth: int = 8,
                 pad_buckets: Optional[bool] = None,
                 reply_fn: Optional[Callable[[np.ndarray], List[Any]]]
                 = None,
                 on_error: str = "reply",
                 max_queue_depth: Optional[int] = None,
                 shed_wait_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 supervise: bool = True,
                 stats: Optional[StageStats] = None,
                 drift_monitor=None,
                 ingest_tap: Optional[Callable] = None):
        if (predictor is None) == (transform is None):
            raise ValueError(
                "pass exactly one of predictor= (hot path) or "
                "transform= (DataTable->DataTable legacy path)")
        if on_error not in ("reply", "raise"):
            raise ValueError("on_error must be 'reply' (500 the batch, "
                             "keep serving) or 'raise' (stop and "
                             "re-raise from serve())")
        if predictor is not None and plan is None:
            # wire the predictor's known width into the auto plan so a
            # wrong-width payload fails at decode time as a per-row 400
            # instead of blowing up the whole batch at score time and
            # coming back as salvage-path 500s (review finding)
            plan = ColumnPlan(
                num_features=getattr(predictor, "num_features", None))
        if pad_buckets is None:
            # padding buys a bounded compile cache on the JIT walk; the
            # native kernel has no shape-specialized compilation, so
            # padding there only scores phantom rows.  Unknown callables
            # (no .mode) are assumed jit-like and padded.
            pad_buckets = getattr(predictor, "mode", "jit") != "native"
        # rid-routed predictors (the RolloutController's blue/green
        # traffic splitter, ISSUE 14): the engine hands the batch's
        # request ids alongside the matrix so the split is per-request
        # and retry-stable.  Engine-level padding is disabled — padded
        # phantom rows have no rid to route; the splitter pads each
        # arm's sub-batch itself.
        self._routed = bool(getattr(predictor, "routes_by_rid", False))
        if self._routed:
            pad_buckets = False
        self._server = server
        self._predictor = predictor
        self._plan = plan
        self._transform = transform
        self._reply_col = reply_col
        self._max_rows = int(max_rows)
        self._budget = float(latency_budget_ms) / 1e3
        self._num_scorers = max(1, int(num_scorers))
        self._num_repliers = max(0, int(num_repliers))
        self._pad_buckets = bool(pad_buckets)
        self._reply_fn = reply_fn
        # binary-wire reply mode (ISSUE 11): when the exchange can ship
        # raw margin blocks (MultiprocessHTTPServer.binary_wire), reply
        # values stay numpy — sliced straight off the margin ndarray —
        # and the per-row tolist()/_json_value builds are skipped; the
        # exchange serializes per session (binary frame or negotiated
        # JSON fallback) at delivery time
        self._ndarray_replies = bool(getattr(server, "binary_wire",
                                             False)) \
            and reply_fn is None
        self._on_error = on_error
        self._max_queue_depth = (None if max_queue_depth is None
                                 else int(max_queue_depth))
        self._shed_wait = (None if shed_wait_ms is None
                           else float(shed_wait_ms) / 1e3)
        self._deadline = (None if deadline_ms is None
                          else float(deadline_ms) / 1e3)
        self._supervise = bool(supervise)
        # streaming data-quality sketches (ISSUE 15): when a
        # DriftMonitor is attached, every scored batch is offered to it
        # (decoded float32 rows + margins) behind the monitor's own
        # duty-cycle gate; with no monitor the hot path pays ONE
        # attribute check per batch.  start() installs it process-wide
        # (ns="drift" + the mmlspark_tpu_drift_* exposition) so the
        # SLO drift objectives and the worker stats beacon see it.
        self._drift = drift_monitor
        # streaming-ingest tap (ISSUE 18): called with every scored
        # batch's decoded rows + margins, AFTER the reply-side work is
        # queued conceptually (same placement as the drift observe).
        # The deployment decides what a "label" is at this point —
        # typically enqueue features keyed by rid until ground truth
        # arrives; the drills append with labels they know.  Advisory
        # like the drift tap: a raising tap is counted and dropped,
        # never an answer lost.  Deliberately SYNCHRONOUS, unlike the
        # duty-gated drift sketches: the tap must see 100% of rows (it
        # is the training feed), and on the small hosts this serves
        # from, a handoff queue + drain thread costs more in wakeup
        # churn than the bin+append it would hide (no-op async tap
        # measured 5.6% p50 on 1 core vs 0.04% inline; the spill fsync
        # is amortized over segment_rows).
        self._ingest_tap = ingest_tap
        self._fatal: Optional[BaseException] = None
        self._died = threading.Event()
        self.stats = stats or StageStats()
        for name in self.RESILIENCE_COUNTERS:
            self.stats.incr(name, 0)     # observable zeros
        self._journal = get_journal()
        # continuous-profiler wiring (ISSUE 12), zero-overhead flavor:
        # the stage histograms this engine ALREADY records are ALIASED
        # into the profile view (shared LatencyStats objects), so the
        # scoring.* phases cost nothing extra per batch; only the
        # dispatch bracketing in _score_matrix adds hot-path work, on
        # pre-resolved timers behind one `enabled` check
        self._prof = get_profiler()
        # pre-resolved stage timers: the pipeline records through these
        # with OUTER windows (decode covers payload extraction, score
        # covers result assembly, reply covers the whole delivery), so
        # the named phases tile the e2e wall time — the perf_report
        # >=90%-attributed acceptance bar depends on this tiling
        self._pt_form = self.stats.timer("batch_form")
        self._pt_decode = self.stats.timer("decode")
        self._pt_score = self.stats.timer("score")
        self._pt_reply = self.stats.timer("reply")
        self._pt_e2e = self.stats.timer("e2e")
        self._pt_queue_wait = self.stats.timer("queue_wait")
        self._prof.alias("scoring.form", self._pt_form)
        self._prof.alias("scoring.decode", self._pt_decode)
        self._prof.alias("scoring.score", self._pt_score)
        self._prof.alias("scoring.reply", self._pt_reply)
        self._prof.alias("scoring.e2e", self._pt_e2e)
        self._prof.alias("scoring.queue_wait", self._pt_queue_wait)
        # saturation taps (ISSUE 20): the enabled flag is CACHED here —
        # per-batch tap sites pay one attribute check when capacity
        # observability is off (the sentinel A/B constructs a fresh
        # engine per arm, so flipping capacity.configure() between
        # bursts is the whole switch).  queue_age records the batch-max
        # true queue age at admission (stamped exchanges only): the
        # capacity monitor's knee estimator reads its windowed p50 —
        # queueing delay is where saturation shows first, and e2e
        # deliberately excludes it
        self._cap_taps = capacity_enabled()
        self._pt_queue_age = self.stats.timer("queue_age")
        self._prof.alias("scoring.queue_age", self._pt_queue_age)
        # journaling is hot-path work too: attributing it explicitly
        # is what lets perf_report explain >=90% of e2e instead of
        # showing an anonymous gap
        self._pt_trace = self.stats.timer("trace")
        self._prof.alias("scoring.trace", self._pt_trace)
        # engine-owned like every other stage (newest engine wins the
        # profile view) — a process-lifetime accumulator here would mix
        # windows with the per-engine e2e and break the attribution
        self._pt_disp_host = self.stats.timer("dispatch_host")
        self._pt_disp_wait = self.stats.timer("device_wait")
        self._prof.alias("scoring.dispatch_host", self._pt_disp_host)
        self._prof.alias("scoring.device_wait", self._pt_disp_wait)
        self._reply_q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._threads: List[threading.Thread] = []
        self._supervisor_thread: Optional[threading.Thread] = None
        self._form_lock = threading.Lock()   # one batch former at a time
        self._inflight = 0          # batches being decoded/scored
        self._inflight_lock = threading.Lock()
        # worker slot -> (batch, t_first) being scored; the supervisor /
        # worker shell salvages this when the worker crashes mid-batch
        self._current: dict = {}
        self._reply_many = getattr(server, "reply_many", None)
        self._request_q = getattr(server, "request_queue", None)
        if self._request_q is None:  # duck-typed custom servers
            exchange = getattr(server, "_exchange", None)
            self._request_q = getattr(exchange, "queue", None)
        self._get_batch = None
        if self._request_q is None:
            # legacy duck type (pre-engine serve_forever contract): a
            # server exposing only get_batch()/reply() still works —
            # batches form through pulls instead of raw queue reads
            self._get_batch = getattr(server, "get_batch", None)
            if self._get_batch is None:
                raise TypeError(
                    "server must expose request_queue, _exchange.queue, "
                    "or the legacy get_batch() contract")

    # -- tracing -------------------------------------------------------------

    @staticmethod
    def _tid(entry) -> str:
        """A request's trace id: the ``_trace_id`` its client sent in
        the payload, else the request id (minted at admission by the
        exchange) — every request is traceable without client opt-in,
        and a client-chosen id survives the worker hop because it rides
        the payload."""
        payload = entry[1]
        if isinstance(payload, dict):
            tid = payload.get("_trace_id")
            if tid:
                return str(tid)
        return str(entry[0])

    def _trace(self, ev: str, batch, **fields) -> None:
        """Journal one per-batch pipeline event carrying the batch's
        request ids and trace ids — ``tools/trace_report.py`` stitches
        these into per-request form→decode→score→reply timelines.
        The emit cost (id-list builds + ring insert) is itself timed
        into the ``trace`` stage / ``scoring.trace`` phase."""
        t0 = time.perf_counter()
        self._journal.emit(ev, rids=[str(e[0]) for e in batch],
                           trace_ids=[self._tid(e) for e in batch],
                           **fields)
        self._pt_trace.record(time.perf_counter() - t0)

    # -- batch forming -------------------------------------------------------

    @staticmethod
    def _norm(item, now: Optional[float] = None
              ) -> Tuple[str, Any, float]:
        """Queue items are ``(rid, payload)`` or ``(rid, payload,
        t_enqueue)``; unstamped items age from first dequeue."""
        if len(item) >= 3:
            return item[0], item[1], item[2]
        return item[0], item[1],  \
            now if now is not None else time.perf_counter()

    def _form_batch(self) -> Optional[
            Tuple[List[Tuple[str, Any, float]], float,
                  List[Tuple[str, Any, int]]]]:
        """Adaptive, deadline-aware close.  A batch closes when:

        * ``max_rows`` requests are aboard (size cap), or
        * the batch has been open for ``latency_budget`` (deadline), or
        * the queue is dry AND no other worker is scoring a batch
          (work-conserving: holding requests to fill a batch only pays
          while the pipeline couldn't start them anyway — if every
          scorer is idle, shipping now costs nothing and saves the
          wait).

        The budget clock starts when the batch OPENS (first dequeue) —
        for exchanges that stamp enqueue time the shed/deadline checks
        additionally see true queue age; for unstamped items (bare
        2-tuples) age starts at dequeue and the ``e2e`` stat excludes
        queueing delay (the benchmark's client-side percentiles capture
        it).

        Admission control runs at batch close: overflow past
        ``max_queue_depth`` is shed from the queue head, then each
        formed row is checked against its deadline (expired → 504,
        never scored) and the wait budget (over → 503 shed).

        Returns ``(live_batch, t_first, error_replies)``; ``None`` on
        an idle poll tick.  ``error_replies`` are the shed/expired
        ``(rid, body, status)`` entries — delivered by the CALLER after
        the form lock is released, because the multiprocess reply path
        blocks on cross-process acks and must not stall every other
        former."""
        if self._request_q is None:
            return self._form_batch_pulling()
        q = self._request_q
        try:
            first = q.get(timeout=0.05)
        except queue.Empty:
            return None
        t_first = time.perf_counter()
        batch: List[Tuple[str, Any, float]] = []
        shed: List[Tuple[str, Any, float]] = []
        try:
            batch.append(self._norm(first, t_first))
            deadline = t_first + self._budget
            while len(batch) < self._max_rows:
                try:
                    batch.append(self._norm(q.get_nowait()))
                    continue
                except queue.Empty:
                    pass
                now = time.perf_counter()
                if now >= deadline:
                    break
                with self._inflight_lock:
                    busy = self._inflight > 0
                if not busy:
                    break    # scorers idle: ship immediately
                try:
                    batch.append(self._norm(
                        q.get(timeout=min(deadline - now, 1e-3))))
                except queue.Empty:
                    continue
            qsize = getattr(q, "qsize", None)
            if self._max_queue_depth is not None and qsize is not None:
                # bounded intake: the backlog beyond the bound is shed
                # NOW with an explicit reply instead of queueing
                # unboundedly.  Dropping from the head sheds the oldest
                # waiters — the requests closest to their deadlines.
                while qsize() > self._max_queue_depth:
                    try:
                        shed.append(self._norm(q.get_nowait()))
                    except queue.Empty:
                        break
            if self._cap_taps:
                # batch-close saturation taps (ISSUE 20): the residual
                # backlog after this batch formed, and how full the
                # batch is against its row cap — both per BATCH, not
                # per row
                if qsize is not None:
                    try:
                        self.stats.set_gauge("queue_depth",
                                             float(qsize()))
                    except (NotImplementedError, OSError):
                        pass
                self.stats.set_gauge(
                    "batch_occupancy",
                    round(len(batch) / max(1, self._max_rows), 4))
            live, errors = self._admit(batch, shed)
        except Exception:  # noqa: BLE001 - form-path bug / bad item
            # rows already pulled off the queue MUST still get replies:
            # without this, a forming crash (malformed queue item, a
            # duck-typed queue quirk) silently drops them and their
            # clients hang until the handler timeout
            return [], t_first, self._error_all(batch + shed)
        return live, t_first, errors

    def _form_batch_pulling(self) -> Optional[
            Tuple[List[Tuple[str, Any, float]], float,
                  List[Tuple[str, Any, int]]]]:
        """Same close policy over the legacy ``get_batch()`` contract
        (servers that expose no raw queue; no depth-based shedding —
        the queue is invisible here, but wait/deadline checks apply)."""
        pulled = self._get_batch(self._max_rows, 0.05)
        if not pulled:
            return None
        t_first = time.perf_counter()
        batch: List[Tuple[str, Any, float]] = []
        try:
            batch = [self._norm(it, t_first) for it in pulled]
            deadline = t_first + self._budget
            while len(batch) < self._max_rows:
                now = time.perf_counter()
                if now >= deadline:
                    break
                with self._inflight_lock:
                    busy = self._inflight > 0
                if not busy:
                    break    # scorers idle: ship immediately
                batch += [self._norm(it, now) for it in
                          self._get_batch(self._max_rows - len(batch),
                                          min(deadline - now, 1e-3))]
            live, errors = self._admit(batch, [])
        except Exception:  # noqa: BLE001 - pulled rows must get replies
            return [], t_first, self._error_all(batch)
        return live, t_first, errors

    def _error_all(self, entries) -> List[Tuple[str, Any, int]]:
        """Last-resort 500s for rows stranded by a forming crash; an
        entry too malformed to even yield a request id is logged and
        dropped (nothing to address a reply to)."""
        log.exception("batch forming failed; erroring %d dequeued rows",
                      len(entries))
        errors = []
        for e in entries:
            try:
                errors.append((e[0], {"error": "scoring failed"}, 500))
            except Exception:  # noqa: BLE001 - unaddressable item
                log.warning("dropping unaddressable queue item %r", e)
        return errors

    def _admit(self, batch, shed):
        """Split a formed batch into live rows vs shed/expired ones and
        build the explicit degradation replies (503 shed / 504
        expired).  Runs at batch-close time, BEFORE any scoring — an
        expired request never burns a batch slot.  Returns
        ``(live, error_replies)``; the caller delivers the errors
        outside the form lock."""
        now = time.perf_counter()
        live, expired = [], []
        max_age = 0.0
        for entry in batch:
            rid, payload, t_enq = entry
            age = now - t_enq
            if age > max_age:
                max_age = age
            dl = self._deadline
            if isinstance(payload, dict) and "_deadline_ms" in payload:
                try:
                    dl = float(payload["_deadline_ms"]) / 1e3
                except (TypeError, ValueError):
                    pass
            elif isinstance(payload, BinaryReq) and payload.deadline_ms:
                # binary wire: the deadline rode the frame header (no
                # payload keys exist to carry it)
                try:
                    dl = float(payload.deadline_ms) / 1e3
                except (TypeError, ValueError):
                    pass
            if dl is not None and age > dl:
                expired.append(entry)
            elif self._shed_wait is not None and age > self._shed_wait:
                shed.append(entry)
            else:
                live.append(entry)
        if self._cap_taps and batch:
            # admission tap (ISSUE 20): one histogram insert per batch
            # with the WORST queue age aboard — true queue age for
            # stamped exchanges, ~0 for unstamped 2-tuples
            self._pt_queue_age.record(max_age)
        errors = []
        if shed:
            self.stats.incr("shed", len(shed))
            self._trace("shed", shed)
            errors += [(e[0], {"error": "shed"}, 503) for e in shed]
        if expired:
            self.stats.incr("expired", len(expired))
            self._trace("expired", expired)
            errors += [(e[0], {"error": "expired"}, 504)
                       for e in expired]
        return live, errors

    def _reply_errors(self, entries) -> None:
        """Deliver explicit degradation replies (shed/expired/crash) —
        no latency timers, these are not scored rows."""
        try:
            if self._reply_many is not None:
                self._reply_many(entries)
            else:
                for rid, body, status in entries:
                    self._server.reply(rid, body, status)
        except Exception:  # noqa: BLE001 - reply path must not kill form
            log.exception("failed delivering %d degradation replies",
                          len(entries))

    def _worker(self, slot: int) -> None:
        """Pipeline worker: form (serialized) → decode → score → reply
        (inline or handed to a replier)."""
        while True:
            with self._form_lock:
                if self._stop.is_set():
                    return
                formed = self._form_batch()
            if formed is None:
                if self._draining.is_set():
                    return   # drain mode: queue dry — exit cleanly
                continue
            batch, t_first, errors = formed
            if errors:
                # shed/expired replies, delivered OUTSIDE the form lock
                # (the multiprocess reply path blocks on acks)
                self._reply_errors(errors)
            if not batch:
                continue     # everything formed was shed/expired
            form_s = time.perf_counter() - t_first
            self._pt_form.record(form_s)
            self._trace("form", batch, rows=len(batch),
                        dur_ms=round(form_s * 1e3, 3))
            self._current[slot] = (batch, t_first)
            with self._inflight_lock:
                self._inflight += 1
                inflight = self._inflight
            if self._cap_taps:
                # scorer utilization at batch start: the fraction of
                # scorer slots busy the moment this batch shipped
                self.stats.set_gauge(
                    "worker_busy",
                    round(inflight / self._num_scorers, 4))
            try:
                if self._predictor is not None:
                    pairs = self._score_predictor(batch)
                else:
                    pairs = self._score_transform(batch)
            except Exception as e:  # noqa: BLE001
                if self._on_error == "raise":
                    # legacy serve_forever semantics: a transform bug
                    # stops the loop and surfaces from serve()
                    self._fatal = e
                    self._died.set()
                    self._stop.set()
                    return
                # hot-path semantics: a bad batch must not kill the
                # worker — salvage it row by row so one poison payload
                # fails only its own request
                log.exception("scoring batch of %d failed; salvaging "
                              "per-row", len(batch))
                pairs = self._salvage_batch(batch)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
            if self._num_repliers == 0:
                self._deliver(pairs, t_first)
            else:
                self._reply_q.put((pairs, t_first, time.perf_counter()))
            self._current.pop(slot, None)

    def _worker_shell(self, slot: int) -> None:
        """Crash boundary around :meth:`_worker`: anything escaping the
        per-batch handler (a :class:`WorkerKilled` chaos injection, a
        bug in the form/deliver path) restarts the worker in place
        after salvaging the batch it held — the engine's worker-
        supervision contract.  ``KeyboardInterrupt``/``SystemExit``
        still propagate."""
        while True:
            try:
                self._worker(slot)
                return                        # clean stop/drain exit
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 - crash boundary
                if self._stop.is_set():
                    return
                log.exception("scoring worker %d crashed; restarting",
                              slot)
                self.stats.incr("restarted")
                inflight = self._current.pop(slot, None)
                # the restart erases the crash scene — capture it first
                # (throttled + rotated inside record_flight, so a
                # crash-looping worker cannot flood the disk)
                record_flight(
                    "scoring_worker_crash",
                    {"slot": slot, "error": repr(e),
                     "batch_rows": len(inflight[0]) if inflight else 0})
                if inflight is not None:
                    self._salvage_crashed(*inflight)

    def _salvage_crashed(self, batch, t_first: float) -> None:
        """Recover the batch a crashed worker held: score it row by row
        and deliver; a second crash during salvage fails the remaining
        rows with explicit 500s (bounded — a worker that dies on every
        call must not loop forever on one batch).  A crash after
        partial delivery can re-reply rows the exchange already
        routed; the exchange drops replies to popped ids, and the
        salvage re-scores the same rows so a double reply carries the
        identical value."""
        try:
            pairs = self._salvage_batch(batch)
            self._deliver(pairs, t_first)
        except BaseException:  # noqa: BLE001 - salvage must terminate
            log.exception("salvage of crashed batch failed; erroring "
                          "%d rows", len(batch))
            self._reply_errors([(e[0], {"error": "scoring failed"}, 500)
                                for e in batch])

    def _salvage_batch(self, batch):
        """Batch-level scoring failed: retry each row alone so only the
        poison row(s) fail.  Rows rescued this way count as
        ``salvaged``."""
        score_one = (self._score_predictor if self._predictor is not None
                     else self._score_transform)
        pairs, rescued = [], 0
        for entry in batch:
            try:
                row_pairs = score_one([entry])
            except Exception:  # noqa: BLE001 - this row is the poison
                pairs.append((entry[0], {"error": "scoring failed"},
                              500))
                continue
            # a 2-tuple result row scored; 3-tuples are decode 400s
            rescued += sum(1 for p in row_pairs if len(p) == 2)
            pairs.extend(row_pairs)
        if rescued:
            self.stats.incr("salvaged", rescued)
        self._trace("salvage", batch, rescued=rescued)
        return pairs

    def _supervisor(self) -> None:
        """Belt-and-braces thread supervision: the worker shell restarts
        crashes in place, but a thread that truly died (shell itself
        failed, replier crashed) is respawned here so capacity
        recovers."""
        while not self._stop.wait(0.2):
            if self._draining.is_set():
                continue     # drain exits are legitimate deaths
            for i, t in enumerate(self._threads):
                if t.is_alive() or self._stop.is_set():
                    continue
                scorer = i < self._num_scorers
                log.warning("%s thread %d found dead; respawning",
                            "scoring" if scorer else "replier", i)
                self.stats.incr("restarted")
                if scorer:
                    nt = threading.Thread(target=self._worker_shell,
                                          args=(i,),
                                          name=f"scoring-worker-{i}",
                                          daemon=True)
                else:
                    nt = threading.Thread(
                        target=self._replier,
                        name=f"scoring-replier-{i}", daemon=True)
                self._threads[i] = nt
                nt.start()

    # -- scoring -------------------------------------------------------------

    def _score_matrix(self, X: np.ndarray, n: int,
                      rids: Optional[List[str]] = None) -> List[Any]:
        """Pad to the power-of-two bucket, score, slice, format.
        Callers own the ``score`` stage bracket (their window also
        covers the per-batch result assembly, so the named phases tile
        the e2e wall time instead of leaking glue between brackets).
        For rid-routed predictors (``routes_by_rid``) the rids ride
        along so the splitter pins each row to its arm."""
        X_rows = X          # unpadded view for the drift sketches
        if self._pad_buckets:
            b = next_pow2(n)
            if b > n:
                Xp = np.zeros((b, X.shape[1]), np.float32)
                Xp[:n] = X
                X = Xp
        scorer = self._predictor
        if self._routed and rids is not None:
            def scorer(M, _p=self._predictor, _r=rids):  # noqa: E731
                return _p.score_routed(M, _r)
        if self._prof.enabled:
            # dispatch bracketing (ISSUE 12): host time until the
            # scorer call returns vs wait until the result
            # materializes (np.asarray blocks), with compile-seq
            # delta classifying the dispatch as cache hit/miss
            prof = self._prof
            seq0 = prof._compile_seq
            t0 = time.perf_counter()
            raw = scorer(X)
            t_host = time.perf_counter()
            m = np.asarray(raw)[:n]
            self._pt_disp_host.record(t_host - t0)
            self._pt_disp_wait.record(time.perf_counter() - t_host)
            prof.count_dispatch("scoring",
                                prof._compile_seq - seq0)
        else:
            m = np.asarray(scorer(X))[:n]
        if self._drift is not None:
            # live-traffic sketches (duty-cycle gated inside; never
            # raises) — rows as decoded, margins as scored
            self._drift.observe(X_rows[:n], m)
        if self._ingest_tap is not None:
            try:
                self._ingest_tap(X_rows[:n], m)
            except Exception:   # noqa: BLE001 - tap is advisory
                self.stats.incr("ingest_tap_errors")
                log.exception("ingest tap failed; batch not retained")
        if self._reply_fn is not None:
            return self._reply_fn(m)
        if self._ndarray_replies:
            # binary wire: hand the margin ndarray through — indexing
            # yields numpy scalars/row views the exchange serializes
            # straight into a float32 reply block (no tolist())
            return m
        return m.tolist()

    def _score_predictor(self, batch):
        t0 = time.perf_counter()
        try:
            X = self._plan.decode([e[1] for e in batch])
        except Exception:  # noqa: BLE001 - malformed row(s) aboard
            X = None
        dec_s = time.perf_counter() - t0
        self._pt_decode.record(dec_s)
        self._trace("decode", batch, dur_ms=round(dec_s * 1e3, 3),
                    **({"fallback": "per_row"} if X is None else {}))
        if X is None:
            return self._score_predictor_salvage(batch)
        t1 = time.perf_counter()
        vals = self._score_matrix(X, X.shape[0],
                                  rids=[str(e[0]) for e in batch])
        pairs = [(e[0], vals[i]) for i, e in enumerate(batch)]
        score_s = time.perf_counter() - t1
        self._pt_score.record(score_s)
        self._trace("score", batch, rows=X.shape[0],
                    dur_ms=round(score_s * 1e3, 3))
        return pairs

    def _score_predictor_salvage(self, batch):
        """The vectorized decode failed: decode per row so ONE malformed
        payload gets its own 400 instead of failing every co-batched
        request (a single misbehaving client must not error out up to
        ``max_rows`` innocent neighbors)."""
        t_dec = time.perf_counter()
        rows, order, good, bad = [], [], [], []
        width = self._plan.num_features
        for entry in batch:
            rid, p = entry[0], entry[1]
            try:
                r = self._plan.decode([p])
            except Exception:  # noqa: BLE001
                bad.append(rid)
                continue
            if width is None:
                width = r.shape[1]
            if r.shape[1] != width:
                bad.append(rid)
                continue
            rows.append(r[0])
            order.append(rid)
            good.append(entry)
        out = [(rid, {"error": "bad request"}, 400) for rid in bad]
        self._pt_decode.record(time.perf_counter() - t_dec)
        if rows:
            X = np.ascontiguousarray(np.stack(rows))
            t0 = time.perf_counter()
            # salvage keeps each surviving row's rid: a routed
            # predictor re-pins it to the SAME arm the vectorized
            # attempt would have used (retry-stable routing)
            vals = self._score_matrix(X, len(rows),
                                      rids=[str(r) for r in order])
            out += [(rid, vals[i]) for i, rid in enumerate(order)]
            score_s = time.perf_counter() - t0
            self._pt_score.record(score_s)
            self._trace("score", good, rows=len(rows),
                        dur_ms=round(score_s * 1e3, 3))
        return out

    def _score_transform(self, batch):
        from .serving import request_table
        t0 = time.perf_counter()
        table = request_table(batch)
        dec_s = time.perf_counter() - t0
        self._pt_decode.record(dec_s)
        self._trace("decode", batch, dur_ms=round(dec_s * 1e3, 3))
        t1 = time.perf_counter()
        out = self._transform(table)
        ids = out["id"]
        vals = out[self._reply_col]
        if self._ndarray_replies:
            # binary-negotiated exchange: skip the per-row _json_value
            # build — the exchange serializes numpy values from the
            # column directly (float32 block per batch)
            pairs = [(str(rid), v) for rid, v in zip(ids, vals)]
        else:
            pairs = [(str(rid), _json_value(v))
                     for rid, v in zip(ids, vals)]
        score_s = time.perf_counter() - t1
        self._pt_score.record(score_s)
        self._trace("score", batch, rows=len(batch),
                    dur_ms=round(score_s * 1e3, 3))
        return pairs

    # -- replies -------------------------------------------------------------

    def _deliver(self, pairs, t_first: float) -> None:
        t0 = time.perf_counter()
        if self._reply_many is not None:
            self._reply_many(
                [(e[0], e[1], e[2] if len(e) > 2 else 200)
                 for e in pairs])
        else:
            for entry in pairs:
                rid, val = entry[0], entry[1]
                status = entry[2] if len(entry) > 2 else 200
                self._server.reply(rid, val, status)
        reply_s = time.perf_counter() - t0
        self._pt_reply.record(reply_s)
        # reply pairs carry no payload, so only rids ride this event;
        # the reader recovers a client trace id from the form event
        t_tr = time.perf_counter()
        self._journal.emit(
            "reply", rids=[str(e[0]) for e in pairs],
            statuses=[e[2] if len(e) > 2 else 200 for e in pairs],
            dur_ms=round(reply_s * 1e3, 3))
        self._pt_trace.record(time.perf_counter() - t_tr)
        e2e_s = time.perf_counter() - t_first
        self._pt_e2e.record(e2e_s)
        self.stats.add_rows(len(pairs))

    def _replier(self) -> None:
        while True:
            item = self._reply_q.get()
            if item is None:
                return
            pairs, t_first, t_handoff = item
            wait_s = time.perf_counter() - t_handoff
            self._pt_queue_wait.record(wait_s)
            try:
                self._deliver(pairs, t_first)
            except Exception:  # noqa: BLE001 - one bad delivery must
                # not kill the replier (dropping every queued batch and
                # wedging workers on the bounded reply queue); give the
                # batch explicit 500s and keep draining
                log.exception("reply delivery failed; erroring %d rows",
                              len(pairs))
                self._reply_errors(
                    [(e[0], {"error": "scoring failed"}, 500)
                     for e in pairs])

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ScoringEngine":
        self._stop.clear()
        self._draining.clear()
        self._died.clear()
        self._fatal = None
        self._current.clear()
        self._threads = [
            threading.Thread(target=self._worker_shell, args=(i,),
                             name=f"scoring-worker-{i}", daemon=True)
            for i in range(self._num_scorers)]
        self._threads += [
            threading.Thread(target=self._replier,
                             name=f"scoring-replier-{i}", daemon=True)
            for i in range(self._num_repliers)]
        for t in self._threads:
            t.start()
        if self._supervise:
            self._supervisor_thread = threading.Thread(
                target=self._supervisor, name="scoring-supervisor",
                daemon=True)
            self._supervisor_thread.start()
        # readiness wiring: servers exposing a ready_check slot (the
        # /readyz endpoint) report this engine's liveness
        if hasattr(self._server, "ready_check"):
            try:
                self._server.ready_check = self.is_ready
            except AttributeError:
                pass
        # telemetry wiring: the newest live engine owns the "scoring"
        # namespace — /metrics scrapes (and the multiprocess driver's
        # render_metrics) see its stage latencies and resilience
        # counters without any per-server plumbing
        get_registry().register("scoring", self.stats)
        if self._cap_taps:
            # saturation wiring (ISSUE 20): observable zeros for the
            # instantaneous gauges, and the process-global capacity
            # sampler (knee estimation, busy fractions, headroom SLO
            # gauges) ticking wherever an engine serves
            self.stats.set_gauge("queue_depth", 0.0)
            self.stats.set_gauge("batch_occupancy", 0.0)
            self.stats.set_gauge("worker_busy", 0.0)
            ensure_capacity_sampler()
        if self._drift is not None:
            # the newest engine's monitor owns ns="drift" (and the
            # mmlspark_tpu_drift_* families), same semantics as above
            from ..core.drift import set_drift_monitor
            set_drift_monitor(self._drift)
        return self

    def is_ready(self) -> bool:
        """Liveness for ``/readyz``: started, not stopping, and at
        least one scoring worker alive."""
        if not self._threads or self._stop.is_set() \
                or self._draining.is_set():
            return False
        return any(t.is_alive()
                   for t in self._threads[:self._num_scorers])

    def stop(self, drain: bool = False, drain_timeout: float = 10.0
             ) -> None:
        """Drain-and-join.  Default: workers stop pulling at their next
        form tick (finishing the batch in hand, replies included), then
        repliers drain on sentinels.  With ``drain=True`` the workers
        first keep forming until the request queue runs dry (bounded by
        ``drain_timeout``), so everything already accepted is answered
        before exit — the graceful-restart path.  Callers should stop
        intake (server accept) first or the drain chases a moving
        queue until the timeout."""
        if drain and not self._stop.is_set():
            self._draining.set()
            deadline = time.monotonic() + drain_timeout
            for t in self._threads[:self._num_scorers]:
                t.join(timeout=max(0.0,
                                   deadline - time.monotonic()))
        self._stop.set()
        self._draining.set()   # unblock any drain-mode check
        for t in self._threads[:self._num_scorers]:
            t.join(timeout=5)
        for _ in range(self._num_repliers):
            self._reply_q.put(None)
        for t in self._threads[self._num_scorers:]:
            t.join(timeout=5)
        if self._supervisor_thread is not None:
            self._supervisor_thread.join(timeout=5)
            self._supervisor_thread = None
        self._threads = []

    def serve(self, stop_event: Optional[threading.Event] = None) -> None:
        """Blocking convenience: start, wait for ``stop_event`` (forever
        when ``None``), then drain and stop — the ``serve_forever``
        calling convention.  With ``on_error="raise"``, a scoring
        exception stops the engine and re-raises here."""
        self.start()
        try:
            while not self._died.is_set() \
                    and (stop_event is None or not stop_event.is_set()):
                if stop_event is not None:
                    stop_event.wait(0.2)
                else:
                    self._died.wait(0.2)
        finally:
            self.stop()
        if self._fatal is not None:
            raise self._fatal

    # -- observability -------------------------------------------------------

    def stats_snapshot(self) -> dict:
        """Rows/s plus per-stage count/mean/p50/p99 and the resilience
        counters (``shed``/``expired``/``salvaged``/``restarted``) —
        the numbers the serving BENCH and chaos artifacts record."""
        return self.stats.snapshot()
