"""Deterministic fault injection for the serving stack (chaos harness).

The training side has had a fault harness since the seed (chunk replay in
``gbdt/engine.py`` + ``tests/test_fault_tolerance.py``); this module is
the serving-side equivalent: seeded, deterministic injectors that wrap
the pieces of the serving pipeline so tests and the
``tools/chaos_serving.py`` drill can prove the resilience layer's
contract — *zero wrong answers, every non-delivered request gets an
explicit reply, ready again when the faults stop* — instead of asserting
it rhetorically.

Determinism model: every injector draws its decisions from a
:class:`ChaosChannel`, an independently seeded RNG stream keyed by
``(seed, channel name)``.  Channels are independent, so thread
interleaving across subsystems (a socket injector racing a predictor
injector) never changes any single subsystem's decision sequence — the
k-th send on a given socket channel fires or not regardless of what the
predictor did.  Within one channel the sequence is a pure function of
the seed and the call index.

Injectors:

* :class:`ChaosPredictor` — wraps a scoring callable; injects batch
  exceptions (ordinary ``RuntimeError`` → the engine's per-row salvage
  path) and worker kills (:class:`~mmlspark_tpu.io.scoring.WorkerKilled`,
  a ``BaseException`` → the engine's supervision/restart path) at
  deterministic call indices or rates.
* :class:`ChaosQueue` — wraps a ``queue.Queue``; stalls ``get`` calls to
  simulate a wedged intake.
* :class:`ChaosSocket` — wraps a connected socket; injects connection
  resets (RST via ``SO_LINGER 0``), partial writes, and slow reads and
  writes — drive it from a client to exercise the server's slow-client
  deadlines and reset handling.
* :func:`kill_process` — SIGKILL a worker process (the multiprocess
  drill's executor-loss injection).

Training-channel injectors (the ``tools/chaos_training.py`` drill and
``tests/test_chaos_training.py`` smoke; ISSUE 4):

* :class:`ChaosBoostStep` — wraps a chunk-step callable (the engine's
  ``_boost_scan`` family or a distributed step) and raises at
  deterministic chunk indices or rates — exercises the
  ``faultTolerantRetries`` replay path.
* :func:`corrupt_file` — torn-write truncation or deterministic
  bit-flip of a checkpoint snapshot; the engine must degrade to a
  fresh fit, never train on garbage.
* :class:`ChaosHeartbeat` — a watchdog ``write_hook`` that stalls
  heartbeat writes, driving the elastic layer's straggler / lease
  machinery.
"""

from __future__ import annotations

import os
import queue
import random
import signal
import struct
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from .scoring import WorkerKilled
from .transport import T_ACK as _T_ACK

__all__ = [
    "ChaosBoostStep", "ChaosChannel", "ChaosControllerKill",
    "ChaosDrift", "ChaosHeartbeat", "ChaosPlan", "ChaosPredictor",
    "ChaosQueue", "ChaosSocket", "ChaosTransport", "WorkerKilled",
    "corrupt_file", "kill_process", "read_ckpt_boundary",
]


class ChaosChannel:
    """One independently seeded decision stream.

    ``fire(rate)`` is the k-th Bernoulli draw of this channel — the
    sequence depends only on ``(seed, name)`` and the call index, never
    on other channels or thread timing elsewhere.
    """

    def __init__(self, seed: Any, name: str):
        self.name = name
        self._rng = random.Random(f"{seed}:{name}")
        self._lock = threading.Lock()
        self.calls = 0
        self.fired = 0

    def fire(self, rate: float) -> bool:
        """Deterministic Bernoulli: True with probability ``rate``."""
        with self._lock:
            self.calls += 1
            hit = rate > 0 and self._rng.random() < rate
            if hit:
                self.fired += 1
            return hit

    def uniform(self, lo: float, hi: float) -> float:
        with self._lock:
            self.calls += 1
            return self._rng.uniform(lo, hi)


class ChaosPlan:
    """Seeded fault plan: a factory of named :class:`ChaosChannel`
    streams plus the injected-fault ledger the drill report commits
    (``counts()``)."""

    def __init__(self, seed: Any = 0):
        self.seed = seed
        self._channels: Dict[str, ChaosChannel] = {}
        self._lock = threading.Lock()

    def channel(self, name: str) -> ChaosChannel:
        with self._lock:
            ch = self._channels.get(name)
            if ch is None:
                ch = self._channels[name] = ChaosChannel(self.seed, name)
            return ch

    def counts(self) -> Dict[str, Dict[str, int]]:
        """Per-channel ``{calls, fired}`` — the injection ledger."""
        with self._lock:
            chans = list(self._channels.values())
        return {c.name: {"calls": c.calls, "fired": c.fired}
                for c in chans}


class ChaosPredictor:
    """Wrap a scoring callable with deterministic failure injection.

    * ``exc_rate`` — per-call probability of an ordinary
      ``RuntimeError`` (the engine treats it as a batch failure and
      salvages per row).
    * ``kill_on_calls`` — exact call indices (1-based) that raise
      :class:`WorkerKilled` instead of scoring — simulates the worker
      thread dying mid-batch (the supervision path).  Call indices
      count every invocation, including the engine's per-row salvage
      retries.

    The wrapper forwards ``mode`` when the inner predictor has one, so
    the engine's pad-buckets auto-detection behaves identically.
    """

    def __init__(self, predictor: Callable, plan: ChaosPlan, *,
                 exc_rate: float = 0.0,
                 kill_on_calls: Iterable[int] = (),
                 name: str = "predictor"):
        self._inner = predictor
        self._exc_rate = float(exc_rate)
        self._kill_on = frozenset(int(k) for k in kill_on_calls)
        self._chan = plan.channel(name)
        self._lock = threading.Lock()
        self.calls = 0
        self.kills = 0
        self.excs = 0
        if hasattr(predictor, "mode"):
            self.mode = predictor.mode

    def __call__(self, X):
        with self._lock:
            self.calls += 1
            n = self.calls
        if n in self._kill_on:
            with self._lock:
                self.kills += 1
            raise WorkerKilled(f"chaos: worker kill at call {n}")
        if self._chan.fire(self._exc_rate):
            with self._lock:
                self.excs += 1
            raise RuntimeError(f"chaos: injected predictor fault "
                               f"(call {n})")
        return self._inner(X)


class ChaosQueue:
    """Wrap a ``queue.Queue`` with deterministic ``get`` stalls (a
    wedged intake / slow upstream).  Puts pass through untouched so no
    request is ever lost — chaos degrades, it must not drop."""

    def __init__(self, inner: "queue.Queue", plan: ChaosPlan, *,
                 stall_rate: float = 0.0, stall_s: float = 0.05,
                 name: str = "queue"):
        self._inner = inner
        self._stall_rate = float(stall_rate)
        self._stall_s = float(stall_s)
        self._chan = plan.channel(name)

    def _maybe_stall(self):
        if self._chan.fire(self._stall_rate):
            time.sleep(self._stall_s)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        self._maybe_stall()
        return self._inner.get(block, timeout)

    def get_nowait(self):
        self._maybe_stall()
        return self._inner.get_nowait()

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        return self._inner.put(item, block, timeout)

    def put_nowait(self, item):
        return self._inner.put_nowait(item)

    def qsize(self) -> int:
        return self._inner.qsize()

    def empty(self) -> bool:
        return self._inner.empty()


class ChaosSocket:
    """Wrap a CONNECTED socket with deterministic network faults:

    * ``reset_rate`` — before a send: hard connection reset (``SO_LINGER
      0`` close emits an RST; the caller sees ``ConnectionResetError``).
    * ``partial_rate`` — before a send: transmit roughly half the bytes,
      then reset — the truncated-request case a server's read path must
      survive.
    * ``slow_rate``/``slow_s`` — before a send or recv: stall — the
      slow-loris case the server's read deadlines must bound.

    Everything else delegates to the wrapped socket.  ``makefile`` is
    delegated raw (buffered readers bypass injection); inject on the
    side that calls ``sendall``/``recv``.
    """

    def __init__(self, sock, plan: ChaosPlan, *,
                 reset_rate: float = 0.0, partial_rate: float = 0.0,
                 slow_rate: float = 0.0, slow_s: float = 0.05,
                 name: str = "socket"):
        self._sock = sock
        self._reset_rate = float(reset_rate)
        self._partial_rate = float(partial_rate)
        self._slow_rate = float(slow_rate)
        self._slow_s = float(slow_s)
        self._chan = plan.channel(name)
        self.resets = 0

    def _reset(self):
        import socket as _socket
        self.resets += 1
        try:
            # linger(on, 0): close() drops the connection with an RST
            # instead of an orderly FIN — the "client yanked the cable"
            # failure servers must shrug off
            self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                                  struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        raise ConnectionResetError("chaos: injected connection reset")

    def sendall(self, data: bytes):
        if self._chan.fire(self._reset_rate):
            self._reset()
        if self._chan.fire(self._partial_rate):
            self._sock.sendall(data[:max(1, len(data) // 2)])
            self._reset()
        if self._chan.fire(self._slow_rate):
            time.sleep(self._slow_s)
        return self._sock.sendall(data)

    def recv(self, bufsize: int, *flags):
        if self._chan.fire(self._slow_rate):
            time.sleep(self._slow_s)
        return self._sock.recv(bufsize, *flags)

    def __getattr__(self, attr):
        return getattr(self._sock, attr)


class ChaosTransport:
    """Frame-aware fault injection for :mod:`mmlspark_tpu.io.transport`
    links — plug an instance factory into ``TransportConfig.socket_wrap``
    (one wrapper per accepted/dialed socket) so the chaos drills
    exercise the transport ITSELF, not just the app on top of it.

    The transport writes exactly one frame per ``sendall``, which is
    what makes frame-level injection possible from a socket wrapper:

    * ``bitflip_rate`` — flip one byte at a deterministic offset past
      the length prefix; the frame-wide CRC32C must catch it, the
      receiver kills the poisoned link, and the session resume must
      replay with zero loss and zero duplication.
    * ``ack_drop_rate`` — silently swallow outbound ACK frames, so the
      peer's replay buffer stays fat and a later resume replays frames
      the receiver already delivered — the sequence-dedup path.
    * ``kill_on_sends`` — exact send indices (1-based) that transmit
      roughly HALF the frame and then hard-reset (``SO_LINGER 0`` →
      RST): the seeded mid-frame link kill the resume contract is
      verified against.
    * ``reset_rate`` — per-send Bernoulli version of the same reset.
    * ``half_open_after`` — after N sends this side goes silent
      WITHOUT closing: writes are swallowed (reads still flow), which
      is exactly what a peer's keepalive timeout must detect as a
      half-open link.

    Counters: ``bitflips`` / ``ack_drops`` / ``resets`` /
    ``blackholed``.  Everything else delegates to the wrapped socket.
    """

    #: byte offset of the frame-type field (after the u32 length)
    _TYPE_OFF = 4

    def __init__(self, sock, plan: ChaosPlan, *,
                 bitflip_rate: float = 0.0, ack_drop_rate: float = 0.0,
                 reset_rate: float = 0.0,
                 kill_on_sends: Iterable[int] = (),
                 half_open_after: int = 0,
                 name: str = "transport"):
        self._sock = sock
        self._bitflip_rate = float(bitflip_rate)
        self._ack_drop_rate = float(ack_drop_rate)
        self._reset_rate = float(reset_rate)
        self._kill_on = frozenset(int(k) for k in kill_on_sends)
        self._half_open_after = int(half_open_after)
        self._chan = plan.channel(name)
        self._lock = threading.Lock()
        self.sends = 0
        self.bitflips = 0
        self.ack_drops = 0
        self.resets = 0
        self.blackholed = 0

    def _reset(self):
        import socket as _socket
        self.resets += 1
        try:
            self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                                  struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        raise ConnectionResetError("chaos: injected transport reset")

    def sendall(self, data: bytes):
        with self._lock:
            self.sends += 1
            n = self.sends
        if self._half_open_after and n > self._half_open_after:
            # half-open: swallow silently, keep the socket "alive"
            self.blackholed += 1
            return None
        if n in self._kill_on:
            # mid-frame kill: the peer reads a torn frame, then RST
            try:
                self._sock.sendall(data[:max(1, len(data) // 2)])
            except OSError:
                pass
            self._reset()
        if self._chan.fire(self._reset_rate):
            self._reset()
        if (self._ack_drop_rate > 0 and len(data) > self._TYPE_OFF
                and data[self._TYPE_OFF] == _T_ACK
                and self._chan.fire(self._ack_drop_rate)):
            self.ack_drops += 1
            return None
        if self._chan.fire(self._bitflip_rate) and len(data) > 5:
            off = int(self._chan.uniform(self._TYPE_OFF,
                                         len(data) - 1))
            off = min(max(off, self._TYPE_OFF), len(data) - 1)
            self.bitflips += 1
            data = (data[:off] + bytes([data[off] ^ 0x40])
                    + data[off + 1:])
        return self._sock.sendall(data)

    def recv(self, bufsize: int, *flags):
        if self._half_open_after and self.sends > self._half_open_after:
            # the silent side also stops answering reads it would have
            # served — but must NOT close (that would be a clean FIN,
            # not a half-open link)
            time.sleep(0.05)
        return self._sock.recv(bufsize, *flags)

    def __getattr__(self, attr):
        return getattr(self._sock, attr)


class ChaosDrift:
    """Seeded mid-traffic data-drift injector (ISSUE 15): perturb ONE
    feature column of the request stream once a configured number of
    rows has flowed — the upstream-pipeline-change / sensor-failure
    event the drift monitor must detect.

    Wrap the drill's payload generator (or a feature matrix producer):
    ``drift(X)`` returns ``X`` untouched for the first ``after_rows``
    rows of cumulative traffic, then applies, to rows past that
    boundary (the cut can land mid-batch):

    * ``scale``/``shift`` — ``x → x * scale + shift`` (a recalibrated
      or re-unit'd upstream feature);
    * ``nan_rate`` — per-row Bernoulli NaN injection drawn from the
      plan's channel (the "feature went silently null" storm).

    ``ramp_rows > 0`` selects ramp mode (ISSUE 18): instead of a step
    change at the cut, the injected shift/scale interpolate linearly
    from no-op to full strength over the ``ramp_rows`` rows following
    ``after_rows`` — the slow upstream-degradation shape that must
    still cross the burn threshold.  The per-row ramp fraction is a
    pure function of the global row index, so the injected stream is
    identical regardless of batch boundaries.

    Deterministic like every injector: the NaN decision sequence is a
    pure function of ``(seed, name)`` and the row index.  Counters:
    ``rows_seen`` / ``rows_injected`` / ``nans_injected`` — the drill's
    injection ledger.  The input is never mutated in place (clients
    may reuse their row buffers)."""

    def __init__(self, plan: ChaosPlan, *, feature: int,
                 shift: float = 0.0, scale: float = 1.0,
                 nan_rate: float = 0.0, after_rows: int = 0,
                 ramp_rows: int = 0, name: str = "drift"):
        self.feature = int(feature)
        self.shift = float(shift)
        self.scale = float(scale)
        self.nan_rate = float(nan_rate)
        self.after_rows = int(after_rows)
        self.ramp_rows = int(ramp_rows)
        self._chan = plan.channel(name)
        self._lock = threading.Lock()
        self.rows_seen = 0
        self.rows_injected = 0
        self.nans_injected = 0

    def __call__(self, X):
        import numpy as np
        X = np.asarray(X)
        squeeze = X.ndim == 1
        if squeeze:
            X = X[None, :]
        n = X.shape[0]
        with self._lock:
            start = self.rows_seen
            self.rows_seen += n
        k0 = max(0, self.after_rows - start)
        if k0 >= n:
            return X[0] if squeeze else X
        X = X.astype(np.float32, copy=True)
        if self.ramp_rows > 0:
            # ramp fraction per global row index past the cut: row
            # ``after_rows + j`` carries (j+1)/ramp_rows of the full
            # perturbation, saturating at 1 — batch-boundary invariant
            j = np.arange(start + k0, start + n) - self.after_rows
            frac = np.minimum((j + 1) / self.ramp_rows, 1.0).astype(
                np.float32)
            eff_scale = 1.0 + (self.scale - 1.0) * frac
            eff_shift = self.shift * frac
            col = X[k0:, self.feature] * eff_scale + eff_shift
        else:
            col = X[k0:, self.feature] * self.scale + self.shift
        if self.nan_rate > 0:
            mask = np.fromiter(
                (self._chan.fire(self.nan_rate)
                 for _ in range(n - k0)), bool, count=n - k0)
            col[mask] = np.nan
            with self._lock:
                self.nans_injected += int(mask.sum())
        X[k0:, self.feature] = col
        with self._lock:
            self.rows_injected += n - k0
        return X[0] if squeeze else X


def kill_process(proc_or_pid) -> int:
    """SIGKILL a worker process (accepts a ``multiprocessing.Process``
    or a raw pid) — the drill's executor-loss injection.  Returns the
    pid killed."""
    pid = getattr(proc_or_pid, "pid", proc_or_pid)
    os.kill(int(pid), signal.SIGKILL)
    return int(pid)


class ChaosBoostStep:
    """Wrap a training chunk-step callable with deterministic failures.

    * ``fail_on_calls`` — exact call indices (1-based, counting every
      invocation INCLUDING replays) that raise ``RuntimeError`` instead
      of running — the "device/tunnel loss at chunk k" injection the
      engine's ``faultTolerantRetries`` replay must absorb.
    * ``exc_rate`` — per-call Bernoulli failure, drawn from the plan's
      channel (thread-interleaving deterministic, like every injector).

    The failure is an ordinary ``RuntimeError`` (the engine replays it)
    — deterministic sanitizer errors (checkify) are deliberately NOT
    simulated here because the engine must re-raise those unreplayed.
    """

    def __init__(self, step: Callable, plan: ChaosPlan, *,
                 exc_rate: float = 0.0,
                 fail_on_calls: Iterable[int] = (),
                 name: str = "boost_step"):
        self._inner = step
        self._exc_rate = float(exc_rate)
        self._fail_on = frozenset(int(k) for k in fail_on_calls)
        self._chan = plan.channel(name)
        self._lock = threading.Lock()
        self.calls = 0
        self.failures = 0

    def __call__(self, *args, **kwargs):
        with self._lock:
            self.calls += 1
            n = self.calls
        if n in self._fail_on or self._chan.fire(self._exc_rate):
            with self._lock:
                self.failures += 1
            raise RuntimeError(
                f"chaos: injected chunk-step failure (call {n})")
        return self._inner(*args, **kwargs)


def corrupt_file(path: str, plan: Optional[ChaosPlan] = None, *,
                 mode: str = "bitflip", name: str = "ckpt") -> str:
    """Corrupt a snapshot file in place — the torn-write / bit-rot
    injection for checkpoint recovery drills.

    * ``mode="torn"`` — truncate to half its length: the partial write
      a power cut leaves behind when the writer skipped the
      atomic-rename discipline.
    * ``mode="bitflip"`` — flip one byte at a deterministic offset
      (drawn from the plan's channel; the file midpoint without a
      plan): silent media corruption an npz CRC must catch.

    Returns ``path``.  The engine's load paths must treat the result as
    absent — degrade to a fresh fit, never a crash, never garbage.
    """
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path}")
    if mode == "torn":
        with open(path, "r+b") as fh:
            fh.truncate(max(1, size // 2))
        return path
    if mode == "bitflip":
        if plan is not None:
            off = int(plan.channel(name).uniform(0, max(0, size - 1)))
        else:
            off = size // 2
        with open(path, "r+b") as fh:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0xFF]))
        return path
    raise ValueError(f"unknown corruption mode {mode!r} "
                     "(use 'torn' or 'bitflip')")


def read_ckpt_boundary(ckpt_dir: str) -> Optional[int]:
    """The boundary iteration named by the durable checkpoint meta in
    ``ckpt_dir`` (None when absent or mid-replace).  The ONE reader the
    training chaos tools poll with — the meta file is replaced
    atomically, so a read never sees a torn write — closing the npz
    each cycle (a lingering NpzFile leaks one fd per poll)."""
    import json as _json

    import numpy as np

    # lazy import: the meta filename lives with the writer; a rename
    # there must not leave this poller watching a path that never
    # appears (io stays import-decoupled from gbdt at module load)
    from ..gbdt.engine import _CKPT_FILE
    meta = os.path.join(ckpt_dir, _CKPT_FILE)
    try:
        with np.load(meta) as z:
            return int(_json.loads(
                bytes(z["__meta__"]).decode("utf-8"))["it"])
    except Exception:  # noqa: BLE001 - absent / replace race
        return None


class ChaosControllerKill(threading.Thread):
    """SIGKILL the CURRENT process the moment a checkpoint boundary
    ``>= at_boundary`` becomes durable in ``ckpt_dir`` — the drill's
    "controller dies mid-fit" injection, timed off the checkpoint meta
    itself so the death deterministically lands between chunk
    boundaries (an outside killer racing the fit can miss the window
    entirely on a fast fit).

    SIGKILL runs no cleanup — no atexit, no finally, no flush — which
    is exactly the failure the recovery contract must absorb."""

    def __init__(self, ckpt_dir: str, at_boundary: int, *,
                 poll_s: float = 0.03):
        super().__init__(daemon=True, name="chaos-controller-kill")
        self._ckpt_dir = ckpt_dir
        self._at = int(at_boundary)
        self._poll_s = float(poll_s)

    def run(self) -> None:
        while True:
            it = read_ckpt_boundary(self._ckpt_dir)
            if it is not None and it >= self._at:
                kill_process(os.getpid())
            time.sleep(self._poll_s)


class ChaosHeartbeat:
    """Heartbeat stall injector: a ``write_hook`` for
    :class:`~mmlspark_tpu.gbdt.elastic.HeartbeatWatchdog` that delays
    lease-file touches so PEERS observe a stale heartbeat.

    Two modes, composable:

    * ``after_s``/``stall_s`` — ONE deterministic stall of ``stall_s``
      seconds once ``after_s`` have elapsed since the first tick (the
      drill's "shard goes quiet mid-fit" event; choose ``stall_s``
      between the peer's straggler threshold and its lease timeout to
      exercise straggler accounting without triggering a gang
      restart).
    * ``rate``/``rate_stall_s`` — per-tick Bernoulli stalls drawn from
      the plan's channel (sustained jitter).
    """

    def __init__(self, plan: Optional[ChaosPlan] = None, *,
                 after_s: float = 0.0, stall_s: float = 0.0,
                 rate: float = 0.0, rate_stall_s: float = 0.05,
                 name: str = "heartbeat"):
        self._after_s = float(after_s)
        self._stall_s = float(stall_s)
        self._rate = float(rate)
        self._rate_stall_s = float(rate_stall_s)
        if rate > 0 and plan is None:
            # a silently disabled injector would let a drill go green
            # having injected nothing
            raise ValueError("ChaosHeartbeat with rate > 0 needs a "
                             "ChaosPlan to draw from")
        self._chan = plan.channel(name) if rate > 0 else None
        self._t0: Optional[float] = None
        self._fired = False
        self.stalls = 0

    def __call__(self) -> None:
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now
        if (self._stall_s > 0 and not self._fired
                and now - self._t0 >= self._after_s):
            self._fired = True
            self.stalls += 1
            time.sleep(self._stall_s)
            return
        if self._chan is not None and self._chan.fire(self._rate):
            self.stalls += 1
            time.sleep(self._rate_stall_s)
