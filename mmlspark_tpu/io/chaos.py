"""Deterministic fault injection for the serving stack (chaos harness).

The training side has had a fault harness since the seed (chunk replay in
``gbdt/engine.py`` + ``tests/test_fault_tolerance.py``); this module is
the serving-side equivalent: seeded, deterministic injectors that wrap
the pieces of the serving pipeline so tests and the
``tools/chaos_serving.py`` drill can prove the resilience layer's
contract — *zero wrong answers, every non-delivered request gets an
explicit reply, ready again when the faults stop* — instead of asserting
it rhetorically.

Determinism model: every injector draws its decisions from a
:class:`ChaosChannel`, an independently seeded RNG stream keyed by
``(seed, channel name)``.  Channels are independent, so thread
interleaving across subsystems (a socket injector racing a predictor
injector) never changes any single subsystem's decision sequence — the
k-th send on a given socket channel fires or not regardless of what the
predictor did.  Within one channel the sequence is a pure function of
the seed and the call index.

Injectors:

* :class:`ChaosPredictor` — wraps a scoring callable; injects batch
  exceptions (ordinary ``RuntimeError`` → the engine's per-row salvage
  path) and worker kills (:class:`~mmlspark_tpu.io.scoring.WorkerKilled`,
  a ``BaseException`` → the engine's supervision/restart path) at
  deterministic call indices or rates.
* :class:`ChaosQueue` — wraps a ``queue.Queue``; stalls ``get`` calls to
  simulate a wedged intake.
* :class:`ChaosSocket` — wraps a connected socket; injects connection
  resets (RST via ``SO_LINGER 0``), partial writes, and slow reads and
  writes — drive it from a client to exercise the server's slow-client
  deadlines and reset handling.
* :func:`kill_process` — SIGKILL a worker process (the multiprocess
  drill's executor-loss injection).
"""

from __future__ import annotations

import os
import queue
import random
import signal
import struct
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from .scoring import WorkerKilled

__all__ = [
    "ChaosChannel", "ChaosPlan", "ChaosPredictor", "ChaosQueue",
    "ChaosSocket", "WorkerKilled", "kill_process",
]


class ChaosChannel:
    """One independently seeded decision stream.

    ``fire(rate)`` is the k-th Bernoulli draw of this channel — the
    sequence depends only on ``(seed, name)`` and the call index, never
    on other channels or thread timing elsewhere.
    """

    def __init__(self, seed: Any, name: str):
        self.name = name
        self._rng = random.Random(f"{seed}:{name}")
        self._lock = threading.Lock()
        self.calls = 0
        self.fired = 0

    def fire(self, rate: float) -> bool:
        """Deterministic Bernoulli: True with probability ``rate``."""
        with self._lock:
            self.calls += 1
            hit = rate > 0 and self._rng.random() < rate
            if hit:
                self.fired += 1
            return hit

    def uniform(self, lo: float, hi: float) -> float:
        with self._lock:
            self.calls += 1
            return self._rng.uniform(lo, hi)


class ChaosPlan:
    """Seeded fault plan: a factory of named :class:`ChaosChannel`
    streams plus the injected-fault ledger the drill report commits
    (``counts()``)."""

    def __init__(self, seed: Any = 0):
        self.seed = seed
        self._channels: Dict[str, ChaosChannel] = {}
        self._lock = threading.Lock()

    def channel(self, name: str) -> ChaosChannel:
        with self._lock:
            ch = self._channels.get(name)
            if ch is None:
                ch = self._channels[name] = ChaosChannel(self.seed, name)
            return ch

    def counts(self) -> Dict[str, Dict[str, int]]:
        """Per-channel ``{calls, fired}`` — the injection ledger."""
        with self._lock:
            chans = list(self._channels.values())
        return {c.name: {"calls": c.calls, "fired": c.fired}
                for c in chans}


class ChaosPredictor:
    """Wrap a scoring callable with deterministic failure injection.

    * ``exc_rate`` — per-call probability of an ordinary
      ``RuntimeError`` (the engine treats it as a batch failure and
      salvages per row).
    * ``kill_on_calls`` — exact call indices (1-based) that raise
      :class:`WorkerKilled` instead of scoring — simulates the worker
      thread dying mid-batch (the supervision path).  Call indices
      count every invocation, including the engine's per-row salvage
      retries.

    The wrapper forwards ``mode`` when the inner predictor has one, so
    the engine's pad-buckets auto-detection behaves identically.
    """

    def __init__(self, predictor: Callable, plan: ChaosPlan, *,
                 exc_rate: float = 0.0,
                 kill_on_calls: Iterable[int] = (),
                 name: str = "predictor"):
        self._inner = predictor
        self._exc_rate = float(exc_rate)
        self._kill_on = frozenset(int(k) for k in kill_on_calls)
        self._chan = plan.channel(name)
        self._lock = threading.Lock()
        self.calls = 0
        self.kills = 0
        self.excs = 0
        if hasattr(predictor, "mode"):
            self.mode = predictor.mode

    def __call__(self, X):
        with self._lock:
            self.calls += 1
            n = self.calls
        if n in self._kill_on:
            with self._lock:
                self.kills += 1
            raise WorkerKilled(f"chaos: worker kill at call {n}")
        if self._chan.fire(self._exc_rate):
            with self._lock:
                self.excs += 1
            raise RuntimeError(f"chaos: injected predictor fault "
                               f"(call {n})")
        return self._inner(X)


class ChaosQueue:
    """Wrap a ``queue.Queue`` with deterministic ``get`` stalls (a
    wedged intake / slow upstream).  Puts pass through untouched so no
    request is ever lost — chaos degrades, it must not drop."""

    def __init__(self, inner: "queue.Queue", plan: ChaosPlan, *,
                 stall_rate: float = 0.0, stall_s: float = 0.05,
                 name: str = "queue"):
        self._inner = inner
        self._stall_rate = float(stall_rate)
        self._stall_s = float(stall_s)
        self._chan = plan.channel(name)

    def _maybe_stall(self):
        if self._chan.fire(self._stall_rate):
            time.sleep(self._stall_s)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        self._maybe_stall()
        return self._inner.get(block, timeout)

    def get_nowait(self):
        self._maybe_stall()
        return self._inner.get_nowait()

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        return self._inner.put(item, block, timeout)

    def put_nowait(self, item):
        return self._inner.put_nowait(item)

    def qsize(self) -> int:
        return self._inner.qsize()

    def empty(self) -> bool:
        return self._inner.empty()


class ChaosSocket:
    """Wrap a CONNECTED socket with deterministic network faults:

    * ``reset_rate`` — before a send: hard connection reset (``SO_LINGER
      0`` close emits an RST; the caller sees ``ConnectionResetError``).
    * ``partial_rate`` — before a send: transmit roughly half the bytes,
      then reset — the truncated-request case a server's read path must
      survive.
    * ``slow_rate``/``slow_s`` — before a send or recv: stall — the
      slow-loris case the server's read deadlines must bound.

    Everything else delegates to the wrapped socket.  ``makefile`` is
    delegated raw (buffered readers bypass injection); inject on the
    side that calls ``sendall``/``recv``.
    """

    def __init__(self, sock, plan: ChaosPlan, *,
                 reset_rate: float = 0.0, partial_rate: float = 0.0,
                 slow_rate: float = 0.0, slow_s: float = 0.05,
                 name: str = "socket"):
        self._sock = sock
        self._reset_rate = float(reset_rate)
        self._partial_rate = float(partial_rate)
        self._slow_rate = float(slow_rate)
        self._slow_s = float(slow_s)
        self._chan = plan.channel(name)
        self.resets = 0

    def _reset(self):
        import socket as _socket
        self.resets += 1
        try:
            # linger(on, 0): close() drops the connection with an RST
            # instead of an orderly FIN — the "client yanked the cable"
            # failure servers must shrug off
            self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                                  struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        raise ConnectionResetError("chaos: injected connection reset")

    def sendall(self, data: bytes):
        if self._chan.fire(self._reset_rate):
            self._reset()
        if self._chan.fire(self._partial_rate):
            self._sock.sendall(data[:max(1, len(data) // 2)])
            self._reset()
        if self._chan.fire(self._slow_rate):
            time.sleep(self._slow_s)
        return self._sock.sendall(data)

    def recv(self, bufsize: int, *flags):
        if self._chan.fire(self._slow_rate):
            time.sleep(self._slow_s)
        return self._sock.recv(bufsize, *flags)

    def __getattr__(self, attr):
        return getattr(self._sock, attr)


def kill_process(proc_or_pid) -> int:
    """SIGKILL a worker process (accepts a ``multiprocessing.Process``
    or a raw pid) — the drill's executor-loss injection.  Returns the
    pid killed."""
    pid = getattr(proc_or_pid, "pid", proc_or_pid)
    os.kill(int(pid), signal.SIGKILL)
    return int(pid)
