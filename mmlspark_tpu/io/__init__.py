"""Data plane / IO (reference ``io/`` package).

Reference: src/main/scala/com/microsoft/ml/spark/io/ (expected paths,
UNVERIFIED — SURVEY.md §2.1, §3.4): HTTP-on-Spark, Spark Serving, binary
file datasource, PowerBI writer.
"""

from .http import (
    HTTPTransformer,
    PartitionConsolidator,
    JSONInputParser,
    JSONOutputParser,
    SimpleHTTPTransformer,
)
from .serving import (DistributedHTTPServer, HTTPServer,
                      MultiprocessHTTPServer, join_exchange,
                      request_table, reply_from_table, serve_forever)
from .scoring import ColumnPlan, ScoringEngine, WorkerKilled
from .chaos import (ChaosChannel, ChaosPlan, ChaosPredictor, ChaosQueue,
                    ChaosSocket, ChaosTransport, kill_process)
from .transport import (Backpressure, ChecksumError, FrameTooLarge,
                        HandshakeError, TransportClient, TransportConfig,
                        TransportError, TransportServer, parse_address)
from .wire import BinaryReq, WireError
from .fleet import (ConsistentHashRing, PredictorFleet,
                    ShardedPredictor, shard_tree_ranges)
from .registry import ModelCorruption, ModelRegistry, RegistryError
from .rollout import RolloutConfig, RolloutController
from .ingest import IngestBuffer, IngestError
from .refresh import RefreshConfig, RefreshController, RefreshError
from .binary import BinaryFileReader, read_binary_files
from .powerbi import PowerBIWriter

__all__ = [
    "HTTPTransformer", "PartitionConsolidator",
    "SimpleHTTPTransformer",
    "JSONInputParser", "JSONOutputParser",
    "HTTPServer", "DistributedHTTPServer", "MultiprocessHTTPServer",
    "join_exchange", "request_table", "reply_from_table",
    "serve_forever", "ColumnPlan", "ScoringEngine", "WorkerKilled",
    "ChaosChannel", "ChaosPlan", "ChaosPredictor", "ChaosQueue",
    "ChaosSocket", "ChaosTransport", "kill_process",
    "Backpressure", "ChecksumError", "FrameTooLarge", "HandshakeError",
    "TransportClient", "TransportConfig", "TransportError",
    "TransportServer", "parse_address",
    "BinaryReq", "WireError",
    "ConsistentHashRing", "PredictorFleet", "ShardedPredictor",
    "shard_tree_ranges",
    "ModelCorruption", "ModelRegistry", "RegistryError",
    "RolloutConfig", "RolloutController",
    "IngestBuffer", "IngestError",
    "RefreshConfig", "RefreshController", "RefreshError",
    "BinaryFileReader", "read_binary_files",
    "PowerBIWriter",
]
