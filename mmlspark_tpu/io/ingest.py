"""Streaming ingest — the training-side feed of the online-learning
loop (ISSUE 18).

The refresh pipeline (``io/refresh.py``) can only retrain on data it
still *has* when drift fires, and it must still have that data after a
SIGKILL.  This module is the durable buffer between live traffic and
the incremental fit:

* **Bin-at-append** — every micro-batched ``(X, y)`` append is binned
  immediately to the ACTIVE model's uint8
  :class:`~mmlspark_tpu.gbdt.binning.BinMapper` ladder.  Raw float32
  rows never accumulate: retained rows cost 1 byte/feature, and —
  because tree thresholds sit exactly on bin upper bounds — the binned
  rows are *sufficient statistics* for continued training
  (:func:`mmlspark_tpu.gbdt.engine.train_incremental` reconstructs the
  active model's margins bit-exactly from bin representatives).
* **Window + reservoir retention** — the buffer holds the last
  ``window_rows`` rows exactly (recency) plus a uniform reservoir
  sample of every row ever evicted from the window (history), so a
  refresh fit sees both the drifted present and the long tail.  Every
  row is retained at most once: first in the window, then either it
  enters the reservoir or it is dropped forever.  Reservoir decisions
  are counter-keyed hashes of ``(seed, evicted_index)`` — a pure
  function of the row's position in the stream, independent of batch
  boundaries and of process restarts.
* **Crash-safe segment spill** — appended rows accumulate in a tail
  and spill to ``seg_NNNNNNNN.npz`` files in exact ``segment_rows``
  slices, written tmp + fsync + atomic-rename (the PR-4/PR-14
  checkpoint discipline).  The in-memory window/reservoir state is
  maintained ONLY over spilled rows, so the durable state is always
  exactly "replay of the segment files": reopening the directory after
  a SIGKILL reproduces the window, the reservoir and every counter
  bit-identically as of the last durable segment (unspilled tail rows
  are the only loss, by contract).  ``compact()`` folds replayed
  segments into one ``state_NNNNNNNN.npz`` snapshot (same atomic
  discipline, snapshot durable before segment unlink) so disk stays
  bounded without ever widening the crash window.

``training_view()`` is the fit input: reservoir + the last
``window_rows`` of (spilled + tail) rows, oldest first.

Telemetry: the buffer federates a StageStats block under
``ns="ingest"`` and renders the ``mmlspark_tpu_ingest_*`` families
(docs/observability.md) into the process scrape.
"""

from __future__ import annotations

import io as _io
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.profiling import StageStats
from ..core.telemetry import PREFIX, _fmt, _labels, get_journal, \
    get_registry
from ..gbdt.binning import BinMapper
from .registry import _atomic_write, _fsync_dir, sha256_hex

log = logging.getLogger(__name__)

__all__ = ["IngestBuffer", "IngestError"]

_META = "meta.json"
_MAPPER = "mapper.json"
_SEG_FMT = "seg_%08d.npz"
_STATE_FMT = "state_%08d.npz"
_FORMAT = 1

INGEST_NS = "ingest"


class IngestError(RuntimeError):
    """Ingest contract violation (shape mismatch, incompatible
    directory, torn configuration)."""


def _hash_u64(seed: int, t: np.ndarray) -> np.ndarray:
    """Counter-keyed 64-bit hash (splitmix64 finalizer over
    ``seed ^ t``): deterministic, platform-independent, vectorized —
    the reservoir's per-row randomness.  uint64 arithmetic wraps
    silently in numpy, which is exactly the mixing we want."""
    x = (np.asarray(t, np.uint64) + np.uint64(0x9E3779B97F4A7C15)) \
        ^ np.uint64(seed & 0xFFFFFFFFFFFFFFFF)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _savez_atomic(path: str, **arrays) -> None:
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    _atomic_write(path, buf.getvalue())


class IngestBuffer:
    """Durable streaming buffer of binned training rows.

    ``root`` is the spill directory.  A fresh directory needs
    ``mapper`` (the active model's bin ladder, persisted alongside);
    reopening an existing one replays its durable state and verifies
    any ``mapper`` passed matches the persisted ladder bit-exactly —
    segments binned under one ladder must never be extended under
    another.
    """

    def __init__(self, root: str, mapper: Optional[BinMapper] = None, *,
                 window_rows: int = 4096, reservoir_rows: int = 2048,
                 segment_rows: int = 512, seed: int = 0,
                 max_segments: int = 64,
                 stats: Optional[StageStats] = None,
                 register: bool = True):
        if segment_rows <= 0 or window_rows <= 0 or reservoir_rows < 0:
            raise IngestError(
                "window_rows/segment_rows must be positive and "
                "reservoir_rows non-negative")
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.stats = stats or StageStats()
        self._lock = threading.RLock()
        self._journal = get_journal()
        existing = os.path.exists(os.path.join(self.root, _META))
        if existing:
            self._load_meta(mapper)
        else:
            if mapper is None:
                raise IngestError(
                    f"fresh ingest dir {self.root} needs a BinMapper "
                    "(the active model's ladder)")
            self.mapper = mapper
            self.window_rows = int(window_rows)
            self.reservoir_rows = int(reservoir_rows)
            self.segment_rows = int(segment_rows)
            self.seed = int(seed)
            self._write_meta()
        self.max_segments = int(max_segments)
        f = self.mapper.num_features
        if self.mapper.num_total_bins > 256:
            raise IngestError(
                "ingest retains uint8 bins; mapper has "
                f"{self.mapper.num_total_bins} total bins (> 256)")
        # durable state: maintained ONLY over spilled rows
        self._win: List[Tuple[np.ndarray, np.ndarray]] = []
        self._win_rows = 0
        self._res_bins = np.zeros((self.reservoir_rows, f), np.uint8)
        self._res_labels = np.zeros(self.reservoir_rows, np.float64)
        self._res_filled = 0
        self._evicted = 0
        self._rows_durable = 0
        # volatile tail: appended, not yet spilled (lost on SIGKILL)
        self._tail: List[Tuple[np.ndarray, np.ndarray]] = []
        self._tail_rows = 0
        self._seg_next = 0
        for k in ("rows", "batches", "segments_spilled",
                  "segments_replayed", "rows_dropped", "compactions",
                  "spilled_bytes"):
            self.stats.incr(k, 0)
        if existing:
            self._replay()
        if register:
            reg = get_registry()
            reg.register(INGEST_NS, self.stats)
            reg.register_exposition(
                INGEST_NS, self.render_prometheus)
        self._registered = register
        self._update_gauges()

    # -- config persistence --------------------------------------------------

    def _write_meta(self) -> None:
        mtext = self.mapper.to_json()
        _atomic_write(os.path.join(self.root, _MAPPER),
                      mtext.encode("utf-8"))
        meta = {"format": _FORMAT,
                "window_rows": self.window_rows,
                "reservoir_rows": self.reservoir_rows,
                "segment_rows": self.segment_rows,
                "seed": self.seed,
                "num_features": self.mapper.num_features,
                "mapper_digest": f"sha256:{sha256_hex(mtext)}"}
        _atomic_write(os.path.join(self.root, _META),
                      json.dumps(meta, indent=1,
                                 sort_keys=True).encode("utf-8"))

    def _load_meta(self, mapper: Optional[BinMapper]) -> None:
        try:
            with open(os.path.join(self.root, _META), "rb") as fh:
                meta = json.loads(fh.read().decode("utf-8"))
            with open(os.path.join(self.root, _MAPPER), "rb") as fh:
                mtext = fh.read().decode("utf-8")
        except (OSError, ValueError) as e:
            raise IngestError(
                f"unreadable ingest dir {self.root}: {e}") from e
        if meta.get("format") != _FORMAT:
            raise IngestError(
                f"ingest dir format {meta.get('format')!r} not "
                f"supported (want {_FORMAT})")
        want = meta.get("mapper_digest", "").split(":", 1)[-1]
        if sha256_hex(mtext) != want:
            raise IngestError(
                f"ingest dir {self.root}: mapper.json fails its "
                "recorded digest; refusing to replay")
        persisted = BinMapper.from_json(mtext)
        if mapper is not None and mapper.to_json() != mtext:
            raise IngestError(
                "ingest dir was binned under a different ladder than "
                "the mapper passed; refusing to mix bin spaces")
        self.mapper = persisted
        self.window_rows = int(meta["window_rows"])
        self.reservoir_rows = int(meta["reservoir_rows"])
        self.segment_rows = int(meta["segment_rows"])
        self.seed = int(meta["seed"])

    # -- durable-state machinery ---------------------------------------------

    def _push_durable(self, b: np.ndarray, y: np.ndarray) -> None:
        """Feed spilled rows, in stream order, through the window →
        reservoir machinery (also the replay path: replay IS re-push)."""
        self._win.append((b, y))
        self._win_rows += len(b)
        self._rows_durable += len(b)
        while self._win_rows > self.window_rows:
            b0, y0 = self._win[0]
            k = min(self._win_rows - self.window_rows, len(b0))
            self._evict(b0[:k], y0[:k])
            if k == len(b0):
                self._win.pop(0)
            else:
                self._win[0] = (b0[k:], y0[k:])
            self._win_rows -= k

    def _evict(self, b: np.ndarray, y: np.ndarray) -> None:
        m = len(b)
        if m == 0:
            return
        R = self.reservoir_rows
        if R == 0:
            self.stats.incr("rows_dropped", m)
            return
        off = 0
        fill = min(R - self._res_filled, m)
        if fill > 0:
            s = self._res_filled
            self._res_bins[s:s + fill] = b[:fill]
            self._res_labels[s:s + fill] = y[:fill]
            self._res_filled += fill
            off = fill
        self._evicted += fill
        if off >= m:
            return
        # Algorithm R with per-step independent counter-keyed
        # randomness: evicted row t is accepted w.p. R/(t+1) into a
        # uniform slot.  Repeated-index fancy assignment keeps the LAST
        # write per slot — identical to sequential processing.
        t = np.arange(self._evicted, self._evicted + (m - off),
                      dtype=np.uint64)
        self._evicted += m - off
        u = _hash_u64(self.seed, 2 * t).astype(np.float64) / 2.0 ** 64
        acc = u * (t.astype(np.float64) + 1.0) < float(R)
        idx = np.nonzero(acc)[0]
        if len(idx):
            slots = (_hash_u64(self.seed, 2 * t[idx] + np.uint64(1))
                     % np.uint64(R)).astype(np.int64)
            self._res_bins[slots] = b[off:][idx]
            self._res_labels[slots] = y[off:][idx]
        self.stats.incr("rows_dropped", int((~acc).sum()))

    # -- append / spill ------------------------------------------------------

    def append(self, X, y) -> int:
        """Bin and retain one micro-batch; spills full segments.
        Returns the number of rows appended."""
        with self.stats.time("append"):
            X = np.asarray(X)
            if X.ndim == 1:
                X = X[None, :]
            if X.ndim != 2 or X.shape[1] != self.mapper.num_features:
                raise IngestError(
                    f"append shape {X.shape} does not match the "
                    f"ladder's {self.mapper.num_features} features")
            yv = np.asarray(y, np.float64).reshape(-1)
            if len(yv) != X.shape[0]:
                raise IngestError(
                    f"append got {X.shape[0]} rows but {len(yv)} "
                    "labels")
            b = np.ascontiguousarray(
                self.mapper.transform_packed(X), dtype=np.uint8)
            with self._lock:
                self._tail.append((b, yv))
                self._tail_rows += len(b)
                self.stats.incr("rows", len(b))
                self.stats.incr("batches")
                self.stats.add_rows(len(b))
                while self._tail_rows >= self.segment_rows:
                    self._spill_one_locked()
                self._update_gauges()
            return int(len(b))

    def _take_tail_locked(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        bs, ys, got = [], [], 0
        while got < k:
            b0, y0 = self._tail[0]
            take = min(k - got, len(b0))
            bs.append(b0[:take])
            ys.append(y0[:take])
            if take == len(b0):
                self._tail.pop(0)
            else:
                self._tail[0] = (b0[take:], y0[take:])
            got += take
        self._tail_rows -= k
        return np.concatenate(bs), np.concatenate(ys)

    def _spill_one_locked(self, rows: Optional[int] = None) -> int:
        k = min(rows or self.segment_rows, self._tail_rows)
        b, yv = self._take_tail_locked(k)
        idx = self._seg_next
        path = os.path.join(self.root, _SEG_FMT % idx)
        _savez_atomic(path, bins=b, labels=yv,
                      first_row=np.int64(self._rows_durable),
                      seg=np.int64(idx))
        self._seg_next = idx + 1
        self._push_durable(b, yv)
        self.stats.incr("segments_spilled")
        self.stats.incr("spilled_bytes", os.path.getsize(path))
        self._journal.emit("ingest_segment", seg=idx, rows=int(len(b)),
                           durable_rows=self._rows_durable)
        if self._live_segments_locked() > self.max_segments:
            self._compact_locked()
        return idx

    def flush(self) -> int:
        """Spill any tail rows so the buffer's full contents are
        durable (the refresh controller calls this before snapshotting
        its fit dataset).  Returns the durable row count."""
        with self._lock:
            while self._tail_rows > 0:
                self._spill_one_locked(rows=self._tail_rows)
            self._update_gauges()
            return self._rows_durable

    # -- compaction ----------------------------------------------------------

    def _seg_files_locked(self) -> List[Tuple[int, str]]:
        out = []
        for fn in os.listdir(self.root):
            if fn.startswith("seg_") and fn.endswith(".npz"):
                out.append((int(fn[4:-4]), os.path.join(self.root, fn)))
        return sorted(out)

    def _state_files_locked(self) -> List[Tuple[int, str]]:
        out = []
        for fn in os.listdir(self.root):
            if fn.startswith("state_") and fn.endswith(".npz"):
                out.append((int(fn[6:-4]), os.path.join(self.root, fn)))
        return sorted(out)

    def _live_segments_locked(self) -> int:
        return len(self._seg_files_locked())

    def _compact_locked(self) -> None:
        if self._seg_next == 0:
            return
        idx = self._seg_next - 1
        wb = np.concatenate([b for b, _ in self._win]) if self._win \
            else np.zeros((0, self.mapper.num_features), np.uint8)
        wy = np.concatenate([y for _, y in self._win]) if self._win \
            else np.zeros(0, np.float64)
        path = os.path.join(self.root, _STATE_FMT % idx)
        # snapshot durable BEFORE any unlink: a crash between the two
        # leaves both snapshot and segments (replay prefers the newest
        # snapshot and ignores segments it already covers)
        _savez_atomic(path, win_bins=wb, win_labels=wy,
                      res_bins=self._res_bins[:self._res_filled],
                      res_labels=self._res_labels[:self._res_filled],
                      evicted=np.int64(self._evicted),
                      rows_durable=np.int64(self._rows_durable),
                      seg=np.int64(idx))
        for i, p in self._seg_files_locked():
            if i <= idx:
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass
        for i, p in self._state_files_locked():
            if i < idx:
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass
        _fsync_dir(self.root)
        self.stats.incr("compactions")
        self._journal.emit("ingest_compact", seg=idx,
                           durable_rows=self._rows_durable)

    def compact(self) -> None:
        """Fold all spilled segments into one snapshot file."""
        with self._lock:
            self._compact_locked()
            self._update_gauges()

    # -- replay --------------------------------------------------------------

    def _replay(self) -> None:
        with self._lock:
            states = self._state_files_locked()
            base = -1
            if states:
                base, spath = states[-1]
                with np.load(spath) as st:
                    wb = np.ascontiguousarray(st["win_bins"], np.uint8)
                    wy = np.asarray(st["win_labels"], np.float64)
                    rb = np.ascontiguousarray(st["res_bins"], np.uint8)
                    ry = np.asarray(st["res_labels"], np.float64)
                    self._evicted = int(st["evicted"])
                    self._rows_durable = int(st["rows_durable"])
                if len(wb):
                    self._win = [(wb, wy)]
                    self._win_rows = len(wb)
                self._res_filled = len(rb)
                self._res_bins[:len(rb)] = rb
                self._res_labels[:len(ry)] = ry
            replayed = 0
            last = base
            for i, p in self._seg_files_locked():
                if i <= base:
                    continue        # crash between snapshot and unlink
                if i != last + 1:
                    raise IngestError(
                        f"ingest dir {self.root}: segment {last + 1} "
                        f"missing (found {i}); refusing a gapped "
                        "replay")
                with np.load(p) as seg:
                    b = np.ascontiguousarray(seg["bins"], np.uint8)
                    yv = np.asarray(seg["labels"], np.float64)
                    first = int(seg["first_row"])
                if first != self._rows_durable:
                    raise IngestError(
                        f"ingest segment {i} starts at row {first}, "
                        f"expected {self._rows_durable}; refusing a "
                        "torn replay")
                self._push_durable(b, yv)
                replayed += 1
                last = i
            self._seg_next = last + 1
            self.stats.incr("segments_replayed", replayed)
            self.stats.incr("rows", self._rows_durable)
            if replayed or base >= 0:
                self._journal.emit(
                    "ingest_replay", segments=replayed,
                    snapshot=base if base >= 0 else None,
                    durable_rows=self._rows_durable)

    # -- views ---------------------------------------------------------------

    def training_view(self) -> Tuple[np.ndarray, np.ndarray]:
        """The fit input: reservoir sample + the last ``window_rows``
        of all appended rows (spilled + tail), oldest first.  Copies —
        safe to hand to a fit while appends continue."""
        with self._lock:
            chunks = list(self._win) + list(self._tail)
            rows = self._win_rows + self._tail_rows
            drop = max(0, rows - self.window_rows)
            out_b = [self._res_bins[:self._res_filled].copy()]
            out_y = [self._res_labels[:self._res_filled].copy()]
            for b, yv in chunks:
                if drop >= len(b):
                    drop -= len(b)
                    continue
                out_b.append(b[drop:].copy())
                out_y.append(yv[drop:].copy())
                drop = 0
            return (np.concatenate(out_b) if out_b else
                    np.zeros((0, self.mapper.num_features), np.uint8),
                    np.concatenate(out_y))

    @property
    def rows_seen(self) -> int:
        return self.stats.counter("rows")

    @property
    def rows_durable(self) -> int:
        with self._lock:
            return self._rows_durable

    @property
    def rows_retained(self) -> int:
        with self._lock:
            return (self._res_filled + self._win_rows
                    + self._tail_rows)

    def _update_gauges(self) -> None:
        self.stats.set_gauge("window_rows", self._win_rows)
        self.stats.set_gauge("reservoir_rows", self._res_filled)
        self.stats.set_gauge("tail_rows", self._tail_rows)

    def close(self) -> None:
        if self._registered:
            reg = get_registry()
            reg.unregister(INGEST_NS)
            reg.unregister_exposition(INGEST_NS)
            self._registered = False

    # -- exposition ----------------------------------------------------------

    def render_prometheus(self, prefix: str = PREFIX) -> str:
        """The ``mmlspark_tpu_ingest_*`` families
        (docs/observability.md §Metric families)."""
        with self._lock:
            self._update_gauges()
        snap = self.stats.snapshot()
        c, g = snap["counters"], snap["gauges"]
        lines: List[str] = []

        def fam(suffix: str, typ: str, help_: str) -> str:
            name = f"{prefix}_ingest_{suffix}"
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {typ}")
            return name

        n = fam("rows_total", "counter",
                "Rows appended to the streaming ingest buffer "
                "(binned at append time).")
        lines.append(f"{n} {c.get('rows', 0)}")
        n = fam("batches_total", "counter",
                "Micro-batches appended.")
        lines.append(f"{n} {c.get('batches', 0)}")
        n = fam("segments_total", "counter",
                "Durable segment spills / replays after restart / "
                "compactions, by event.")
        for ev, key in (("spilled", "segments_spilled"),
                        ("replayed", "segments_replayed"),
                        ("compacted", "compactions")):
            lines.append(f'{n}{_labels({"event": ev})} '
                         f'{c.get(key, 0)}')
        n = fam("retained_rows", "gauge",
                "Rows currently retained, by store (window = exact "
                "recency, reservoir = uniform history, tail = "
                "not-yet-durable).")
        for store in ("window", "reservoir", "tail"):
            lines.append(f'{n}{_labels({"store": store})} '
                         f'{_fmt(g.get(store + "_rows", 0))}')
        n = fam("rows_dropped_total", "counter",
                "Rows evicted from the window that the reservoir "
                "declined (gone forever, by design).")
        lines.append(f"{n} {c.get('rows_dropped', 0)}")
        n = fam("spilled_bytes_total", "counter",
                "Bytes written to durable segment files.")
        lines.append(f"{n} {c.get('spilled_bytes', 0)}")
        return "\n".join(lines) + "\n"
